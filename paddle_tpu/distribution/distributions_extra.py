"""Distributions part 2 (reference: python/paddle/distribution/{binomial,
chi2,continuous_bernoulli,multivariate_normal,independent,
transformed_distribution,transform,lkj_cholesky,exponential_family,kl}.py):
remaining families, the Transform machinery, and the register_kl registry.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import gammaln, xlogy, xlog1py

from ..framework.tensor import Tensor
from ..framework import random as _random
from .distributions import (Distribution, Normal, Gamma, _arr, _t, _shape,
                            kl_divergence as _base_kl)

__all__ = [
    "Binomial", "Chi2", "ContinuousBernoulli", "ExponentialFamily",
    "Independent", "MultivariateNormal", "TransformedDistribution",
    "LKJCholesky", "register_kl",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


# ----------------------------------------------------------------- families

class ExponentialFamily(Distribution):
    """Base carrying the Bregman-divergence entropy trick (reference
    exponential_family.py _mean_carrier_measure contract)."""


class Binomial(ExponentialFamily):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _arr(total_count)
        self.probs = _arr(probs)
        super().__init__(jnp.broadcast_shapes(np.shape(self.total_count),
                                              np.shape(self.probs)))

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        sh = _shape(shape, self.total_count, self.probs)
        n = jnp.broadcast_to(self.total_count, sh).astype(jnp.float32)
        p = jnp.broadcast_to(self.probs, sh)
        out = jax.random.binomial(_random.split_key(), n, p, shape=sh)
        return _t(out)

    def log_prob(self, value):
        v = _arr(value)
        n, p = self.total_count, self.probs
        log_comb = (gammaln(n + 1) - gammaln(v + 1) - gammaln(n - v + 1))
        return _t(log_comb + xlogy(v, p) + xlog1py(n - v, -p))

    def entropy(self):
        # Exact sum over the support: O(max(total_count)) memory and
        # requires a CONCRETE total_count (np.max on the value), so this
        # cannot run under jit/tracing — by design for the small-count
        # use cases the reference targets.
        try:
            n = int(np.max(np.asarray(self.total_count)))
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError) as e:
            raise ValueError(
                "Binomial.entropy() enumerates the support and needs a "
                "concrete total_count; call it outside jit") from e
        ks = jnp.arange(n + 1, dtype=jnp.float32)
        shape = (n + 1,) + (1,) * max(len(self.batch_shape), 0)
        ks = ks.reshape(shape)
        lp = self.log_prob(_t(jnp.broadcast_to(
            ks, (n + 1,) + tuple(self.batch_shape))))._data
        valid = ks <= jnp.broadcast_to(self.total_count,
                                       tuple(self.batch_shape))
        lp = jnp.where(valid, lp, -jnp.inf)
        p = jnp.exp(lp)
        return _t(-jnp.sum(jnp.where(p > 0, p * lp, 0.0), axis=0))


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _arr(df)
        super().__init__(df / 2.0, jnp.full(np.shape(df), 0.5))
        self.df = df


class ContinuousBernoulli(ExponentialFamily):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _arr(probs)
        self._lims = lims
        super().__init__(np.shape(self.probs))

    def _outside_unstable(self):
        lo, hi = self._lims
        return (self.probs < lo) | (self.probs > hi)

    def _log_norm(self):
        # C(p) = 2 atanh(1-2p)/(1-2p) for p != 0.5, else 2
        p = self.probs
        safe = jnp.where(self._outside_unstable(), p, 0.499)
        c = jnp.log(jnp.abs(
            2.0 * jnp.arctanh(1.0 - 2.0 * safe) / (1.0 - 2.0 * safe)))
        # Taylor around 1/2: log 2 + 4/3 x^2 + ... with x = p - 1/2
        x = p - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x * x) * x * x
        return jnp.where(self._outside_unstable(), c, taylor)

    @property
    def mean(self):
        p = self.probs
        safe = jnp.where(self._outside_unstable(), p, 0.499)
        m = safe / (2.0 * safe - 1.0) \
            + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
        x = p - 0.5
        taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x * x) * x
        return _t(jnp.where(self._outside_unstable(), m, taylor))

    def sample(self, shape=()):
        sh = _shape(shape, self.probs)
        u = jax.random.uniform(_random.split_key(), sh)
        return self.icdf(_t(u))

    rsample = sample

    def icdf(self, value):
        u = _arr(value)
        p = self.probs
        safe = jnp.where(self._outside_unstable(), p, 0.49)
        icdf = (jnp.log1p(u * (2.0 * safe - 1.0) / (1.0 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return _t(jnp.where(self._outside_unstable(), icdf, u))

    def log_prob(self, value):
        v = _arr(value)
        return _t(xlogy(v, self.probs) + xlog1py(1.0 - v, -self.probs)
                  + self._log_norm())

    def entropy(self):
        # E[-log p(X)] with the closed-form mean
        m = self.mean._data
        return _t(-(xlogy(m, self.probs)
                    + xlog1py(1.0 - m, -self.probs) + self._log_norm()))


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _arr(loc)
        given = [a is not None for a in
                 (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("exactly one of covariance_matrix / "
                             "precision_matrix / scale_tril is required")
        if scale_tril is not None:
            self._tril = _arr(scale_tril)
        elif covariance_matrix is not None:
            self._tril = jnp.linalg.cholesky(_arr(covariance_matrix))
        else:
            prec = _arr(precision_matrix)
            self._tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        d = self.loc.shape[-1]
        super().__init__(np.shape(self.loc)[:-1], (d,))

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def covariance_matrix(self):
        return _t(self._tril @ jnp.swapaxes(self._tril, -1, -2))

    @property
    def variance(self):
        return _t(jnp.sum(jnp.square(self._tril), axis=-1))

    def sample(self, shape=()):
        sh = tuple(shape) + tuple(self.batch_shape) + tuple(self.event_shape)
        eps = jax.random.normal(_random.split_key(), sh)
        return _t(self.loc + jnp.einsum("...ij,...j->...i", self._tril, eps))

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        diff = v - self.loc
        L = jnp.broadcast_to(self._tril,
                             diff.shape[:-1] + self._tril.shape[-2:])
        sol = jax.scipy.linalg.solve_triangular(
            L, diff[..., None], lower=True)[..., 0]
        m = jnp.sum(jnp.square(sol), axis=-1)
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1)), axis=-1)
        d = self.event_shape[0]
        return _t(-0.5 * (d * math.log(2 * math.pi) + m) - half_logdet)

    def entropy(self):
        d = self.event_shape[0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1)), axis=-1)
        return _t(0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet)


class Independent(Distribution):
    """Reinterprets trailing batch dims of ``base`` as event dims
    (reference independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        if self._rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        b = tuple(base.batch_shape)
        cut = len(b) - self._rank
        super().__init__(b[:cut], b[cut:] + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._data
        return _t(jnp.sum(lp, axis=tuple(range(lp.ndim - self._rank,
                                               lp.ndim))))

    def entropy(self):
        e = self.base.entropy()._data
        return _t(jnp.sum(e, axis=tuple(range(e.ndim - self._rank, e.ndim))))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices (reference
    lkj_cholesky.py, onion-method sampling)."""

    def __init__(self, dim, concentration=1.0, sample_method="onion",
                 name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self.dim = int(dim)
        self.concentration = _arr(concentration)
        self.sample_method = sample_method
        super().__init__(np.shape(self.concentration), (dim, dim))

    def sample(self, shape=()):
        d = self.dim
        eta = jnp.broadcast_to(self.concentration,
                               _shape(shape, self.concentration))
        # onion method: build rows from beta marginals + uniform directions
        sh = tuple(np.shape(eta))
        L = jnp.zeros(sh + (d, d))
        L = L.at[..., 0, 0].set(1.0)
        for i in range(1, d):
            beta_c = eta + (d - 1 - i) / 2.0
            y = jax.random.beta(_random.split_key(), i / 2.0, beta_c, sh)
            u = jax.random.normal(_random.split_key(), sh + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.clip(1.0 - y, 1e-38)))
        return _t(L)

    def log_prob(self, value):
        L = _arr(value)
        d = self.dim
        eta = self.concentration
        order = jnp.arange(2, d + 1, dtype=jnp.float32)
        exponents = 2.0 * (eta - 1.0) + d - order
        diags = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        unnorm = jnp.sum(exponents * jnp.log(diags), axis=-1)
        # normalization (reference lkj_cholesky.py log_normalizer)
        dm1 = d - 1
        alpha = eta + 0.5 * dm1
        denom = gammaln(alpha) * dm1
        numer = _mvlgamma(alpha - 0.5, dm1)
        pi_const = 0.5 * dm1 * math.log(math.pi)
        norm = pi_const + numer - denom
        return _t(unnorm - norm)


def _mvlgamma(a, p):
    out = 0.25 * p * (p - 1) * math.log(math.pi)
    for j in range(p):
        out = out + gammaln(a - 0.5 * j)
    return out


# ---------------------------------------------------------------- transforms

class Transform:
    _type = "bijection"

    def forward(self, x):
        return _t(self._forward(_arr(x)))

    def inverse(self, y):
        return _t(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return _t(self._fldj(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _arr(y)
        return _t(-self._fldj(self._inverse(yv)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    def __call__(self, x):
        return self.forward(x)


class AbsTransform(Transform):
    _type = "other"

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _type = "other"

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    def _forward(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        zc = jnp.cumprod(1 - z, axis=-1)
        lead = z * jnp.concatenate(
            [jnp.ones_like(z[..., :1]), zc[..., :-1]], axis=-1)
        return jnp.concatenate([lead, zc[..., -1:]], axis=-1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = y.shape[-1] - jnp.arange(1, y.shape[-1])
        rem = 1.0 - jnp.cumsum(y_crop, axis=-1)
        rem = jnp.concatenate([jnp.ones_like(rem[..., :1]), rem[..., :-1]],
                              axis=-1)
        z = y_crop / rem
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset.astype(y.dtype))

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(np.prod(self.in_event_shape)) != \
                int(np.prod(self.out_event_shape)):
            raise ValueError("shapes must have the same number of elements")

    def _forward(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.reshape(x, lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[:y.ndim - len(self.out_event_shape)]
        return jnp.reshape(y, lead + self.in_event_shape)

    def _fldj(self, x):
        lead = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ld = self.base._fldj(x)
        return jnp.sum(ld, axis=tuple(range(ld.ndim - self._rank, ld.ndim)))


class StackTransform(Transform):
    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _forward(self, x):
        parts = [t._forward(xi) for t, xi in zip(
            self.transforms,
            jnp.moveaxis(x, self.axis, 0))]
        return jnp.stack(parts, axis=self.axis)

    def _inverse(self, y):
        parts = [t._inverse(yi) for t, yi in zip(
            self.transforms,
            jnp.moveaxis(y, self.axis, 0))]
        return jnp.stack(parts, axis=self.axis)

    def _fldj(self, x):
        parts = [t._fldj(xi) for t, xi in zip(
            self.transforms, jnp.moveaxis(x, self.axis, 0))]
        return jnp.stack(parts, axis=self.axis)


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        event = tuple(base.event_shape)
        for t in self.transforms:
            event = t.forward_shape(event)
        super().__init__(tuple(base.batch_shape), event)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = 0.0
        y = _arr(value)
        for t in reversed(self.transforms):
            x = t._inverse(y)
            lp = lp - t._fldj(x)
            y = x
        return _t(lp + self.base.log_prob(_t(y))._data)


# --------------------------------------------------------------- KL registry

_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering an analytic KL(p||q) (reference kl.py
    register_kl)."""
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            return fn(p, q)
    return _base_kl(p, q)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    d = p.event_shape[0]
    half_logdet_p = jnp.sum(jnp.log(jnp.diagonal(
        p._tril, axis1=-2, axis2=-1)), axis=-1)
    half_logdet_q = jnp.sum(jnp.log(jnp.diagonal(
        q._tril, axis1=-2, axis2=-1)), axis=-1)
    M = jax.scipy.linalg.solve_triangular(q._tril, p._tril, lower=True)
    tr = jnp.sum(jnp.square(M), axis=(-2, -1))
    diff = q.loc - p.loc
    sol = jax.scipy.linalg.solve_triangular(
        q._tril, diff[..., None], lower=True)[..., 0]
    m = jnp.sum(jnp.square(sol), axis=-1)
    return _t(half_logdet_q - half_logdet_p + 0.5 * (tr + m - d))


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    if p._rank != q._rank:
        raise NotImplementedError
    kl = kl_divergence(p.base, q.base)._data
    return _t(jnp.sum(kl, axis=tuple(range(kl.ndim - p._rank, kl.ndim))))
