"""paddle.distribution (reference: python/paddle/distribution/)."""
from .distributions import (  # noqa: F401
    Distribution, Normal, Uniform, Categorical, Bernoulli, Exponential,
    Beta, Dirichlet, Gamma, Laplace, LogNormal, Multinomial, Poisson,
    Geometric, Cauchy, Gumbel, StudentT)
from .distributions_extra import (  # noqa: F401
    Binomial, Chi2, ContinuousBernoulli, ExponentialFamily, Independent,
    MultivariateNormal, TransformedDistribution, LKJCholesky, register_kl,
    kl_divergence,
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform)
