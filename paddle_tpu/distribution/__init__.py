"""paddle.distribution (reference: python/paddle/distribution/)."""
from .distributions import (  # noqa: F401
    Distribution, Normal, Uniform, Categorical, Bernoulli, Exponential,
    Beta, Dirichlet, Gamma, Laplace, LogNormal, Multinomial, Poisson,
    Geometric, Cauchy, Gumbel, StudentT, kl_divergence)
