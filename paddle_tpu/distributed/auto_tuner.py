"""paddle.distributed.auto_tuner — parallel-config search.

Reference: python/paddle/distributed/auto_tuner/ (tuner.py:21 Tuner,
search.py GridSearch, prune.py:143 invalid-config pruning,
recorder.py History sorting).

TPU formulation: candidates are (pp, dp, tp, sharding stage, micro
batch) factorizations of the chip count; pruning uses divisibility and a
first-order HBM model (params/grads/optimizer state sharded by
dp-sharding and tp, activations by remat policy).  run_fn measures a
real trial (the driver typically passes a jitted train-step timing fn);
the recorder keeps history sorted by the metric.
"""
from __future__ import annotations

import itertools
import json

__all__ = ["Tuner", "Recorder", "candidate_configs", "prune_invalid",
           "estimate_hbm_bytes"]


def candidate_configs(num_devices, model=None, max_micro=8):
    """All (pp, dp, tp) factorizations × sharding stage × micro-batch."""
    out = []
    for pp in _divisors(num_devices):
        rem = num_devices // pp
        for dp in _divisors(rem):
            tp = rem // dp
            for stage in (0, 1, 2, 3):
                for micro in (m for m in (1, 2, 4, 8) if m <= max_micro):
                    out.append({"pp": pp, "dp": dp, "tp": tp,
                                "sharding_stage": stage,
                                "micro_batch": micro})
    return out


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def estimate_hbm_bytes(cfg, num_params, hidden=4096, layers=32, seq=4096,
                       batch=8, bytes_param=2, bytes_opt=12, remat=True):
    """First-order per-chip HBM model (reference: the memory cost model
    in auto_tuner/prune.py + cost/)."""
    tp = cfg["tp"]
    pp = cfg["pp"]
    dp = cfg["dp"]
    stage = cfg["sharding_stage"]
    shard_params = tp * pp * (dp if stage >= 3 else 1)
    shard_opt = tp * pp * (dp if stage >= 1 else 1)
    shard_grad = tp * pp * (dp if stage >= 2 else 1)
    p = num_params * bytes_param / shard_params
    o = num_params * bytes_opt / shard_opt
    g = num_params * bytes_param / shard_grad
    mb = max(batch // (dp * cfg["micro_batch"]), 1)
    act_per_layer = mb * seq * hidden * 2
    acts = act_per_layer * (1 if remat else layers) * \
        (layers // pp) / tp
    return p + o + g + acts


def prune_invalid(configs, num_devices, model_cfg=None, hbm_limit=None,
                  layers=None, batch=None):
    """Divisibility + memory pruning (reference: prune.py:143)."""
    out = []
    layers = layers or (model_cfg or {}).get("layers", 32)
    batch = batch or (model_cfg or {}).get("batch", 8)
    for c in configs:
        if c["pp"] * c["dp"] * c["tp"] != num_devices:
            continue
        if layers % c["pp"]:
            continue
        if batch % (c["dp"] * c["micro_batch"]):
            continue
        if c["sharding_stage"] and c["dp"] == 1:
            continue
        if hbm_limit and model_cfg:
            need = estimate_hbm_bytes(
                c, model_cfg["num_params"],
                hidden=model_cfg.get("hidden", 4096),
                layers=layers, seq=model_cfg.get("seq", 4096),
                batch=batch)
            if need > hbm_limit:
                continue
        out.append(c)
    return out


class Recorder:
    """Reference: recorder.py History."""

    def __init__(self):
        self.history = []

    def add(self, cfg, metric, error=None):
        self.history.append({"config": cfg, "metric": metric,
                             "error": error})

    def best(self, mode="max"):
        ok = [h for h in self.history if h["error"] is None
              and h["metric"] is not None]
        if not ok:
            return None
        return (max if mode == "max" else min)(
            ok, key=lambda h: h["metric"])

    def save(self, path):
        with open(path, "w") as f:
            json.dump(self.history, f, indent=2)


class Tuner:
    """Grid search over pruned candidates (reference: tuner.py:21)."""

    def __init__(self, num_devices, model_cfg=None, hbm_limit=None,
                 max_trials=None, mode="max"):
        self.num_devices = num_devices
        self.model_cfg = model_cfg
        self.hbm_limit = hbm_limit
        self.max_trials = max_trials
        self.mode = mode
        self.recorder = Recorder()
        cands = candidate_configs(num_devices)
        self.candidates = prune_invalid(cands, num_devices, model_cfg,
                                        hbm_limit)

    def tune(self, run_fn):
        """run_fn(cfg) -> metric (e.g. tokens/s); exceptions recorded as
        failed trials (reference: the trial-job launcher)."""
        for i, cfg in enumerate(self.candidates):
            if self.max_trials is not None and i >= self.max_trials:
                break
            try:
                metric = run_fn(cfg)
                self.recorder.add(cfg, metric)
            except Exception as e:     # failed trial, keep searching
                self.recorder.add(cfg, None, error=str(e))
        best = self.recorder.best(self.mode)
        return best["config"] if best else None
