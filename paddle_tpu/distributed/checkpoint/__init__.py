from .save_load import save_state_dict, load_state_dict

__all__ = ["save_state_dict", "load_state_dict"]
