"""Distributed checkpoint with re-shard-on-load.

Reference: python/paddle/distributed/checkpoint/save_state_dict.py:145 +
load_state_dict.py:467 — per-rank shard files + global Metadata mapping
tensor→shards; load computes the overlap between saved shards and the
current placements and re-slices, so training resumes on a *different*
mesh/parallel config.

TPU-native implementation on orbax-style principles: each process writes
the shards it owns (`addressable_shards`) + a metadata fragment; after an
ALL-rank barrier the coordinator merges fragments into metadata.json (a
second barrier holds everyone until the merged metadata exists).  Load
never materializes a full global tensor: for every *target* shard it
reads only the saved shards that overlap that slice
(`jax.make_array_from_callback` pulls exactly the local slices), so peak
host memory is ~one target shard + one saved-rank payload file.
"""
from __future__ import annotations

import json
import os
import pickle
from collections import OrderedDict

import jax
import numpy as np

from ...framework.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]

_META = "metadata.json"
_PAYLOAD_CACHE_FILES = 2   # bound host memory to ~2 rank files at once


def _rank():
    """Process rank: launcher env (PADDLE_TRAINER_ID) under
    paddle.distributed.launch, else jax.process_index()."""
    r = os.environ.get("PADDLE_TRAINER_ID")
    if r is not None:
        return int(r)
    return jax.process_index()


def _barrier(tag):
    """Cross-PROCESS barrier: rendezvous TCPStore under the paddle
    launcher, jax's coordination service under jax-native multi-host,
    no-op single-process (collective.barrier is only a device sync, not
    a process barrier)."""
    if int(os.environ.get("PADDLE_TRAINERS_NUM", "1")) > 1:
        from ..store import create_or_get_global_tcp_store
        create_or_get_global_tcp_store().barrier(tag=f"ckpt/{tag}")
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"ckpt/{tag}")


def _shard_index(index_tuple, shape):
    """Normalized [(start, stop), ...] from a numpy index tuple."""
    out = []
    for sl, dim in zip(index_tuple, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return out


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    meta = {"tensors": {}}
    rank = _rank()
    shard_file = os.path.join(path, f"shard_{rank}.pkl")
    payload = {}
    for name, t in _flatten_state(state_dict).items():
        arr = t._data if isinstance(t, Tensor) else jax.numpy.asarray(t)
        gshape = list(np.shape(arr))
        entry = {"shape": gshape, "dtype": str(np.dtype(arr.dtype)),
                 "shards": []}
        shards = getattr(arr, "addressable_shards", None)
        if shards:
            for s in shards:
                idx = _shard_index(s.index, gshape) if s.index else \
                    [(0, d) for d in gshape]
                key = f"{name}@{rank}:{len(entry['shards'])}"
                # dedupe replicated shards: keep first per unique index
                if any(sh["index"] == idx for sh in entry["shards"]):
                    continue
                entry["shards"].append({"index": idx, "file": key})
                payload[key] = np.asarray(s.data)
        else:
            key = f"{name}@{rank}:0"
            entry["shards"].append({"index": [(0, d) for d in gshape],
                                    "file": key})
            payload[key] = np.asarray(arr)
        meta["tensors"][name] = entry
    with open(shard_file, "wb") as f:
        pickle.dump(payload, f, protocol=4)
    with open(os.path.join(path, f"meta_{rank}.json"), "w") as f:
        json.dump(meta, f)

    # EVERY rank reaches this barrier before the coordinator merges, so no
    # fragment can be missed (reference save_state_dict.py:145 barriers
    # before writing the global Metadata); a second barrier keeps fast
    # ranks from returning before metadata.json exists.
    _barrier("fragments")
    if rank == coordinator_rank:
        # a reused directory may hold fragments/payloads from an older,
        # larger world or a failed save whose shard entries point at
        # stale payload files; the coordinator knows this save's world
        # size and removes anything outside it before merging
        import glob
        import re as _re
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        world = max(world, jax.process_count())
        for f in glob.glob(os.path.join(path, "meta_*.json")) \
                + glob.glob(os.path.join(path, "shard_*.pkl")):
            m = _re.search(r"_(\d+)\.(?:json|pkl)$", f)
            if m and int(m.group(1)) >= world:
                os.remove(f)
        merged = {"tensors": {}}
        for frag in sorted(glob.glob(os.path.join(path, "meta_*.json"))):
            with open(frag) as f:
                m = json.load(f)
            for name, entry in m["tensors"].items():
                tgt = merged["tensors"].setdefault(
                    name, {"shape": entry["shape"], "dtype": entry["dtype"],
                           "shards": []})
                for sh in entry["shards"]:
                    if not any(e["index"] == sh["index"]
                               for e in tgt["shards"]):
                        tgt["shards"].append(sh)
        tmp = os.path.join(path, _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, os.path.join(path, _META))
    _barrier("metadata")


class _PayloadReader:
    """Reads saved shard payloads with a small LRU over rank files, so
    host memory stays ~one rank file regardless of checkpoint size."""

    def __init__(self, path):
        self.path = path
        self.cache = OrderedDict()

    def __call__(self, fname):
        srank = fname.split("@")[1].split(":")[0]
        pfile = os.path.join(self.path, f"shard_{srank}.pkl")
        if pfile not in self.cache:
            if len(self.cache) >= _PAYLOAD_CACHE_FILES:
                self.cache.popitem(last=False)
            with open(pfile, "rb") as f:
                self.cache[pfile] = pickle.load(f)
        else:
            self.cache.move_to_end(pfile)
        return self.cache[pfile][fname]


def _read_slice(entry, bounds, dtype, reader):
    """Assemble ONLY the [(start, stop), ...] `bounds` slice of a saved
    tensor from whichever saved shards overlap it (reference
    load_state_dict.py:467 computes the same overlaps rank-locally)."""
    sizes = tuple(b - a for a, b in bounds)
    out = np.zeros(sizes, dtype)
    for sh in entry["shards"]:
        inter = [(max(a, sa), min(b, sb))
                 for (a, b), (sa, sb) in zip(bounds, sh["index"])]
        if any(a >= b for a, b in inter):
            continue
        src = reader(sh["file"])
        src_idx = tuple(slice(a - sa, b - sa) for (a, b), (sa, _sb)
                        in zip(inter, sh["index"]))
        dst_idx = tuple(slice(a - ta, b - ta) for (a, b), (ta, _tb)
                        in zip(inter, bounds))
        out[dst_idx] = np.asarray(src[src_idx], dtype)
    return out


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in place, re-slicing saved shards to
    the current placements.  Only the slices needed by this process's
    addressable target shards are ever read/assembled on host."""
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    reader = _PayloadReader(path)

    flat = _flatten_state(state_dict)
    for name, t in flat.items():
        if name not in meta["tensors"]:
            continue
        entry = meta["tensors"][name]
        gshape = tuple(entry["shape"])
        is_tensor = isinstance(t, Tensor)
        is_array = isinstance(t, jax.Array)
        if not (is_tensor or is_array):
            continue
        if tuple(t.shape) != gshape:
            raise ValueError(
                f"{name}: saved global shape {gshape} != "
                f"target {tuple(t.shape)}")
        arr = t._data if is_tensor else t
        tgt_dtype = np.dtype(arr.dtype)
        sharding = getattr(arr, "sharding", None)
        if sharding is not None:
            def cb(idx, _e=entry, _d=tgt_dtype, _g=gshape):
                bounds = _shard_index(idx, _g) if idx else \
                    [(0, d) for d in _g]
                return _read_slice(_e, bounds, _d, reader)

            new = jax.make_array_from_callback(gshape, sharding, cb)
        else:
            new = jax.numpy.asarray(_read_slice(
                entry, [(0, d) for d in gshape], tgt_dtype, reader))
        if is_tensor:
            t._data = new                   # fill the Tensor in place
        else:
            # raw jax.Array targets are immutable: rebind in the dict
            _set_by_path(state_dict, name, new)
    return state_dict


def _set_by_path(state, dotted, value):
    """Rebind a flattened name.  Dots are ambiguous — they join nesting
    levels AND appear inside flat keys ("llama.norm.weight") — so walk
    by consuming the LONGEST key present at each level."""
    node, rest = state, dotted
    while True:
        if rest in node and not isinstance(node[rest], dict):
            node[rest] = value
            return
        parts = rest.split(".")
        for i in range(len(parts) - 1, 0, -1):
            k = ".".join(parts[:i])
            if k in node and isinstance(node[k], dict):
                node, rest = node[k], ".".join(parts[i:])
                break
        else:
            raise KeyError(dotted)


def _flatten_state(state, prefix=""):
    out = {}
    for k, v in state.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_state(v, key + "."))
        else:
            out[key] = v
    return out
