"""Distributed checkpoint with re-shard-on-load.

Reference: python/paddle/distributed/checkpoint/save_state_dict.py:145 +
load_state_dict.py:467 — per-rank shard files + global Metadata mapping
tensor→shards; load computes the overlap between saved shards and the
current placements and re-slices, so training resumes on a *different*
mesh/parallel config.

TPU-native implementation on orbax-style principles: each process writes
the shards it owns (`addressable_shards`) + a metadata.json with
global shape / dtype / shard index maps; load assembles requested slices
from whichever saved shards overlap and device_puts into the target
sharding.  Single-controller runs write all shards.
"""
from __future__ import annotations

import json
import os
import pickle

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...framework.tensor import Tensor
from ..mesh import get_mesh
from ..placement import placements_to_spec

__all__ = ["save_state_dict", "load_state_dict"]

_META = "metadata.json"


def _shard_index(index_tuple, shape):
    """Normalized [(start, stop), ...] from a numpy index tuple."""
    out = []
    for sl, dim in zip(index_tuple, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return out


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    meta = {"tensors": {}}
    rank = jax.process_index()
    shard_file = os.path.join(path, f"shard_{rank}.pkl")
    payload = {}
    for name, t in _flatten_state(state_dict).items():
        arr = t._data if isinstance(t, Tensor) else jax.numpy.asarray(t)
        gshape = list(np.shape(arr))
        entry = {"shape": gshape, "dtype": str(np.dtype(arr.dtype)),
                 "shards": []}
        shards = getattr(arr, "addressable_shards", None)
        if shards:
            for s in shards:
                idx = _shard_index(s.index, gshape) if s.index else \
                    [(0, d) for d in gshape]
                key = f"{name}@{rank}:{len(entry['shards'])}"
                # dedupe replicated shards: keep first per unique index
                if any(sh["index"] == idx for sh in entry["shards"]):
                    continue
                entry["shards"].append({"index": idx, "file": key})
                payload[key] = np.asarray(s.data)
        else:
            key = f"{name}@{rank}:0"
            entry["shards"].append({"index": [(0, d) for d in gshape],
                                    "file": key})
            payload[key] = np.asarray(arr)
        meta["tensors"][name] = entry
    with open(shard_file, "wb") as f:
        pickle.dump(payload, f, protocol=4)
    # every rank writes its metadata fragment; the coordinator merges all
    # fragments present (multi-host runs share the checkpoint dir, matching
    # the reference's global Metadata written after a barrier)
    with open(os.path.join(path, f"meta_{rank}.json"), "w") as f:
        json.dump(meta, f)
    if rank == coordinator_rank:
        from ..collective import barrier
        barrier()
        merged = {"tensors": {}}
        import glob
        for frag in sorted(glob.glob(os.path.join(path, "meta_*.json"))):
            with open(frag) as f:
                m = json.load(f)
            for name, entry in m["tensors"].items():
                tgt = merged["tensors"].setdefault(
                    name, {"shape": entry["shape"], "dtype": entry["dtype"],
                           "shards": []})
                for sh in entry["shards"]:
                    if not any(e["index"] == sh["index"]
                               for e in tgt["shards"]):
                        tgt["shards"].append(sh)
        with open(os.path.join(path, _META), "w") as f:
            json.dump(merged, f)


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None,
                    offload=False):
    """Fill `state_dict`'s tensors in place, re-slicing saved shards to the
    current placements (reference load_state_dict.py:467)."""
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    # load all shard payloads lazily per file
    payload_cache: dict[str, dict] = {}

    def get_payload(fname):
        srank = fname.split("@")[1].split(":")[0]
        pfile = os.path.join(path, f"shard_{srank}.pkl")
        if pfile not in payload_cache:
            with open(pfile, "rb") as f:
                payload_cache[pfile] = pickle.load(f)
        return payload_cache[pfile][fname]

    flat = _flatten_state(state_dict)
    for name, t in flat.items():
        if name not in meta["tensors"]:
            continue
        entry = meta["tensors"][name]
        gshape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        if tuple(t.shape) != gshape and isinstance(t, Tensor):
            raise ValueError(
                f"{name}: saved global shape {gshape} != target {tuple(t.shape)}")
        # assemble the full array from saved shards, then re-place with the
        # target's sharding (XLA slices per-device; only the local slices
        # materialize on devices)
        full = np.zeros(gshape, dtype)
        for sh in entry["shards"]:
            idx = tuple(slice(a, b) for a, b in sh["index"])
            full[idx] = get_payload(sh["file"])
        if isinstance(t, Tensor):
            target_sharding = getattr(t._data, "sharding", None)
            arr = jax.device_put(full.astype(np.dtype(t._data.dtype)),
                                 target_sharding) \
                if target_sharding is not None else jax.numpy.asarray(full)
            t._data = arr
    return state_dict


def _flatten_state(state, prefix=""):
    out = {}
    for k, v in state.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_state(v, key + "."))
        else:
            out[key] = v
    return out
