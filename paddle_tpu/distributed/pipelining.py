"""SPMD pipeline parallelism.

Reference: fleet/meta_parallel/pipeline_parallel.py (1F1B:575, train_batch:
820) + p2p_communication.py — rank-to-rank isend/irecv of activations driven
by a host-side schedule.  XLA has no native PP (SURVEY §7 hard part (a)), so
the TPU-native formulation is: stage weights live stacked along a leading
dim sharded over the 'pp' mesh axis; one `lax.scan` over
(microbatches + stages - 1) ticks runs inside `shard_map`; activations move
stage-to-stage with `lax.ppermute` over ICI.  Differentiating through the
scan yields the reverse (backward) pipeline automatically — the 1F1B
interleave is then XLA's latency hiding rather than a hand-written
schedule; `jax.checkpoint` on the stage body gives the usual
activation-memory profile.

Constraints: pipelined stages must be shape-homogeneous (e.g. transformer
blocks); embedding/head run replicated outside the pipelined region — the
standard TPU pipelining recipe.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["spmd_pipeline"]


def _stage_spec(leaf):
    return P("pp", *([None] * (leaf.ndim - 1)))


def spmd_pipeline(stage_fn: Callable, stacked_params, microbatches, mesh,
                  axis_name: str = "pp", remat: bool = True):
    """Run `stage_fn(params, x) -> x` as a pipeline over `axis_name`.

    stacked_params: pytree with leading dim = n_stages on every leaf
    microbatches:  [M, mb, ...] array (replicated over pp)
    returns:       [M, mb, ...] outputs of the final stage (replicated)
    """
    n_stages = mesh.shape[axis_name]
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def per_device(params, mbs):
        params = jax.tree_util.tree_map(lambda a: a[0], params)  # my stage
        stage = jax.lax.axis_index(axis_name)
        m = mbs.shape[0]
        total = m + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, outs = carry
            # stage 0 ingests microbatch t (clamped; masked later)
            inj = mbs[jnp.minimum(t, m - 1)]
            state = jnp.where(stage == 0, inj, state)
            state = body(params, state)
            out_idx = t - (n_stages - 1)
            is_out = jnp.logical_and(stage == n_stages - 1,
                                     jnp.logical_and(out_idx >= 0,
                                                     out_idx < m))
            idx = jnp.clip(out_idx, 0, m - 1)
            outs = outs.at[idx].set(jnp.where(is_out, state, outs[idx]))
            state = jax.lax.ppermute(state, axis_name, perm)
            return (state, outs), None

        state0 = jnp.zeros_like(mbs[0])
        outs0 = jnp.zeros_like(mbs)
        (state, outs), _ = jax.lax.scan(tick, (state0, outs0),
                                        jnp.arange(total))
        # only the final stage's buffer is real; keep it pp-stacked and
        # let the caller's slice broadcast from the last stage (cheaper
        # than psum-ing a buffer that is zeros on pp-1 stages)
        return outs[None]

    spec_params = jax.tree_util.tree_map(_stage_spec, stacked_params)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(spec_params, P()),
                   out_specs=P(axis_name), check_vma=False)
    return fn(stacked_params, microbatches)[-1]
