"""Placements (reference: paddle/phi/core/distributed/auto_parallel/
placement_types.h — Shard/Replicate/Partial) and their mapping to
jax PartitionSpec entries."""
from __future__ import annotations

from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["Placement", "Shard", "Replicate", "Partial",
           "placements_to_spec", "spec_to_placements"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("Replicate")


class Partial(Placement):
    """Pending-reduction state (reference partial placement; GSPMD analog:
    values awaiting psum — representable only inside shard_map, so at the
    API level resharding from Partial triggers the reduction)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))


def placements_to_spec(mesh, placements, ndim):
    """[Shard(0), Replicate()] + mesh dims -> PartitionSpec rows."""
    entries: list = [None] * ndim
    for axis_idx, p in enumerate(placements):
        if isinstance(p, Shard):
            name = mesh.dim_names[axis_idx]
            cur = entries[p.dim]
            if cur is None:
                entries[p.dim] = name
            elif isinstance(cur, tuple):
                entries[p.dim] = cur + (name,)
            else:
                entries[p.dim] = (cur, name)
    return PartitionSpec(*entries)


def spec_to_placements(mesh, spec, ndim):
    placements = [Replicate() for _ in mesh.dim_names]
    for tensor_dim, entry in enumerate(spec):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        for n in names:
            placements[mesh.dim_names.index(n)] = Shard(tensor_dim)
    return placements
