"""Bootstrap rendezvous store.

Native C++ TCPStore (csrc/tcp_store.cc, reference
paddle/phi/core/distributed/store/tcp_store.h:121) when the native core is
available, else an in-process Python fallback with the same API — the
fallback only supports single-process use (enough for tests and local runs
where jax.distributed handles real rendezvous).
"""
from __future__ import annotations

import threading
import time

__all__ = ["TCPStore", "create_or_get_global_tcp_store"]

try:
    from ..core import TCPStore as _NativeTCPStore
    from ..core import available as _native_available
except Exception:  # pragma: no cover
    _NativeTCPStore = None

    def _native_available():
        return False


class _LocalStore:
    """Same-process stand-in (API of tcp_store.h) when g++ is unavailable."""

    def __init__(self, host="127.0.0.1", port=0, is_master=True,
                 world_size=1, timeout=300.0):
        self.host, self.port = host, port
        self.world_size = world_size
        self._kv = {}
        self._cv = threading.Condition()

    def set(self, key, value):
        v = value if isinstance(value, bytes) else str(value).encode()
        with self._cv:
            self._kv[key] = v
            self._cv.notify_all()

    def get(self, key):
        with self._cv:
            self._cv.wait_for(lambda: key in self._kv)
            return self._kv[key]

    def add(self, key, delta):
        with self._cv:
            cur = int.from_bytes(self._kv.get(key, b"\0" * 8), "little",
                                 signed=True)
            cur += delta
            self._kv[key] = cur.to_bytes(8, "little", signed=True)
            self._cv.notify_all()
            return cur

    def wait(self, keys):
        if isinstance(keys, str):
            keys = [keys]
        with self._cv:
            self._cv.wait_for(lambda: all(k in self._kv for k in keys))

    def check(self, key):
        with self._cv:
            return key in self._kv

    def delete_key(self, key):
        with self._cv:
            return self._kv.pop(key, None) is not None

    def num_keys(self):
        with self._cv:
            return len(self._kv)

    def barrier(self, tag="default"):
        pass  # single process

    def close(self):
        pass


def TCPStore(host="127.0.0.1", port=0, is_master=False, world_size=1,
             timeout=300.0):
    """Factory matching paddle.distributed's TCPStore constructor shape."""
    if _NativeTCPStore is not None and _native_available():
        return _NativeTCPStore(host, port, is_master=is_master,
                               world_size=world_size, timeout=timeout)
    return _LocalStore(host, port, is_master, world_size, timeout)


_global_store = None
_global_lock = threading.Lock()


def create_or_get_global_tcp_store():
    """reference parallel.py:1134 — one process-wide store."""
    global _global_store
    with _global_lock:
        if _global_store is None:
            import os
            host = os.environ.get("PADDLE_MASTER_HOST")
            port = os.environ.get("PADDLE_MASTER_PORT")
            if (host is None or port is None) and \
                    os.environ.get("PADDLE_MASTER"):
                # PADDLE_MASTER is the jax coordination endpoint; the KV
                # store deterministically claims the next port so every
                # rank agrees without extra configuration
                mh, _, mp = os.environ["PADDLE_MASTER"].partition(":")
                host = host or mh
                port = port or str(int(mp) + 1)
            host = host or "127.0.0.1"
            port = int(port or "0")
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
            _global_store = TCPStore(host, port, is_master=(rank == 0),
                                     world_size=world)
        return _global_store
