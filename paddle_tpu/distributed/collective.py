"""Collective communication API.

Reference: python/paddle/distributed/communication/* + ProcessGroup layer
(paddle/fluid/distributed/collective/process_group_nccl.h:37).  TPU-native
story (SURVEY §8): a "process group" is a set of mesh axis names; inside
jit/shard_map the collective IS the XLA op (psum/all_gather/ppermute over
ICI); eagerly, collectives execute as tiny jitted shard_map programs over
the group's mesh axes.  Single-device groups are identity.

Two calling contexts, one API:
  * traced (inside shard_map with the axis in scope) → jax.lax collective
  * eager Tensor → jitted shard_map over the global mesh
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import get_mesh, ProcessMesh
from ..framework.tensor import Tensor
from .. import observability as _obs

__all__ = ["Group", "new_group", "get_group", "all_reduce", "all_gather",
           "all_gather_object", "reduce_scatter", "all_to_all", "broadcast",
           "reduce", "scatter", "barrier", "send", "recv", "irecv", "isend",
           "ReduceOp", "split", "wait", "get_world_size_group"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communicator = one or more mesh axes (reference: new_group over
    rank lists; here groups are axis-aligned, matching hybrid topology)."""

    def __init__(self, axis_names, mesh=None, gid=0):
        self.axis_names = tuple(axis_names) if not isinstance(axis_names, str) \
            else (axis_names,)
        self._mesh = mesh
        self.id = gid

    @property
    def mesh(self):
        return self._mesh or get_mesh()

    @property
    def nranks(self):
        m = self.mesh
        if m is None:
            return 1
        n = 1
        for a in self.axis_names:
            if a in m.dim_names:
                n *= m.get_dim_size(a)
        return n

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        return 0  # single-controller SPMD: per-device rank exists in-graph

    @property
    def process_ids(self):
        return list(range(self.nranks))

    def get_group_rank(self, rank):
        return rank if rank < self.nranks else -1

    def __repr__(self):
        return f"Group(axes={self.axis_names}, nranks={self.nranks})"


_groups: dict[int, Group] = {}
_next_gid = [1]
_default_group: Group | None = None


def _get_or_create_default_group():
    global _default_group
    if _default_group is None:
        m = get_mesh()
        axes = tuple(m.dim_names) if m is not None else ()
        _default_group = Group(axes, gid=0)
        _groups[0] = _default_group
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_names=None):
    """reference collective.py:194 new_group.  Axis-aligned groups: pass
    axis_names; rank-list groups map onto the axis whose size matches."""
    gid = _next_gid[0]
    _next_gid[0] += 1
    if axis_names is None:
        m = get_mesh()
        if m is not None and ranks is not None:
            matches = [a for a in m.dim_names
                       if m.get_dim_size(a) == len(ranks)]
            if len(matches) > 1:
                import warnings
                warnings.warn(
                    f"new_group(ranks={ranks}): multiple mesh axes "
                    f"{matches} have size {len(ranks)}; picking "
                    f"{matches[0]!r}. Pass axis_names= to disambiguate.")
            if matches:
                axis_names = (matches[0],)
        axis_names = axis_names or (m.dim_names if m else ())
    g = Group(axis_names, gid=gid)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid) or _get_or_create_default_group()


def _axes(group):
    if group is None:
        group = _get_or_create_default_group()
    return tuple(a for a in group.axis_names
                 if get_mesh() is not None and a in get_mesh().dim_names)


# telemetry for the eager collective path (traced collectives live
# inside XLA programs and are profiled by the device tracer): call +
# payload-byte counters per collective kind, and a RecordEvent span so
# host traces show where collective time goes
_M_COLL_CALLS = _obs.counter(
    "collective_calls_total", "eager collective invocations", ("op",))
_M_COLL_BYTES = _obs.counter(
    "collective_bytes_total", "payload bytes entering eager collectives",
    ("op",))


def _payload_bytes(arr):
    try:
        n = int(np.prod(np.shape(arr)) or 1)
        dt = getattr(arr, "dtype", None)
        return n * (np.dtype(dt).itemsize if dt is not None else 0)
    except Exception:
        return 0


class _collective_span:
    """Span + counters around one eager collective."""

    def __init__(self, name, arr=None):
        self._name = name
        _M_COLL_CALLS.labels(name).inc()
        b = _payload_bytes(arr) if arr is not None else 0
        if b:
            _M_COLL_BYTES.labels(name).inc(b)
        from ..profiler import RecordEvent
        self._ev = RecordEvent(f"collective:{name}")

    def __enter__(self):
        self._ev.begin()
        return self

    def __exit__(self, *exc):
        self._ev.end()
        return False


def _eager_shardmap(fn, x, group):
    """Run a per-shard function over the group's axes on an eager array."""
    m = get_mesh().jax_mesh
    axes = _axes(group)
    sharding = getattr(x, "sharding", None)
    spec = sharding.spec if isinstance(sharding, NamedSharding) \
        else PartitionSpec()
    from jax import shard_map
    out_spec = spec  # same layout by default
    return jax.jit(shard_map(fn, mesh=m, in_specs=(spec,),
                             out_specs=out_spec,
                             check_vma=False))(x)


def _prod_reduce(x, axes):
    # XLA has no pprod; exp∘psum∘log is numerically fragile, so gather+prod.
    for a in axes:
        x = jnp.prod(jax.lax.all_gather(x, a, axis=0), axis=0)
    return x


def _reduce_fn(op):
    fns = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
           ReduceOp.MIN: jax.lax.pmin, ReduceOp.PROD: _prod_reduce,
           ReduceOp.AVG: jax.lax.psum}
    if op not in fns:
        raise ValueError(f"unsupported reduce op: {op!r}")
    return fns[op]


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce over the group's mesh axes."""
    axes = _axes(group)
    if not axes:
        return tensor
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if isinstance(arr, jax.core.Tracer):
        out = _reduce_fn(op)(arr, axes)
        if op == ReduceOp.AVG:
            out = out / np.prod([jax.lax.axis_size(a) for a in axes])
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return out

    def body(x):
        r = _reduce_fn(op)(x, axes)
        if op == ReduceOp.AVG:
            import numpy as _np
            n = int(_np.prod([get_mesh().get_dim_size(a) for a in axes]))
            r = r / n
        return r
    with _collective_span("all_reduce", arr):
        out = _eager_shardmap(body, arr, group)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """Gather shards from every rank (reference: all_gather fills a list).
    Traced form returns the concatenated array."""
    axes = _axes(group)
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if not axes:
        if tensor_list is not None:
            tensor_list.append(Tensor(arr) if not isinstance(tensor, Tensor)
                               else tensor)
            return tensor_list
        return tensor
    def _gather_all(x):
        for a in axes:
            x = jax.lax.all_gather(x, a, axis=axis, tiled=True)
        return x

    if isinstance(arr, jax.core.Tracer):
        return _gather_all(arr)

    # eager: every rank's gathered result is identical → replicated output
    def body(x):
        return _gather_all(x)
    m = get_mesh().jax_mesh
    from jax import shard_map
    sharding = getattr(arr, "sharding", None)
    spec = sharding.spec if isinstance(sharding, NamedSharding) \
        else PartitionSpec()
    with _collective_span("all_gather", arr):
        gathered = jax.jit(shard_map(
            body, mesh=m, in_specs=(spec,), out_specs=PartitionSpec(),
            check_vma=False))(arr)
    if tensor_list is not None:
        n = int(np.prod([get_mesh().get_dim_size(a) for a in axes]))
        for piece in jnp.split(gathered, n, axis=axis):
            tensor_list.append(Tensor(piece))
        return tensor_list
    return Tensor(gathered)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op=True, axis=0):
    axes = _axes(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        src = jnp.concatenate([t._data if isinstance(t, Tensor) else t
                               for t in src], axis=axis)
    elif isinstance(src, Tensor):
        src = src._data
    if not axes:
        if isinstance(tensor, Tensor):
            tensor._data = src
        return tensor
    def _scatter_all(x):
        for a in axes:
            x = jax.lax.psum_scatter(x, a, scatter_dimension=axis,
                                     tiled=True)
        return x

    if isinstance(src, jax.core.Tracer):
        return _scatter_all(src)

    with _collective_span("reduce_scatter", src):
        out = _eager_shardmap(_scatter_all, src, group)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return Tensor(out)


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """reference: dist.alltoall — exchange the i-th chunk with rank i."""
    axes = _axes(group)
    if isinstance(in_tensor_list, (list, tuple)):
        x = jnp.stack([t._data if isinstance(t, Tensor) else t
                       for t in in_tensor_list], axis=0)
    else:
        x = in_tensor_list._data if isinstance(in_tensor_list, Tensor) \
            else in_tensor_list
    if not axes:
        if out_tensor_list is not None:
            out_tensor_list.extend(
                [Tensor(s) for s in list(x)] if x.ndim else [Tensor(x)])
            return out_tensor_list
        return in_tensor_list
    if isinstance(x, jax.core.Tracer):
        return jax.lax.all_to_all(x, axes[0], split_axis=0, concat_axis=0,
                                  tiled=True)
    # Eager all-to-all is ill-posed under a single controller (each logical
    # rank's output differs but hosts see one value) — the meaningful form
    # is the traced one (MoE dispatch under shard_map). Replicated input →
    # the exchange is the identity on the list.
    if out_tensor_list is not None:
        out_tensor_list.extend(
            t if isinstance(t, Tensor) else Tensor(t)
            for t in (in_tensor_list if isinstance(in_tensor_list,
                                                   (list, tuple)) else [x]))
        return out_tensor_list
    return in_tensor_list


alltoall = all_to_all


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Broadcast from src rank.  Under SPMD every rank already holds the
    replicated value, so this materializes the replicated sharding."""
    axes = _axes(group)
    if not axes:
        return tensor
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if isinstance(arr, jax.core.Tracer):
        # select src's value on every rank
        idx = jax.lax.axis_index(axes[0])
        src_val = jax.lax.all_gather(arr, axes[0], axis=0)[src]
        return src_val
    m = get_mesh()
    sh = NamedSharding(m.jax_mesh, PartitionSpec())
    with _collective_span("broadcast", arr):
        out = jax.device_put(arr, sh)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return Tensor(out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Deliver tensor_list[my_rank] (src names the sender, whose list is
    authoritative — under a single controller every rank sees that list).
    Traced form selects the chunk by in-graph axis_index."""
    axes = _axes(group)
    if tensor_list:
        arrs = [t._data if isinstance(t, Tensor) else t for t in tensor_list]
        if not axes:
            tensor._data = arrs[0]
            return tensor
        first = arrs[0]
        if isinstance(first, jax.core.Tracer) or any(
                isinstance(a, jax.core.Tracer) for a in arrs):
            stacked = jnp.stack(arrs)
            my = jax.lax.axis_index(axes[0])
            return jnp.take(stacked, my, axis=0)
        # eager single-controller: the calling process is rank 0
        tensor._data = arrs[0]
        return tensor
    return tensor


def barrier(group=None):
    with _collective_span("barrier"):
        (jax.device_put(0) + 0).block_until_ready()


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv is expressed as ppermute inside "
        "shard_map on TPU (see fleet.meta_parallel pipeline); host-level "
        "P2P is not part of the SPMD model")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv is expressed as ppermute inside "
        "shard_map on TPU (see fleet.meta_parallel pipeline)")


isend = send
irecv = recv


def split(x, num_or_sections, axis=0, group=None):
    from ..ops.manipulation import split as _split
    return _split(x, num_or_sections, axis)


def wait(tensor, group=None, use_calc_stream=True):
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if hasattr(arr, "block_until_ready"):
        from .watchdog import comm_guard
        with comm_guard("wait", group):
            arr.block_until_ready()
    return tensor


def get_world_size_group(group=None):
    g = group or _get_or_create_default_group()
    return g.nranks


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)
    return object_list
