"""Distributed environment state.

Reference: env vars set by the launcher (PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM — python/paddle/distributed/launch) + ParallelEnv
(python/paddle/distributed/parallel.py).  On TPU, process identity comes
from jax.distributed / the TPU runtime; single-process SPMD over all local
devices is the common case, where rank/world refer to *processes* (hosts)
and mesh axes handle the device-level parallelism.
"""
from __future__ import annotations

import os

import jax

__all__ = ["get_rank", "get_world_size", "ParallelEnv", "init_parallel_env",
           "is_initialized"]

_initialized = False


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))


def is_initialized():
    return _initialized


def init_parallel_env():
    """reference parallel.py:978 init_parallel_env. Multi-process: bring
    up jax's coordination service from the launcher env
    (JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID set by
    paddle_tpu.distributed.launch) — the TPU analog of TCPStore +
    ProcessGroupNCCL init; single-process: just mark state."""
    global _initialized
    if not _initialized:
        nprocs = int(os.environ.get("JAX_NUM_PROCESSES",
                                    os.environ.get("PADDLE_TRAINERS_NUM",
                                                   "1")))
        if nprocs > 1:
            from jax._src import distributed as _jd
            if getattr(_jd.global_state, "client", None) is None:
                # not yet rendezvoused (on TPU pods the runtime may have
                # done it already; then this is a no-op)
                from .launch import DEFAULT_MASTER
                rank_var = os.environ.get(
                    "JAX_PROCESS_ID", os.environ.get("PADDLE_TRAINER_ID"))
                if rank_var is None:
                    raise RuntimeError(
                        "multi-process init needs JAX_PROCESS_ID or "
                        "PADDLE_TRAINER_ID per rank (set by "
                        "paddle_tpu.distributed.launch); defaulting all "
                        "ranks to 0 would hang the rendezvous")
                jax.distributed.initialize(
                    coordinator_address=os.environ.get(
                        "JAX_COORDINATOR_ADDRESS",
                        os.environ.get("PADDLE_MASTER", DEFAULT_MASTER)),
                    num_processes=nprocs,
                    process_id=int(rank_var))
    _initialized = True
    from .collective import _get_or_create_default_group
    return _get_or_create_default_group()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []
