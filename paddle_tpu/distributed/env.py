"""Distributed environment state.

Reference: env vars set by the launcher (PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM — python/paddle/distributed/launch) + ParallelEnv
(python/paddle/distributed/parallel.py).  On TPU, process identity comes
from jax.distributed / the TPU runtime; single-process SPMD over all local
devices is the common case, where rank/world refer to *processes* (hosts)
and mesh axes handle the device-level parallelism.
"""
from __future__ import annotations

import os

import jax

__all__ = ["get_rank", "get_world_size", "ParallelEnv", "init_parallel_env",
           "is_initialized"]

_initialized = False


def get_rank(group=None):
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return int(os.environ.get("PADDLE_TRAINERS_NUM", jax.process_count()))


def is_initialized():
    return _initialized


def init_parallel_env():
    """reference parallel.py:978 init_parallel_env — on TPU the runtime
    already rendezvoused (jax.distributed), so this marks state and returns
    the default group."""
    global _initialized
    _initialized = True
    from .collective import _get_or_create_default_group
    return _get_or_create_default_group()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []
