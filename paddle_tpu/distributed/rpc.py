"""paddle.distributed.rpc — minimal RPC.

Reference: python/paddle/distributed/rpc/rpc.py (init_rpc, rpc_sync,
rpc_async, shutdown over the brpc C++ service
paddle/fluid/distributed/rpc/).

TPU formulation: a thread-per-connection TCP server with
length-prefixed pickle frames — the host-side control plane (parameter
serving, coordination) the reference runs over brpc; device-side
communication stays on XLA collectives.  WorkerInfo/rank discovery
rides the same TCPStore used for process-group bootstrap.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from concurrent.futures import Future

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


class WorkerInfo:
    def __init__(self, name, rank, ip, port):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.ip}, port={self.port})")


_state = {"server": None, "workers": {}, "name": None, "stop": None,
          "rank": None, "store": None, "token": None, "thread": None}


def _host_ip():
    """Reachable address of this host (reference advertises the trainer
    endpoint IP, not loopback)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))   # no packets sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _send_frame(sock, obj):
    data = pickle.dumps(obj, protocol=4)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_frame(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


def _serve(server_sock, stop_event):
    server_sock.settimeout(0.2)
    while not stop_event.is_set():
        try:
            conn, _ = server_sock.accept()
        except socket.timeout:
            continue
        except OSError:
            return

        def handle(c):
            try:
                req = _recv_frame(c)
                token, fn, args, kwargs = req
                if token != _state["token"]:
                    _send_frame(c, ("err", PermissionError(
                        "rpc auth token mismatch")))
                    return
                try:
                    result = ("ok", fn(*args, **kwargs))
                except Exception as e:      # ship the failure back
                    result = ("err", e)
                try:
                    _send_frame(c, result)
                except Exception as e:      # unpicklable result/exception
                    _send_frame(c, ("err", RuntimeError(
                        f"rpc result not serializable: {e}")))
            except Exception:   # tpu-lint: disable=thread-bare-except
                pass            # malformed/hostile peer frames are
            finally:            # dropped by design; real call failures
                c.close()       # were already shipped back as ("err",)

        # per-connection handlers are fire-and-forget by design: the
        # server cannot enumerate them, and closing the listener (plus
        # each handler's own socket close) is the shutdown path
        # tpu-lint: disable=thread-unjoined
        threading.Thread(target=handle, args=(conn,), daemon=True).start()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC server + discover peers (reference:
    rpc.py init_rpc over TCPStore)."""
    import os

    if _state["server"] is not None:
        shutdown()      # re-init replaces the previous server cleanly

    rank = rank if rank is not None else int(
        os.getenv("PADDLE_TRAINER_ID", "0"))
    world_size = world_size if world_size is not None else int(
        os.getenv("PADDLE_TRAINERS_NUM", "1"))

    ip = _host_ip() if world_size > 1 else "127.0.0.1"
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((ip if world_size > 1 else "127.0.0.1", 0))
    srv.listen(64)
    port = srv.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(target=_serve, args=(srv, stop), daemon=True)
    t.start()
    _state["thread"] = t        # joined in shutdown()

    # peer discovery + shared auth token via the KV store (pickle over
    # sockets is code execution; the token keeps strangers out)
    from .store import create_or_get_global_tcp_store
    store = create_or_get_global_tcp_store()
    if rank == 0:
        import secrets
        token = secrets.token_hex(16)
        store.set("/rpc/token", token)
    else:
        import time as _time
        deadline0 = _time.monotonic() + 60
        while True:
            try:
                token = store.get("/rpc/token")
                break
            except Exception:
                if _time.monotonic() > deadline0:
                    raise TimeoutError("init_rpc: no auth token from rank 0")
                _time.sleep(0.05)
        if isinstance(token, bytes):
            token = token.decode()
    _state.update(server=srv, name=name, stop=stop, rank=rank,
                  store=store, token=token)
    store.set(f"/rpc/{rank}", f"{name},{ip},{port}")
    import time
    deadline = time.monotonic() + 60
    workers = {}
    while len(workers) < world_size:
        for r in range(world_size):
            if r in workers:
                continue
            try:
                raw = store.get(f"/rpc/{r}")
            except Exception:
                continue
            if isinstance(raw, bytes):
                raw = raw.decode()
            wname, ip, p = str(raw).split(",")
            workers[r] = WorkerInfo(wname, r, ip, int(p))
        if time.monotonic() > deadline:
            raise TimeoutError("init_rpc: peers did not register")
        if len(workers) < world_size:
            time.sleep(0.05)
    _state["workers"] = {w.name: w for w in workers.values()}
    return _state["workers"][name]


def get_worker_info(name=None):
    if name is None:
        name = _state["name"]
    return _state["workers"][name]


def get_all_worker_infos():
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def rpc_sync(to, fn, args=None, kwargs=None, timeout=120):
    """Run fn(*args) on worker `to`, return its result."""
    w = _state["workers"][to]
    with socket.create_connection((w.ip, w.port), timeout=timeout) as c:
        _send_frame(c, (_state["token"], fn, tuple(args or ()),
                        dict(kwargs or {})))
        status, payload = _recv_frame(c)
    if status == "err":
        raise payload
    return payload


def rpc_async(to, fn, args=None, kwargs=None, timeout=120):
    """Future-returning variant (reference returns FutureWrapper)."""
    fut: Future = Future()

    def run():
        try:
            fut.set_result(rpc_sync(to, fn, args, kwargs, timeout))
        except Exception as e:
            fut.set_exception(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    fut._thread = t            # retained so callers can join if needed
    fut.wait = fut.result      # paddle API parity (fut.wait())
    return fut


def shutdown():
    if _state["stop"] is not None:
        _state["stop"].set()
    if _state["server"] is not None:
        try:
            _state["server"].close()
        except OSError:
            pass
    if _state["thread"] is not None:
        _state["thread"].join(timeout=5.0)
    if _state["store"] is not None and _state["rank"] is not None:
        try:    # drop our registration so a re-init can't find stale peers
            _state["store"].delete_key(f"/rpc/{_state['rank']}")
        except Exception:
            pass
    _state.update(server=None, workers={}, name=None, stop=None,
                  rank=None, store=None, token=None, thread=None)
