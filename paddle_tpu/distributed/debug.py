"""Sharding inspection + per-op rule pinning.

Reference analog: the 113 per-op SPMD rule files
(paddle/phi/infermeta/spmd_rules/matmul.cc ...) give every reference op
deliberate, INSPECTABLE placement semantics.  On TPU, placement comes
from GSPMD propagation — correct by construction but silent: a
regression in a constraint upstream can quietly re-shard half the model.
This module restores the two capabilities the rule files provide:

  * `debug_shardings(fn, *args)` — compile and report, from the
    SPMD-PARTITIONED module: every instruction's per-shard (local)
    shape, the parameter/output shardings (which survive partitioning),
    and the collective inventory (all-reduce/all-gather/...).  Tests pin
    "what sharding did op X get" through its local shape — a [16,128]
    matmul tiled dp=2 x tp=4 MUST appear as a [8,32] dot — and pin
    "no surprise collectives" directly (the inspection surface);
  * `sharding_rules({...})` / `pin_rule` — a per-op override that runs a
    registry op under `jax.shard_map` with EXPLICIT in/out specs, for
    the ops GSPMD gets wrong (the rule surface).
"""
from __future__ import annotations

import contextlib
import re
import threading
from dataclasses import dataclass

import jax
from jax.sharding import PartitionSpec

__all__ = ["debug_shardings", "ShardingReport", "sharding_rules",
           "OpShardRule"]

# HLO text: `%name = bf16[8,128]{1,0} dot(...), sharding={devices=[2,1]0,1}`
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>[\w\[\],{}:()\s]*?)\s*"
    r"(?P<kind>[\w\-]+)\((?P<rest>.*)$")
_SHARD_RE = re.compile(r"sharding=\{([^}]*)\}")
_SHAPE_RE = re.compile(r"^\(?\s*([a-z0-9]+)\[([\d,]*)\]")


@dataclass
class Instruction:
    name: str          # HLO instruction name, e.g. dot.42
    kind: str          # HLO opcode, e.g. dot / gather / custom-call
    shape: str         # result type text, e.g. bf16[256,512]
    sharding: str      # sharding annotation text ('' = none recorded)

    def __repr__(self):
        sh = self.sharding or "<default>"
        return f"{self.name}: {self.kind} {self.shape} sharding={sh}"


_COLLECTIVES = ("all-reduce", "all-gather", "all-to-all",
                "collective-permute", "reduce-scatter")


class ShardingReport(list):
    """List[Instruction] with query helpers for tests/debugging."""

    def find(self, kind=None, name=None):
        out = ShardingReport(
            i for i in self
            if (kind is None or i.kind == kind)
            and (name is None or name in i.name))
        return out

    def shardings(self, kind=None, name=None):
        return [i.sharding for i in self.find(kind, name)]

    def local_shapes(self, kind=None, name=None):
        """Per-shard result shapes — the partitioned module's direct
        record of how each op was tiled."""
        return [i.shape for i in self.find(kind, name)]

    def collectives(self):
        """The communication GSPMD inserted: what to pin in regression
        tests ('this step has exactly one tp all-reduce')."""
        return ShardingReport(i for i in self
                              if i.kind in _COLLECTIVES)

    def summary(self, max_rows=40):
        rows = [repr(i) for i in self
                if i.sharding or i.kind in _COLLECTIVES][:max_rows]
        more = len(self) - len(rows)
        return "\n".join(rows + ([f"... +{more} more"] if more > 0
                                  else []))


def debug_shardings(fn, *args, static_argnums=(), **kwargs):
    """Compile `fn(*args, **kwargs)` and return a ShardingReport of every
    HLO instruction in the OPTIMIZED module, with the sharding XLA/GSPMD
    assigned to it.  `fn` may already be jitted.

        rep = dist.debug_shardings(train_step, params, batch)
        assert "devices=[1,8]" in rep.find(kind="dot")[0].sharding
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums)
    compiled = jitted.lower(*args, **kwargs).compile()
    report = ShardingReport()
    for mod_text in [compiled.as_text()]:
        for line in mod_text.splitlines():
            m = _INSTR_RE.match(line)
            if not m or "=" not in line:
                continue
            sh = _SHARD_RE.search(line)
            ty = m.group("type").strip()
            sm = _SHAPE_RE.match(ty)
            report.append(Instruction(
                name=m.group("name"), kind=m.group("kind"),
                shape=(f"{sm.group(1)}[{sm.group(2)}]" if sm else ty),
                sharding=sh.group(1) if sh else ""))
    return report


# ------------------------------------------------------------- pin rules
@dataclass
class OpShardRule:
    """Explicit placement for one registry op: run its body under
    shard_map(mesh, in_specs, out_specs).  in_specs: one PartitionSpec
    per ARRAY input in flat order (non-array args stay closed over);
    out_specs: a spec or pytree of specs matching the op's outputs."""
    mesh: object
    in_specs: tuple
    out_specs: object
    check_vma: bool = False


class _RuleState(threading.local):
    def __init__(self):
        self.rules = {}


_state = _RuleState()


def get_pinned_rule(opname):
    return _state.rules.get(opname)


@contextlib.contextmanager
def sharding_rules(rules):
    """Pin per-op placements for the ops GSPMD propagates wrongly:

        rule = dist.OpShardRule(mesh, in_specs=(P(None, "tp"), P("tp")),
                                out_specs=P(None))
        with dist.sharding_rules({"embedding": rule}):
            loss = train_step(...)

    Inside the scope, every dispatch of the named ops runs its body
    under jax.shard_map with the given specs — GSPMD cannot re-decide
    those ops' placement (reference: the per-op rule files under
    paddle/phi/infermeta/spmd_rules/)."""
    saved = dict(_state.rules)
    _state.rules.update(rules)
    try:
        yield
    finally:
        _state.rules = saved


def apply_rule(rule: OpShardRule, body, args, kwargs):
    """Run `body(*args, **kwargs)` under the rule's shard_map; arrays in
    flat order consume rule.in_specs, everything else is closed over."""
    from jax.tree_util import tree_flatten, tree_unflatten
    import numpy as np

    flat, treedef = tree_flatten((args, kwargs))
    arr_pos = [i for i, x in enumerate(flat)
               if isinstance(x, (jax.Array, np.ndarray))
               or hasattr(x, "aval")]
    if len(arr_pos) != len(rule.in_specs):
        raise ValueError(
            f"OpShardRule: {len(rule.in_specs)} in_specs for "
            f"{len(arr_pos)} array inputs")

    def inner(arrays):
        flat2 = list(flat)
        for p, a in zip(arr_pos, arrays):
            flat2[p] = a
        a2, k2 = tree_unflatten(treedef, flat2)
        return body(*a2, **k2)

    mesh = getattr(rule.mesh, "jax_mesh", rule.mesh)
    return jax.shard_map(
        inner, mesh=mesh, in_specs=(tuple(rule.in_specs),),
        out_specs=rule.out_specs, check_vma=rule.check_vma)(
            tuple(flat[p] for p in arr_pos))
