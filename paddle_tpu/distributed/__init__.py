"""paddle.distributed namespace — TPU-native (SURVEY §8: process groups →
mesh axes, NCCL → XLA collectives over ICI/DCN, reshard functions → GSPMD
resharding)."""
from . import env
from .env import get_rank, get_world_size, init_parallel_env, ParallelEnv, \
    is_initialized
from .mesh import ProcessMesh, get_mesh, set_mesh, auto_mesh, \
    init_device_mesh
from .placement import Shard, Replicate, Partial, Placement
from .collective import Group, new_group, get_group, all_reduce, all_gather, \
    all_gather_object, reduce_scatter, all_to_all, alltoall, broadcast, \
    reduce, scatter, barrier, send, recv, isend, irecv, ReduceOp, wait
from .auto_parallel.api import shard_tensor, reshard, shard_layer, \
    shard_optimizer, dtensor_from_local, dtensor_to_local, unshard_dtensor, \
    ShardingStage1, ShardingStage2, ShardingStage3, get_placements
from .shard_ops import sharding_constraint, annotate
from .debug import (debug_shardings, ShardingReport,
                    sharding_rules, OpShardRule)
from . import fleet
from . import rpc
from . import ps
from . import auto_tuner
from . import launch
from . import checkpoint
from .checkpoint import save_state_dict, load_state_dict
from .fleet.meta_parallel.parallel_wrappers import DataParallel
from .fleet.base import ParallelMode
from . import pipelining
from .store import TCPStore, create_or_get_global_tcp_store
from .watchdog import (CommTask, CommTaskManager, get_comm_task_manager,
                       comm_guard)
from . import io
from .compat import (
    ReduceType, Strategy, DistAttr, DistModel, to_static, alltoall_single,
    gather, broadcast_object_list, scatter_object_list,
    destroy_process_group, get_backend, is_available,
    gloo_init_parallel_env, gloo_barrier, gloo_release, spawn, split,
    dtensor_from_fn, shard_dataloader, shard_scaler, InMemoryDataset,
    QueueDataset, CountFilterEntry, ProbabilityEntry, ShowClickEntry)

__all__ = [
    "env", "get_rank", "get_world_size", "init_parallel_env", "ParallelEnv",
    "is_initialized", "ProcessMesh", "get_mesh", "set_mesh", "auto_mesh",
    "init_device_mesh", "Shard", "Replicate", "Partial", "Placement",
    "Group", "new_group", "get_group", "all_reduce", "all_gather",
    "reduce_scatter", "all_to_all", "alltoall", "broadcast", "reduce",
    "scatter", "barrier", "send", "recv", "ReduceOp", "wait",
    "shard_tensor", "reshard", "shard_layer", "shard_optimizer",
    "dtensor_from_local", "dtensor_to_local", "unshard_dtensor",
    "ShardingStage1", "ShardingStage2", "ShardingStage3", "fleet",
    "checkpoint", "save_state_dict", "load_state_dict", "DataParallel",
    "sharding_constraint", "annotate", "debug_shardings",
    "ShardingReport", "sharding_rules", "OpShardRule", "get_placements", "TCPStore",
    "create_or_get_global_tcp_store",
    "ParallelMode", "ReduceType", "Strategy", "DistAttr", "DistModel",
    "to_static", "alltoall_single", "gather", "broadcast_object_list",
    "scatter_object_list", "destroy_process_group", "get_backend",
    "is_available", "gloo_init_parallel_env", "gloo_barrier",
    "gloo_release", "spawn", "split", "dtensor_from_fn",
    "shard_dataloader", "shard_scaler", "InMemoryDataset", "QueueDataset",
    "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry", "io",
    "CommTask", "CommTaskManager", "get_comm_task_manager", "comm_guard",
]
