"""paddle.distributed namespace — TPU-native (SURVEY §8: process groups →
mesh axes, NCCL → XLA collectives over ICI/DCN)."""
from . import env
from .env import get_rank, get_world_size, init_parallel_env, ParallelEnv, \
    is_initialized

__all__ = ["env", "get_rank", "get_world_size", "init_parallel_env",
           "ParallelEnv", "is_initialized"]
