"""Mixture-of-Experts with expert parallelism, TPU-native.

Reference: incubate/distributed/models/moe/moe_layer.py (MoELayer :119,
global_scatter/global_gather alltoall dispatch :263; ops
paddle/fluid/operators/collective/global_scatter_op.cc) and the gating
kernels number_count / limit_by_capacity / prune_gate_by_capacity
(paddle/phi/kernels/gpu/).

TPU formulation (GShard/Switch): gating produces a *dense* dispatch tensor
with a static capacity — data-dependent token routing becomes two einsums,
which XLA partitions into all-to-alls over the 'ep' mesh axis when expert
tensors are sharded on their leading (expert) dim.  No dynamic shapes under
jit (SURVEY §7 hard part (c)).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["top_k_gating", "moe_dispatch_combine", "number_count",
           "limit_by_capacity", "prune_gate_by_capacity"]


# -------------------------------------------------- reference gating utils
def number_count(gate_idx, upper_range):
    """Tokens per expert (reference number_count_kernel)."""
    return jnp.sum(jax.nn.one_hot(gate_idx, upper_range, dtype=jnp.int32),
                   axis=tuple(range(gate_idx.ndim)))


def limit_by_capacity(expert_count, capacity, n_worker=1):
    """Clamp per-expert token counts (reference limit_by_capacity_kernel)."""
    return jnp.minimum(expert_count, capacity * n_worker)


def prune_gate_by_capacity(gate_idx, expert_count, capacity):
    """Mark overflow tokens' gate index as -1 (reference
    prune_gate_by_capacity_kernel)."""
    onehot = jax.nn.one_hot(gate_idx, expert_count.shape[-1],
                            dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based position per expert
    my_pos = jnp.sum(pos, axis=-1)
    return jnp.where(my_pos <= capacity, gate_idx, -1)


# ------------------------------------------------------------- GShard core
def top_k_gating(logits, top_k=2, capacity_factor=1.25, capacity=None,
                 train=True, noise_key=None):
    """logits: [S, E] -> (combine [S, E, C] f32, dispatch [S, E, C] bool,
    aux_loss scalar).  Static capacity C."""
    s, e = logits.shape
    if capacity is None:
        capacity = max(4, int(math.ceil(s * top_k * capacity_factor / e)))
    if train and noise_key is not None:
        logits = logits + jax.random.gumbel(noise_key, logits.shape,
                                            logits.dtype) * 1e-2
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    combine = jnp.zeros((s, e, capacity), jnp.float32)
    dispatch = jnp.zeros((s, e, capacity), bool)
    masked = probs
    # position_in_expert accumulates across the k selection rounds
    fill = jnp.zeros((e,), jnp.int32)
    aux = 0.0
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                     # [S]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [S, E]
        # Switch load-balancing loss: E * sum_e(frac_tokens_e * mean_prob_e)
        frac = jnp.mean(onehot, axis=0)                        # [E]
        mean_p = jnp.mean(probs, axis=0)                       # [E]
        aux = aux + e * jnp.sum(frac * mean_p)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot      # 0-based
        pos = pos + fill[None, :] * onehot
        in_cap = (pos < capacity) & (onehot > 0)
        posc = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
        sel = jax.nn.one_hot(posc, capacity, dtype=jnp.float32) \
            * in_cap[..., None]
        gate_val = jnp.sum(probs * onehot, axis=-1, keepdims=True)
        combine = combine + sel * gate_val[..., None]
        dispatch = dispatch | (sel > 0)
        fill = fill + jnp.sum(onehot * in_cap, axis=0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)
    return combine, dispatch, aux / top_k


def moe_dispatch_combine(x, gate_w, w1, b1, w2, b2, *, top_k=2,
                         capacity_factor=1.25, activation=jax.nn.gelu,
                         mesh=None, ep_axis="ep", train=True,
                         noise_key=None):
    """Full MoE FFN over flat tokens.

    x: [S, M]; gate_w: [M, E]; w1: [E, M, F]; b1: [E, F]; w2: [E, F, M];
    b2: [E, M].  Returns (y [S, M], aux_loss).

    With `mesh` given and `ep_axis` in it, expert-stacked tensors get
    Shard(0) constraints over ep: XLA lowers the dispatch einsum to the
    all-to-all the reference codes as global_scatter/global_gather.
    """
    logits = x @ gate_w.astype(x.dtype)
    combine, dispatch, aux = top_k_gating(
        logits, top_k=top_k, capacity_factor=capacity_factor, train=train,
        noise_key=noise_key)
    combine = combine.astype(x.dtype)
    # dispatch: [S, E, C] x [S, M] -> [E, C, M]  (the global_scatter);
    # boolean mask — gate scaling happens only on the combine side
    expert_in = jnp.einsum("sec,sm->ecm", dispatch.astype(x.dtype), x)
    if mesh is not None and ep_axis in mesh.axis_names:
        shard_e = NamedSharding(mesh, P(ep_axis, None, None))
        expert_in = jax.lax.with_sharding_constraint(expert_in, shard_e)
    h = activation(jnp.einsum("ecm,emf->ecf", expert_in, w1)
                   + b1[:, None, :])
    expert_out = jnp.einsum("ecf,efm->ecm", h, w2) + b2[:, None, :]
    if mesh is not None and ep_axis in mesh.axis_names:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(ep_axis, None, None)))
    # combine back: the global_gather
    y = jnp.einsum("sec,ecm->sm", combine, expert_out)
    return y, aux.astype(jnp.float32)
