"""Mixture-of-Experts with expert parallelism, TPU-native.

Reference: incubate/distributed/models/moe/moe_layer.py (MoELayer :119,
global_scatter/global_gather alltoall dispatch :263; ops
paddle/fluid/operators/collective/global_scatter_op.cc) and the gating
kernels number_count / limit_by_capacity / prune_gate_by_capacity
(paddle/phi/kernels/gpu/).

TPU formulation (GShard/Switch): gating produces a *dense* dispatch tensor
with a static capacity — data-dependent token routing becomes two einsums,
which XLA partitions into all-to-alls over the 'ep' mesh axis when expert
tensors are sharded on their leading (expert) dim.  No dynamic shapes under
jit (SURVEY §7 hard part (c)).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["top_k_gating", "moe_dispatch_combine", "number_count",
           "limit_by_capacity", "prune_gate_by_capacity",
           "sort_dispatch_combine"]


# -------------------------------------------------- reference gating utils
def number_count(gate_idx, upper_range):
    """Tokens per expert (reference number_count_kernel)."""
    return jnp.sum(jax.nn.one_hot(gate_idx, upper_range, dtype=jnp.int32),
                   axis=tuple(range(gate_idx.ndim)))


def limit_by_capacity(expert_count, capacity, n_worker=1):
    """Clamp per-expert token counts (reference limit_by_capacity_kernel)."""
    return jnp.minimum(expert_count, capacity * n_worker)


def prune_gate_by_capacity(gate_idx, expert_count, capacity):
    """Mark overflow tokens' gate index as -1 (reference
    prune_gate_by_capacity_kernel)."""
    onehot = jax.nn.one_hot(gate_idx, expert_count.shape[-1],
                            dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based position per expert
    my_pos = jnp.sum(pos, axis=-1)
    return jnp.where(my_pos <= capacity, gate_idx, -1)


# ------------------------------------------------------------- GShard core
def top_k_gating(logits, top_k=2, capacity_factor=1.25, capacity=None,
                 train=True, noise_key=None):
    """logits: [S, E] -> (combine [S, E, C] f32, dispatch [S, E, C] bool,
    aux_loss scalar).  Static capacity C.  Shares the gating front-end
    (_topk_choices) with the sort dispatch so the two formulations can
    never desynchronize on noise/aux/tie semantics."""
    s, e = logits.shape
    capacity = _capacity(s, top_k, capacity_factor, e, capacity)
    idx, gv, aux = _topk_choices(logits, top_k, train, noise_key)

    combine = jnp.zeros((s, e, capacity), jnp.float32)
    dispatch = jnp.zeros((s, e, capacity), bool)
    # position_in_expert accumulates across the k selection rounds
    fill = jnp.zeros((e,), jnp.int32)
    for r in range(top_k):
        onehot = jax.nn.one_hot(idx[:, r], e, dtype=jnp.float32)  # [S, E]
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot      # 0-based
        pos = pos + fill[None, :] * onehot
        in_cap = (pos < capacity) & (onehot > 0)
        posc = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
        sel = jax.nn.one_hot(posc, capacity, dtype=jnp.float32) \
            * in_cap[..., None]
        combine = combine + sel * gv[:, r, None, None]
        dispatch = dispatch | (sel > 0)
        fill = fill + jnp.sum(onehot * in_cap, axis=0).astype(jnp.int32)
    return combine, dispatch, aux


def _topk_choices(logits, top_k, train, noise_key):
    """Shared gating front-end: per-token expert ids [S, K] (descending
    prob, ties to the lower index like iterated argmax), gate values
    [S, K] f32, and the Switch load-balancing aux loss."""
    if train and noise_key is not None:
        logits = logits + jax.random.gumbel(noise_key, logits.shape,
                                            logits.dtype) * 1e-2
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    e = probs.shape[-1]
    gv, idx = jax.lax.top_k(probs, top_k)                 # [S, K] each
    frac = jnp.mean(jax.nn.one_hot(idx, e, dtype=jnp.float32), axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_p[None, :], axis=-1).mean()
    return idx, gv, aux


def _capacity(s, top_k, capacity_factor, e, capacity):
    if capacity is not None:
        return capacity
    return max(4, int(math.ceil(s * top_k * capacity_factor / e)))


# The hand-written VJPs below keep BOTH directions pure gathers: XLA's
# TPU row-scatter runs ~13x slower than the equivalent gather (measured
# v5e), and autodiff of a gather emits exactly that scatter.  Index
# arrays ride along as regular (None-cotangent) arguments so they stay
# jit-safe.

@jax.custom_vjp
def _gather_dispatch(x, ft_slot, svalid, dest, keep):
    """Token rows [S, M] -> expert buffer [E*C, M].

    ft_slot[slot] = token index feeding that slot, svalid[slot] = slot
    actually filled; dest[k-major entry] = slot fed by that entry
    (dump slot when dropped), keep[entry] = entry in capacity."""
    return jnp.where(svalid[:, None], x[ft_slot], 0)


def _gather_dispatch_fwd(x, ft_slot, svalid, dest, keep):
    out = _gather_dispatch(x, ft_slot, svalid, dest, keep)
    # zero-width carrier keeps x's shape/dtype in the residuals as a
    # jax type (saving x itself would pin the whole activation)
    xref = jnp.zeros((x.shape[0], 0), x.dtype)
    return out, (xref, dest, keep)


def _gather_dispatch_bwd(res, dbuf):
    xref, dest, keep = res
    s = xref.shape[0]
    m = dbuf.shape[-1]
    k = dest.shape[0] // s
    # dest is k-major entry order, so the reshape IS the per-round split
    dent = dbuf[jnp.minimum(dest, dbuf.shape[0] - 1)] \
        * keep[:, None].astype(dbuf.dtype)                # [N, M] gather
    dx = jnp.sum(dent.reshape(k, s, m), axis=0)
    return (dx.astype(xref.dtype), None, None, None, None)


_gather_dispatch.defvjp(_gather_dispatch_fwd, _gather_dispatch_bwd)


@jax.custom_vjp
def _gather_combine(flat, gvf, ft, ft_slot, gv_slot, svalid, dest, keep,
                    sref):
    """Expert rows [E*C, M] * gate values -> token rows [S, M].
    sref is a [S] int8 shape-carrier so S stays static under tracing."""
    m = flat.shape[-1]
    s = sref.shape[0]
    k = dest.shape[0] // s
    back = flat[jnp.minimum(dest, flat.shape[0] - 1)] \
        * (gvf * keep.astype(gvf.dtype))[:, None]
    return jnp.sum(back.reshape(k, s, m), axis=0)


def _gather_combine_fwd(flat, gvf, ft, ft_slot, gv_slot, svalid, dest,
                        keep, sref):
    out = _gather_combine(flat, gvf, ft, ft_slot, gv_slot, svalid, dest,
                          keep, sref)
    return out, (flat, gvf, ft, ft_slot, gv_slot, svalid, dest, keep)


def _gather_combine_bwd(res, dy):
    flat, gvf, ft, ft_slot, gv_slot, svalid, dest, keep = res
    # slot gets its gradient from the unique entry that fills it
    dflat = jnp.where(svalid[:, None],
                      gv_slot[:, None] * dy[ft_slot].astype(flat.dtype),
                      0)
    # gate-value grad: <expert row, token cotangent> per entry
    dgv = keep.astype(gvf.dtype) * jnp.sum(
        flat[jnp.minimum(dest, flat.shape[0] - 1)].astype(jnp.float32)
        * dy[ft].astype(jnp.float32), axis=-1).astype(gvf.dtype)
    return (dflat, dgv, None, None, None, None, None, None, None)


_gather_combine.defvjp(_gather_combine_fwd, _gather_combine_bwd)



def _count_rank(idx, gv, e, dtype):
    """Counting-sort front-end shared by the capacity and tile-aligned
    dispatches: k-major flatten + per-expert rank via one-hot cumsum
    (round-0 choices rank before round-1, matching the reference's
    round-by-round position accounting)."""
    s, k = idx.shape
    n = s * k
    fe = idx.T.reshape(n)                  # k-major: round 0 first
    ft = jnp.tile(jnp.arange(s, dtype=jnp.int32), k)
    gvf = gv.T.reshape(n).astype(dtype)
    onehot = jax.nn.one_hot(fe, e, dtype=jnp.int32)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=1) - 1
    counts = jnp.sum(onehot, axis=0)
    return n, fe, ft, gvf, pos, counts


def _slot_views(entry_of_slot, ft, gvf, n):
    """Slot-side maps from the inverted permutation: validity, feeding
    token, gate value."""
    svalid = entry_of_slot < n
    eos = jnp.minimum(entry_of_slot, n - 1)
    return svalid, ft[eos], jnp.where(svalid, gvf[eos], 0)


def sort_dispatch_combine(x, idx, gv, e, capacity, ffn):
    """Counting-sort dispatch/combine (reference global_scatter/
    global_gather, paddle/fluid/operators/collective/global_scatter_op.cc
    — without the dense [S, E, C] one-hot the GShard formulation
    materializes).

    x: [S, M] tokens; idx/gv: [S, K] expert choices (k-major priority:
    all first choices fill capacity before any second choice, matching
    the reference's round-by-round position accounting); ffn maps
    [E, C, M] -> [E, C, M].  Returns y [S, M].

    TPU formulation: the expert alphabet is tiny, so the dispatch
    permutation comes from a COUNTING sort — a one-hot cumsum gives each
    entry its rank within its expert and one small int scatter inverts
    slot -> entry.  (The previous formulation's two [S*K] argsorts cost
    ~0.85 ms/layer on v5e — 20x this whole front-end — and forced an
    extra inverse-permutation gather in both directions.)  Dispatch,
    combine, and both backward paths are pure gathers; static shapes
    throughout; overflow tokens contribute zero (SURVEY §7 hard
    part (c)).
    """
    s, m = x.shape
    n, fe, ft, gvf, pos, _counts = _count_rank(idx, gv, e, x.dtype)
    keep = pos < capacity
    # dump slot e*capacity catches dropped entries; sliced off below
    dest = jnp.where(keep, fe * capacity + pos, e * capacity)

    # slot -> entry: each kept entry owns a unique slot, so one int
    # scatter inverts the map.  The dump slot e*capacity is IN range of
    # the +1-sized target (dropped entries legitimately land there,
    # last-writer-wins); the [:e*capacity] slice — not mode="drop" —
    # is what discards it.
    entry_of_slot = jnp.full((e * capacity + 1,), n, jnp.int32) \
        .at[dest].set(jnp.arange(n, dtype=jnp.int32),
                      mode="drop")[:e * capacity]
    svalid, ft_slot, gv_slot = _slot_views(entry_of_slot, ft, gvf, n)

    expert_in = _gather_dispatch(x, ft_slot, svalid, dest, keep)
    expert_out = ffn(expert_in.reshape(e, capacity, m))
    flat = expert_out.reshape(e * capacity, m)
    return _gather_combine(flat, gvf, ft, ft_slot, gv_slot, svalid, dest,
                           keep, jnp.zeros((s,), jnp.int8))


def grouped_dispatch_ffn(x, idx, gv, e, w1, b1, w2, b2, gated=False,
                         use_kernel=None):
    """DROPLESS dispatch + grouped expert FFN (megablocks-style; the
    reference's fused_moe/CUTLASS-grouped-GEMM analog).

    Tokens counting-sort into a TILE-aligned buffer: each expert's rows
    round up to the 128-row tile, so every row tile belongs to one
    expert and ``ops.pallas.grouped_ffn`` computes both expert GEMMs
    fused with the expert selected per tile.  No capacity factor, no
    dropped tokens; padding waste <= E*127 rows.

    x [S, M]; idx/gv [S, K]; w1 [E, M, F(*2)]; w2 [E, F, M].
    Returns y [S, M].
    """
    from ..ops.pallas.grouped_ffn import (TILE, _INTERPRET, grouped_ffn,
                                          grouped_ffn_xla)

    s, m = x.shape
    n, fe, ft, gvf, pos, counts = _count_rank(idx, gv, e, x.dtype)
    padded = -(-counts // TILE) * TILE
    off = jnp.cumsum(padded) - padded      # tile-aligned expert starts
    r = (-(-n // TILE) + e) * TILE         # static row bound

    dest = (off[fe] + pos).astype(jnp.int32)   # dropless: always kept
    entry_of_slot = jnp.full((r,), n, jnp.int32) \
        .at[dest].set(jnp.arange(n, dtype=jnp.int32))
    svalid, ft_slot, gv_slot = _slot_views(entry_of_slot, ft, gvf, n)
    keep = jnp.ones((n,), bool)

    # tile -> expert: experts own contiguous tile runs starting at off
    tile_starts = jnp.arange(r // TILE, dtype=jnp.int32) * TILE
    emap = jnp.clip(
        jnp.searchsorted(off, tile_starts, side="right") - 1, 0, e - 1)

    x_buf = _gather_dispatch(x, ft_slot, svalid, dest, keep)
    if use_kernel is None:
        # the kernel lowers via Mosaic: TPU (or interpret mode) only
        use_kernel = _INTERPRET or jax.default_backend() == "tpu"
    fn = grouped_ffn if use_kernel else grouped_ffn_xla
    out = fn(x_buf, w1, b1, w2, b2, emap, gated)
    return _gather_combine(out, gvf, ft, ft_slot, gv_slot, svalid, dest,
                           keep, jnp.zeros((s,), jnp.int8))


def moe_dispatch_combine(x, gate_w, w1, b1, w2, b2, *, top_k=2,
                         capacity_factor=1.25, activation=jax.nn.gelu,
                         mesh=None, ep_axis="ep", train=True,
                         noise_key=None, dispatch_mode="sort"):
    """Full MoE FFN over flat tokens.

    x: [S, M]; gate_w: [M, E]; w1: [E, M, F]; b1: [E, F]; w2: [E, F, M];
    b2: [E, M].  Returns (y [S, M], aux_loss).

    dispatch_mode "sort" (default) routes tokens with a stable sort +
    scatter/gather — O(S*K*M) data movement; "dense" keeps the GShard
    one-hot einsum formulation ([S, E, C] transient) as the reference
    implementation the equivalence tests compare against.

    With `mesh` given and `ep_axis` in it, expert-stacked tensors get
    Shard(0) constraints over ep: XLA lowers the dispatch movement to
    the all-to-all the reference codes as global_scatter/global_gather.
    """
    logits = x @ gate_w.astype(x.dtype)
    s, e = logits.shape
    cap = _capacity(s, top_k, capacity_factor, e, None)
    ep_sharded = mesh is not None and ep_axis in mesh.axis_names

    def constrain(t):
        if ep_sharded:
            spec = P(ep_axis, *([None] * (t.ndim - 1)))
            return jax.lax.with_sharding_constraint(
                t, NamedSharding(mesh, spec))
        return t

    def ffn(expert_in):
        expert_in = constrain(expert_in)
        h = activation(jnp.einsum("ecm,emf->ecf", expert_in, w1)
                       + b1[:, None, :])
        return constrain(jnp.einsum("ecf,efm->ecm", h, w2)
                         + b2[:, None, :])

    if dispatch_mode == "sort":
        idx, gv, aux = _topk_choices(logits, top_k, train, noise_key)
        y = sort_dispatch_combine(x, idx, gv, e, cap, ffn)
        return y, aux.astype(jnp.float32)
    if dispatch_mode == "grouped":
        # dropless tile-aligned grouped GEMM (no capacity, no drops);
        # single-device formulation — the per-tile expert gather inside
        # the kernel cannot cross ep shards
        if ep_sharded:
            raise NotImplementedError(
                "dispatch_mode='grouped' is single-device; use 'sort' "
                "under an ep-sharded mesh")
        if activation is not jax.nn.silu:
            raise NotImplementedError(
                "the grouped kernel implements the silu FFN "
                "(gated=True for swiglu via grouped_dispatch_ffn)")
        idx, gv, aux = _topk_choices(logits, top_k, train, noise_key)
        y = grouped_dispatch_ffn(x, idx, gv, e, w1, b1, w2, b2)
        return y, aux.astype(jnp.float32)
    if dispatch_mode != "dense":
        raise ValueError(f"dispatch_mode must be 'sort', 'grouped' or "
                         f"'dense', got {dispatch_mode!r}")

    combine, dispatch, aux = top_k_gating(
        logits, top_k=top_k, capacity_factor=capacity_factor,
        capacity=cap, train=train, noise_key=noise_key)
    combine = combine.astype(x.dtype)
    # dispatch: [S, E, C] x [S, M] -> [E, C, M]  (the global_scatter);
    # boolean mask — gate scaling happens only on the combine side
    expert_in = jnp.einsum("sec,sm->ecm", dispatch.astype(x.dtype), x)
    expert_out = ffn(expert_in)
    # combine back: the global_gather
    y = jnp.einsum("sec,ecm->sm", combine, expert_out)
    return y, aux.astype(jnp.float32)
