"""Activation recomputation.

Reference: fleet/recompute/recompute.py (+ recompute_hybrid.py) — a PyLayer
that reruns forward under saved RNG state during backward.  TPU-native:
`jax.checkpoint` on the pure stage function; under the compiled train step
XLA rematerializes instead of storing.  RNG correctness comes from the
trace-key design (framework/random.py): the folded per-call keys are pure
functions of the traced key, so the recomputed forward reproduces dropout
masks by construction — no RNG state tracker needed.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.tree_util import tree_flatten, tree_unflatten

from ...framework.tensor import Tensor
from ...autograd import tape
from ...ops.registry import _tangent_dtype

__all__ = ["recompute", "recompute_sequential"]


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              **kwargs):
    """Checkpoint `function(*args)`: store only inputs, recompute
    activations in backward."""
    from ...nn.layer import Layer

    layer = function if isinstance(function, Layer) else None
    if layer is None:
        bound = getattr(function, "__self__", None)
        layer = bound if isinstance(bound, Layer) else None
    if layer is None:
        layer = getattr(function, "_recompute_layer", None)
    if layer is None and not isinstance(function, Layer):
        # Closure over unknown parameters: rematerialization would silently
        # drop their grads (tape can't see through the closure). Run the
        # function on the tape directly — correct grads, no remat.
        return function(*args, **kwargs)

    flat, treedef = tree_flatten((args, kwargs),
                                 is_leaf=lambda x: isinstance(x, Tensor))
    t_idx = [i for i, x in enumerate(flat) if isinstance(x, Tensor)]
    tensors = [flat[i] for i in t_idx]
    params = {k: p for k, p in layer.named_parameters()} if layer else {}
    diff_params = {k: p for k, p in params.items() if not p.stop_gradient}

    def pure(param_arrays, *tensor_arrays):
        with tape.no_grad():
            if layer is not None:
                saved = layer.functional_state()
                merged = dict(saved)
                merged.update(param_arrays)
                layer.load_functional_state(merged)
            try:
                flat2 = list(flat)
                for i, a in zip(t_idx, tensor_arrays):
                    flat2[i] = Tensor(a, stop_gradient=True)
                a2, k2 = tree_unflatten(treedef, flat2)
                out = function(*a2, **k2)
                out_flat, out_tree = tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                return [o._data if isinstance(o, Tensor) else o
                        for o in out_flat], out_tree
            finally:
                if layer is not None:
                    layer.load_functional_state(saved)

    out_tree_box = []

    def pure_arrays(param_arrays, *tensor_arrays):
        outs, out_tree = pure(param_arrays, *tensor_arrays)
        if not out_tree_box:
            out_tree_box.append(out_tree)
        return outs

    ckpt = jax.checkpoint(pure_arrays)

    record = tape.is_grad_enabled() and (
        bool(diff_params) or any(not t.stop_gradient for t in tensors))
    param_arrays = {k: p._data for k, p in diff_params.items()}
    tensor_arrays = [t._data for t in tensors]

    if not record:
        outs = pure_arrays(param_arrays, *tensor_arrays)
        return _wrap_recompute(outs, out_tree_box[0], None)

    diff_tensors = [t for t in tensors if not t.stop_gradient]
    diff_pos = [j for j, t in enumerate(tensors) if not t.stop_gradient]

    def closed(p, *diff_arrays):
        ta = list(tensor_arrays)
        for pos, a in zip(diff_pos, diff_arrays):
            ta[pos] = a
        return ckpt(p, *ta)

    outs, raw_vjp = jax.vjp(closed, param_arrays,
                            *[t._data for t in diff_tensors])
    out_avals = [jax.ShapeDtypeStruct(np.shape(a), _tangent_dtype(a))
                 for a in outs]
    inputs = list(diff_params.values()) + diff_tensors

    def vjp_fn(flat_cots):
        pgrads, *agrads = raw_vjp(list(flat_cots))
        return tuple([pgrads[k] for k in diff_params] + list(agrads))

    node = tape.GradNode("recompute", vjp_fn, inputs, out_avals)
    return _wrap_recompute(outs, out_tree_box[0], node)


def _wrap_recompute(outs, out_tree, node):
    wrapped = []
    for i, a in enumerate(outs):
        diff = node is not None and _tangent_dtype(a) != jax.dtypes.float0
        t = Tensor(a, stop_gradient=not diff)
        if diff:
            t._grad_node = node
            t._out_index = i
        wrapped.append(t)
    return tree_unflatten(out_tree, wrapped)


def recompute_sequential(ctx, functions, *args):
    """reference: recompute over a Sequential in chunks.  Each chunk is
    wrapped in a throwaway Sequential sharing the sublayers so the tape
    sees its parameters as checkpoint inputs."""
    from ...nn.layer_common import Sequential
    from ...nn.layer import Layer

    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    n = len(funcs)
    per = max(1, n // segments)
    x = args[0] if len(args) == 1 else args
    i = 0
    while i < n:
        chunk = funcs[i:i + per]
        if all(isinstance(f, Layer) for f in chunk):
            x = recompute(Sequential(*chunk), x)
        else:
            for f in chunk:
                x = f(x)
        i += per
    return x
