"""HybridParallelOptimizer + grad scaler.

Reference: fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:266 — wraps the inner optimizer, fuses the
DP/SEP gradient allreduce (:520) and makes grad clip topology-aware.  Under
GSPMD the gradient reduction is emitted by XLA (replicated params +
dp-sharded batch), and the global-norm clip already reduces over the full
(global) arrays — so the wrapper's job collapses to API fidelity + making
sure clipping happens before the inner step.
"""
from __future__ import annotations

from ...autograd import no_grad

__all__ = ["HybridParallelOptimizer", "HybridParallelGradScaler"]


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self.inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    @property
    def _parameter_list(self):
        return self.inner_opt._parameter_list

    @property
    def _grad_clip(self):
        return self.inner_opt._grad_clip

    def get_lr(self):
        return self.inner_opt.get_lr()

    def set_lr(self, v):
        self.inner_opt.set_lr(v)

    @no_grad()
    def step(self):
        # grads of replicated params are already globally reduced (GSPMD);
        # inner step applies clip + update
        self.inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self.inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss)

    def state_dict(self):
        return self.inner_opt.state_dict()

    def set_state_dict(self, state):
        return self.inner_opt.set_state_dict(state)

    def opt_state(self):
        return self.inner_opt.opt_state()

    def load_opt_state(self, s):
        return self.inner_opt.load_opt_state(s)

    def __getattr__(self, name):
        return getattr(self.__dict__["inner_opt"], name)


class HybridParallelGradScaler:
    def __init__(self, scaler, hcg=None):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self.__dict__["_scaler"], name)

    def scale(self, var):
        return self._scaler.scale(var)

    def minimize(self, optimizer, scaled_loss):
        return self._scaler.minimize(optimizer, scaled_loss)
