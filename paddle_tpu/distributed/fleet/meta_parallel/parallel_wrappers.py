"""Meta-parallel model wrappers.

Reference: python/paddle/distributed/fleet/meta_parallel/
{tensor_parallel,sharding_parallel,segment_parallel}.py — in the reference
these broadcast parameters across the relevant groups at construction and
sync grads after backward.  Under single-controller SPMD both jobs move into
GSPMD: parameters are globally consistent by construction, and gradient
reduction is emitted by XLA from the sharding layout.  The wrappers keep the
reference API (model attribute passthrough) and apply the input-batch
sharding for their axis.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ....nn.layer import Layer
from ....framework.tensor import Tensor
from ...mesh import get_mesh

__all__ = ["MetaParallelBase", "TensorParallel", "ShardingParallel",
           "SegmentParallel", "DataParallel"]


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self.add_sublayer("_layers", layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


def _shard_batch(args, axis):
    """Shard arg batch dims over a mesh axis (input pipeline contract)."""
    m = get_mesh()
    if m is None or axis not in m.dim_names:
        return args
    out = []
    for a in args:
        if isinstance(a, Tensor) and a.ndim > 0 and \
                a._data.shape[0] % m.get_dim_size(axis) == 0:
            sh = NamedSharding(m.jax_mesh,
                               PartitionSpec(axis, *([None] * (a.ndim - 1))))
            t = Tensor(jax.device_put(a._data, sh),
                       stop_gradient=a.stop_gradient)
            out.append(t)
        else:
            out.append(a)
    return tuple(out)


class DataParallel(MetaParallelBase):
    """paddle.DataParallel (reference python/paddle/distributed/parallel.py):
    grads sync by construction under GSPMD (replicated params + dp-sharded
    batch → XLA emits the gradient psum over dp)."""

    def __init__(self, layers, hcg=None, strategy=None,
                 comm_buffer_size=25, last_comm_buffer_size=1,
                 find_unused_parameters=False, group=None):
        super().__init__(layers, hcg, strategy)
        self._axis = "dp"

    def forward(self, *args, **kwargs):
        args = _shard_batch(args, self._axis)
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    @property
    def need_dp(self):
        m = get_mesh()
        return m is not None and "dp" in m.dim_names and \
            m.get_dim_size("dp") > 1


class TensorParallel(MetaParallelBase):
    def forward(self, *args, **kwargs):
        args = _shard_batch(args, "dp")
        return self._layers(*args, **kwargs)


class ShardingParallel(MetaParallelBase):
    def forward(self, *args, **kwargs):
        args = _shard_batch(args, "sharding")
        return self._layers(*args, **kwargs)


class SegmentParallel(MetaParallelBase):
    """reference segment_parallel.py:26 — shards the sequence dim over the
    sep axis."""

    def forward(self, *args, **kwargs):
        m = get_mesh()
        if m is None or "sep" not in m.dim_names:
            return self._layers(*args, **kwargs)
        out = []
        for a in args:
            if isinstance(a, Tensor) and a.ndim >= 2 and \
                    a._data.shape[1] % m.get_dim_size("sep") == 0:
                sh = NamedSharding(
                    m.jax_mesh,
                    PartitionSpec(None, "sep", *([None] * (a.ndim - 2))))
                out.append(Tensor(jax.device_put(a._data, sh),
                                  stop_gradient=a.stop_gradient))
            else:
                out.append(a)
        return self._layers(*out, **kwargs)
