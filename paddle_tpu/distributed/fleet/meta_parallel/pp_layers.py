"""PipelineLayer: stage-partitioned model description.

Reference: fleet/meta_parallel/pp_layers.py — LayerDesc:92,
PipelineLayer:56, SegmentLayers:257.  The description API is kept; the
execution strategy differs: homogeneous middle blocks are pipelined via
distributed.pipelining.spmd_pipeline (weights stacked over the pp axis),
pre/post segments run replicated.
"""
from __future__ import annotations

import numpy as np

from ....nn.layer import Layer
from ....nn.layer_common import LayerList, Sequential

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "SegmentLayers"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({getattr(self.layer_func, '__name__', '?')})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference pp_layers.py:257 — split N layers into num_parts stages."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self):
        n = len(self.layers_desc)
        if self.method.startswith("layer:"):
            # cut at layers whose class name matches
            name = self.method.split(":", 1)[1]
            match_idx = [i for i, d in enumerate(self.layers_desc)
                         if _desc_name(d) == name]
            if len(match_idx) >= self.num_parts:
                per = len(match_idx) // self.num_parts
                cuts = [0]
                for p in range(1, self.num_parts):
                    cuts.append(match_idx[p * per])
                cuts.append(n)
                return cuts
        # uniform
        base = n // self.num_parts
        extra = n % self.num_parts
        cuts = [0]
        for i in range(self.num_parts):
            cuts.append(cuts[-1] + base + (1 if i >= self.num_parts - extra
                                           else 0))
        return cuts


def _desc_name(d):
    if isinstance(d, LayerDesc):
        return getattr(d.layer_func, "__name__", "")
    return type(d).__name__


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self._num_virtual = int(num_virtual_pipeline_stages or 1)
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval
        self._layers_desc = list(layers)

        # build all layers (single-controller: whole model lives here; the
        # pp *placement* happens at compile time via stacked stage params)
        built = []
        self._shared = {}
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = d.build_layer()
                built.append((self._shared[d.layer_name], d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif callable(d) and not isinstance(d, Layer):
                built.append((d, None))
            else:
                built.append((d, None))
        self.run_function = []
        layer_list = LayerList()
        for i, (l, ffn) in enumerate(built):
            if isinstance(l, Layer):
                layer_list.append(l)
                if ffn is not None:
                    shared = l
                    self.run_function.append(
                        lambda x, _f=ffn, _l=shared: _f(_l, x))
                else:
                    self.run_function.append(l)
            else:
                self.run_function.append(l)
        self.layers = layer_list

        # VPP segments the model into num_stages * num_virtual parts;
        # device s owns chunks {c*S+s} (reference interleaved assignment
        # pipeline_parallel.py:1174, pp_layers _get_virtual segmentation)
        cuts = SegmentLayers(self._layers_desc,
                             self._num_stages * self._num_virtual,
                             seg_method).do_segment()
        self.segment_parts = cuts

    def get_num_stages(self):
        return self._num_stages

    def get_num_virtual_stages(self):
        return self._num_virtual

    def get_stage_from_index(self, idx):
        # VPP: segment k = chunk (k // S) resident on device k % S
        # (interleaved assignment); v=1 reduces to the plain mapping
        nseg = self._num_stages * self._num_virtual
        for k in range(nseg):
            if self.segment_parts[k] <= idx < self.segment_parts[k + 1]:
                return k % self._num_stages
        return self._num_stages - 1

    def forward(self, x):
        """Replicated sequential semantics (numerically identical to the
        pipelined execution; PipelineParallel compiles the pipelined
        version)."""
        for fn in self.run_function:
            x = fn(x)
        return x

    def loss(self, out, label):
        if self._loss_fn is None:
            return out
        return self._loss_fn(out, label)
