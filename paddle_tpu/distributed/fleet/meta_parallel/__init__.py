from .parallel_wrappers import MetaParallelBase, TensorParallel, \
    ShardingParallel, SegmentParallel, DataParallel
from .pipeline_parallel import PipelineParallel
from .pp_layers import PipelineLayer, LayerDesc, SharedLayerDesc

__all__ = ["MetaParallelBase", "TensorParallel", "ShardingParallel",
           "SegmentParallel", "DataParallel", "PipelineParallel",
           "PipelineLayer", "LayerDesc", "SharedLayerDesc"]
