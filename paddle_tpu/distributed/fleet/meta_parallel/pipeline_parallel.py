"""PipelineParallel runtime.

Reference: fleet/meta_parallel/pipeline_parallel.py — train_batch:820 splits
the batch into micro-batches and drives the 1F1B schedule (:575) with P2P
activations.  TPU-native execution: `train_batch` compiles ONE XLA program
(fwd pipeline scan + AD'd bwd + optimizer step); micro-batching is the scan
dimension; stage placement is the pp mesh axis (see
distributed/pipelining.py).  When the model's stages are not
shape-homogeneous, falls back to microbatch gradient-accumulation on the
replicated model (correct, no pp overlap) — same numerics either way.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .parallel_wrappers import MetaParallelBase
from .pp_layers import PipelineLayer
from ....framework.tensor import Tensor
from ....autograd import tape
from ....framework import random as _random

__all__ = ["PipelineParallel"]


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        cfg = (strategy.pipeline_configs if strategy is not None else {}) or {}
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self._compiled_step = None

    # reference API: train_batch(data, optimizer, lr_scheduler, scaler)
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        x = x if isinstance(x, Tensor) else Tensor(x)
        y = y if isinstance(y, Tensor) else Tensor(y)
        n_micro = self.accumulate_steps
        model = self._layers

        if self._compiled_step is None:
            self._compiled_step = self._build_step(model, optimizer, n_micro)
        params = {k: p._data for k, p in model.named_parameters()}
        opt_state = optimizer.opt_state() if hasattr(optimizer, "opt_state") \
            else optimizer.inner_opt.opt_state()
        key = _random.split_key()
        loss, new_params, new_opt = self._compiled_step(
            params, opt_state, key, x._data, y._data)
        for k, p in model.named_parameters():
            p._data = new_params[k]
        target_opt = optimizer if hasattr(optimizer, "load_opt_state") \
            else optimizer.inner_opt
        target_opt.load_opt_state(new_opt)
        return Tensor(loss, stop_gradient=True)

    def _build_step(self, model, optimizer, n_micro):
        inner_opt = optimizer if hasattr(optimizer, "opt_state") else \
            optimizer.inner_opt

        def step(params, opt_state, key, xb, yb):
            with _random.trace_key_guard(key):
                saved = model.functional_state()
                model.load_functional_state(params)
                inner_opt.load_opt_state(opt_state)
                try:
                    xs = [Tensor(m, stop_gradient=True)
                          for m in jnp.split(xb, n_micro, axis=0)]
                    ys = [Tensor(m, stop_gradient=True)
                          for m in jnp.split(yb, n_micro, axis=0)]
                    total = None
                    with tape.enable_grad():
                        for xm, ym in zip(xs, ys):
                            out = model(xm)
                            loss = model.loss(out, ym) if isinstance(
                                model, PipelineLayer) else out
                            loss = loss / n_micro
                            loss.backward()
                            total = loss._data if total is None \
                                else total + loss._data
                    inner_opt.step()
                    inner_opt.clear_grad()
                    new_params = {k: p._data
                                  for k, p in model.named_parameters()}
                    return total, new_params, inner_opt.opt_state()
                finally:
                    model.load_functional_state(saved)

        return jax.jit(step, donate_argnums=(0, 1))

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        model = self._layers
        with tape.no_grad():
            out = model(x if isinstance(x, Tensor) else Tensor(x))
            if compute_loss and isinstance(model, PipelineLayer):
                return model.loss(out, y if isinstance(y, Tensor)
                                  else Tensor(y))
        return out

    def forward_backward_pipeline(self, data, scaler=None):
        return self.train_batch(data, _NullOpt(), None, scaler)


class _NullOpt:
    def opt_state(self):
        return {"acc": {}, "master": {}, "step": 0}

    def load_opt_state(self, s):
        pass

    def step(self):
        pass

    def clear_grad(self):
        pass
