"""PipelineParallel runtime.

Reference: fleet/meta_parallel/pipeline_parallel.py — train_batch:820 splits
the batch into micro-batches and drives the 1F1B schedule (:575) with P2P
activations.  TPU-native execution: `train_batch` compiles ONE XLA program
that runs the hand-scheduled 1F1B engine
(distributed/pipeline_schedules.pipeline_1f1b_hetero) over the 'pp' mesh
axis — the PipelineLayer's segments become per-stage `lax.switch`
branches, activations/cotangents hop stages via ppermute, and each
microbatch's backward starts as soon as its forward leaves the pipe.

Requirements for the pipelined path (checked at compile time):
  * a hybrid topology with pp axis size > 1, and the model is a
    PipelineLayer whose stage count equals the pp size;
  * every non-final segment emits one activation of a single common
    shape/dtype (the ring payload).  Stage 0 may consume arbitrary input;
    the final segment runs inside the loss head on the last device.
When a model does not satisfy this, train_batch falls back to microbatch
gradient-accumulation on the replicated model (correct numerics, no
pipeline overlap) and says so once via warnings.warn.
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

from .parallel_wrappers import MetaParallelBase
from .pp_layers import PipelineLayer
from ...pipeline_schedules import pipeline_1f1b_hetero
from ....framework.tensor import Tensor
from ....autograd import tape
from ....framework import random as _random

__all__ = ["PipelineParallel"]


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        cfg = (strategy.pipeline_configs if strategy is not None else {}) or {}
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self._compiled_step = None

    # reference API: train_batch(data, optimizer, lr_scheduler, scaler)
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        x, y = data
        x = x if isinstance(x, Tensor) else Tensor(x)
        y = y if isinstance(y, Tensor) else Tensor(y)
        n_micro = self.accumulate_steps
        model = self._layers

        if self._compiled_step is None:
            self._compiled_step = self._build_step(model, optimizer, n_micro)
        params = {k: p._data for k, p in model.named_parameters()}
        opt_state = optimizer.opt_state() if hasattr(optimizer, "opt_state") \
            else optimizer.inner_opt.opt_state()
        key = _random.split_key()
        loss, new_params, new_opt = self._compiled_step(
            params, opt_state, key, x._data, y._data)
        for k, p in model.named_parameters():
            p._data = new_params[k]
        target_opt = optimizer if hasattr(optimizer, "load_opt_state") \
            else optimizer.inner_opt
        target_opt.load_opt_state(new_opt)
        return Tensor(loss, stop_gradient=True)

    # ---- pipelined path -------------------------------------------------
    def _pp_mesh(self):
        hcg = self._hcg
        if hcg is None:
            return None
        pm = hcg.mesh() if callable(hcg.mesh) else hcg.mesh
        mesh = getattr(pm, "jax_mesh", pm)
        return mesh if "pp" in mesh.axis_names and mesh.shape["pp"] > 1 \
            else None

    def _segment_fns(self, model, n_micro, mesh, xb):
        """Per-stage branch fns over the functional param dict, with the
        final segment folded into the loss head.  Returns (stage_fns,
        last_fn) or None if the stages can't form a homogeneous ring."""
        S = mesh.shape["pp"]
        if not isinstance(model, PipelineLayer) or \
                model.get_num_stages() != S:
            return None
        v = getattr(model, "get_num_virtual_stages", lambda: 1)()
        if v > 1 and n_micro % S != 0:
            self._fallback_reason = (
                f"interleaved VPP needs n_micro % pp == 0, got "
                f"{n_micro} % {S}")
            return None
        nseg = S * v
        cuts = model.segment_parts

        def seg_run(p, h, lo, hi):
            saved = model.functional_state()
            model.load_functional_state(p)
            try:
                with tape.no_grad():
                    for fn in model.run_function[lo:hi]:
                        h = fn(h)
            finally:
                model.load_functional_state(saved)
            return h

        def make_stage(idx):
            lo, hi = cuts[idx], cuts[idx + 1]

            def branch(p, x, aux_j):
                h = Tensor(aux_j["x"], stop_gradient=True) if idx == 0 \
                    else Tensor(x, stop_gradient=True)
                return seg_run(p, h, lo, hi)._data

            return branch

        def identity_stage(p, x, aux_j):
            return x

        def last_fn(p, y, aux_j):
            out = seg_run(p, Tensor(y, stop_gradient=True),
                          cuts[nseg - 1], cuts[nseg])
            loss = model.loss(out, Tensor(aux_j["y"], stop_gradient=True))
            return loss._data / n_micro

        # ring homogeneity probe (abstract eval only): stages 0..S-2 must
        # emit one common activation shape/dtype.  Probe failures are
        # recorded so the fallback warning names the real cause instead
        # of masking a genuine model bug.
        params = {k: p._data for k, p in model.named_parameters()}
        mb_shape = (xb.shape[0] // n_micro,) + tuple(xb.shape[1:])
        try:
            h = jax.eval_shape(
                lambda p, a: make_stage(0)(p, None, {"x": a, "y": None}),
                params, jax.ShapeDtypeStruct(mb_shape, xb.dtype))
            shapes = {(h.shape, h.dtype)}
            for i in range(1, nseg - 1):
                h = jax.eval_shape(
                    lambda p, x, _i=i: make_stage(_i)(p, x, None),
                    params, h)
                shapes.add((h.shape, h.dtype))
            if len(shapes) != 1:
                self._fallback_reason = (
                    f"stage activations differ: {sorted(map(str, shapes))}")
                return None
        except Exception as e:
            self._fallback_reason = (
                f"stage probe raised {type(e).__name__}: {e}")
            return None

        stage_fns = [make_stage(i) for i in range(nseg - 1)] \
            + [identity_stage]
        return stage_fns, last_fn, v

    def _build_step(self, model, optimizer, n_micro):
        inner_opt = optimizer if hasattr(optimizer, "opt_state") else \
            optimizer.inner_opt
        mesh = self._pp_mesh()

        def accum_step(params, opt_state, key, xb, yb):
            """Fallback: sequential microbatch grad-accumulation."""
            with _random.trace_key_guard(key):
                saved = model.functional_state()
                model.load_functional_state(params)
                inner_opt.load_opt_state(opt_state)
                try:
                    xs = [Tensor(m_, stop_gradient=True)
                          for m_ in jnp.split(xb, n_micro, axis=0)]
                    ys = [Tensor(m_, stop_gradient=True)
                          for m_ in jnp.split(yb, n_micro, axis=0)]
                    total = None
                    with tape.enable_grad():
                        for xm, ym in zip(xs, ys):
                            out = model(xm)
                            loss = model.loss(out, ym) if isinstance(
                                model, PipelineLayer) else out
                            loss = loss / n_micro
                            loss.backward()
                            total = loss._data if total is None \
                                else total + loss._data
                    inner_opt.step()
                    inner_opt.clear_grad()
                    new_params = {k: p._data
                                  for k, p in model.named_parameters()}
                    return total, new_params, inner_opt.opt_state()
                finally:
                    model.load_functional_state(saved)

        def make_pipelined(stage_fns, last_fn, n_virtual=1):
            def step(params, opt_state, key, xb, yb):
                with _random.trace_key_guard(key):
                    saved = model.functional_state()
                    inner_opt.load_opt_state(opt_state)
                    try:
                        aux = {
                            "x": xb.reshape(
                                (n_micro, xb.shape[0] // n_micro)
                                + xb.shape[1:]),
                            "y": yb.reshape(
                                (n_micro, yb.shape[0] // n_micro)
                                + yb.shape[1:]),
                        }
                        loss, grads = pipeline_1f1b_hetero(
                            stage_fns, last_fn, params, aux, mesh,
                            n_virtual=n_virtual)
                        model.load_functional_state(params)
                        named = dict(model.named_parameters())
                        with tape.no_grad():
                            for k, p in named.items():
                                if not p.stop_gradient:
                                    p._grad = Tensor(grads[k],
                                                     stop_gradient=True)
                            inner_opt.step()
                            inner_opt.clear_grad()
                        new_params = {k: p._data for k, p in named.items()}
                        return loss, new_params, inner_opt.opt_state()
                    finally:
                        model.load_functional_state(saved)

            return step

        def compile_for(xb):
            if mesh is not None:
                self._fallback_reason = \
                    "model is not a PipelineLayer with pp-many stages"
                built = self._segment_fns(model, n_micro, mesh, xb)
                if built is not None:
                    return make_pipelined(*built)
                warnings.warn(
                    "PipelineLayer can't use the 1F1B pipeline engine "
                    f"({self._fallback_reason}); train_batch falls back "
                    "to gradient accumulation without pipeline overlap")
            return accum_step

        compiled = {}

        def dispatch(params, opt_state, key, xb, yb):
            sig = (xb.shape, str(xb.dtype), yb.shape, str(yb.dtype))
            if sig not in compiled:
                compiled[sig] = jax.jit(compile_for(xb),
                                        donate_argnums=(0, 1))
            return compiled[sig](params, opt_state, key, xb, yb)

        return dispatch

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        model = self._layers
        with tape.no_grad():
            out = model(x if isinstance(x, Tensor) else Tensor(x))
            if compute_loss and isinstance(model, PipelineLayer):
                return model.loss(out, y if isinstance(y, Tensor)
                                  else Tensor(y))
        return out

    def forward_backward_pipeline(self, data, scaler=None):
        return self.train_batch(data, _NullOpt(), None, scaler)


class _NullOpt:
    def opt_state(self):
        return {"acc": {}, "master": {}, "step": 0}

    def load_opt_state(self, s):
        pass

    def step(self):
        pass

    def clear_grad(self):
        pass
