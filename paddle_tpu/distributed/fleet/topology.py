"""Hybrid-parallel topology.

Reference: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology:70 (N-d rank grid ordered pp→mp→sep→sharding→dp) and
HybridCommunicateGroup:189 (per-axis comm groups).  TPU-native: the rank
grid IS a jax Mesh with axes named after the parallel strategies; a "comm
group" is the axis name; XLA routes each axis's collectives over the right
ICI dimension.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ..mesh import ProcessMesh, set_mesh
from ..collective import Group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("pipe", "data", "sharding", "sep",
                                           "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world = int(np.prod(dims))
        self._grid = np.arange(self._world).reshape(dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coords = [kwargs[n] for n in self._parallel_names]
        return int(self._grid[tuple(coords)])

    def get_coord(self, rank):
        idx = np.argwhere(self._grid == rank)[0]
        import collections
        Coord = collections.namedtuple("Coord", self._parallel_names)
        return Coord(*[int(i) for i in idx])

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[axis] = index
        return sorted(int(r) for r in self._grid[tuple(sl)].reshape(-1))

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._grid, axis, -1)
        return [sorted(int(x) for x in row)
                for row in moved.reshape(-1, self._dims[axis])]

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)._asdict()
        coord.update(kwargs)
        return self.get_rank(**coord)


class HybridCommunicateGroup:
    """reference topology.py:189 — builds per-axis groups and a device mesh.

    Axis order pp→sep→sharding→dp→mp matches the reference's placement of
    model parallel innermost (fastest-varying ICI dimension), which keeps
    TP collectives on the shortest links.
    """

    def __init__(self, topology=None, *, dp_degree=1, mp_degree=1,
                 pp_degree=1, sharding_degree=1, sep_degree=1, order=None):
        if topology is not None:
            names = topology.get_hybrid_group_names()
            get = {n: topology.get_dim(n) for n in names}
            pp_degree = get.get("pipe", 1)
            dp_degree = get.get("data", 1)
            sharding_degree = get.get("sharding", 1)
            sep_degree = get.get("sep", 1)
            mp_degree = get.get("model", 1)
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sep_degree = sep_degree
        self._topo = topology or CommunicateTopology(
            ("pipe", "data", "sharding", "sep", "model"),
            (pp_degree, dp_degree, sharding_degree, sep_degree, mp_degree))

        n_needed = (dp_degree * mp_degree * pp_degree * sharding_degree *
                    sep_degree)
        devs = jax.devices()
        if n_needed > len(devs):
            raise ValueError(
                f"hybrid topology needs {n_needed} devices, have {len(devs)}")
        grid = np.asarray(devs[:n_needed]).reshape(
            pp_degree, sep_degree, sharding_degree, dp_degree, mp_degree)
        self._mesh = ProcessMesh(Mesh(grid, ("pp", "sep", "sharding", "dp",
                                             "mp")))
        set_mesh(self._mesh)

        self._dp_group = Group(("dp",), self._mesh, gid=101)
        self._mp_group = Group(("mp",), self._mesh, gid=102)
        self._pp_group = Group(("pp",), self._mesh, gid=103)
        self._sharding_group = Group(("sharding",), self._mesh, gid=104)
        self._sep_group = Group(("sep",), self._mesh, gid=105)
        # fused groups (reference creates dp+sep fused allreduce group)
        self._dp_sep_group = Group(("dp", "sep"), self._mesh, gid=106)
        self._check_group = Group(tuple(self._mesh.dim_names), self._mesh,
                                  gid=107)

    # --- mesh access (TPU-native addition) ---
    @property
    def mesh(self) -> ProcessMesh:
        return self._mesh

    def topology(self):
        return self._topo

    # --- parallel mode info (reference API) ---
    def get_parallel_mode(self):
        from .base import ParallelMode
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._mp_degree > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._sep_degree > 1:
            return ParallelMode.SEGMENT_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    # single-controller SPMD: host rank is 0; in-graph rank = axis_index
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_sep_parallel_rank(self):
        return 0

    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_dp_sep_parallel_group(self):
        return self._dp_sep_group

    def get_check_parallel_group(self, *a):
        return self._check_group

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    @property
    def global_rank(self):
        return 0

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(0, pipe=stage_id)
