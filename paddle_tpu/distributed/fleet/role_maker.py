"""PS-era role makers + data generators (compat surface).

Reference: python/paddle/distributed/fleet/base/role_maker.py:395
(PaddleCloudRoleMaker reads the PADDLE_* cluster env the launcher
exports; UserDefinedRoleMaker takes an explicit server/worker layout)
and data_generator/data_generator.py (line-protocol generators feeding
the PS InMemoryDataset).  TPU formulation: roles map onto the jax
distributed process grid (distributed/launcher rendezvous) and the PS
tables live in distributed/ps.py; these classes keep the reference API
so recommendation-stack scripts run.
"""
from __future__ import annotations

import os
import sys

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator", "UtilBase"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """reference role_maker.py:395 — role/rank/size from the launcher's
    PADDLE_* environment (our launcher exports the same names)."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._kwargs = kwargs
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._size = len(eps.split(",")) if eps else int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._servers = [s for s in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if s]
        self._role = (Role.SERVER
                      if os.environ.get("TRAINING_ROLE", "TRAINER")
                      .upper() == "PSERVER" else Role.WORKER)

    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _worker_index(self):
        return self._rank

    def _worker_num(self):
        return self._size

    def _server_num(self):
        return len(self._servers)

    def _get_pserver_endpoints(self):
        return list(self._servers)

    def _role_id(self):
        return self._rank

    def _node_num(self):
        return max(1, self._size)

    def to_string(self):
        return (f"role={self._role} rank={self._rank} "
                f"workers={self._size} servers={len(self._servers)}")


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference role_maker.py UserDefinedRoleMaker: explicit layout."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective)
        self._kwargs = kwargs
        self._rank = int(kwargs.get("current_id", 0))
        self._role = kwargs.get("role", Role.WORKER)
        self._size = int(kwargs.get("worker_num", 1))
        self._servers = list(kwargs.get("server_endpoints", []))


class UtilBase:
    """reference fleet/base/util_factory.py surface: small collective
    helpers over the active communication group."""

    def __init__(self, role_maker=None):
        self._role_maker = role_maker

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np
        from .. import collective

        ops = {"sum": collective.ReduceOp.SUM,
               "max": collective.ReduceOp.MAX,
               "min": collective.ReduceOp.MIN}
        if mode not in ops:
            raise ValueError(
                f"all_reduce mode must be one of {sorted(ops)}, "
                f"got {mode!r}")
        try:
            import paddle_tpu as paddle
            t = paddle.to_tensor(np.asarray(input))
            collective.all_reduce(t, op=ops[mode])
            return np.asarray(t.numpy())
        except Exception as e:
            # a swallowed failure here silently returns the UN-reduced
            # local value — every rank then proceeds with a different
            # number, which is far worse than failing
            raise RuntimeError(
                f"fleet util all_reduce(mode={mode!r}, "
                f"comm_world={comm_world!r}) failed: {e}") from e

    def barrier(self, comm_world="worker"):
        from .. import collective
        try:
            collective.barrier()
        except Exception:
            pass

    def get_file_shard(self, files):
        rm = self._role_maker or PaddleCloudRoleMaker()
        n, i = rm._worker_num(), rm._worker_index()
        return files[i::n]

    def print_on_rank(self, message, rank_id=0):
        rm = self._role_maker or PaddleCloudRoleMaker()
        if rm._worker_index() == rank_id:
            print(message)


class DataGenerator:
    """reference data_generator.py:25 — subclasses implement
    generate_sample(line) yielding (slot_name, values) pairs; run_from_
    stdin/memory emit the PS line protocol."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(line) -> iterator of "
            "[(slot_name, values), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            gen = self.generate_sample(line)
            if gen is None:
                continue
            for record in gen():
                sys.stdout.write(self._gen_str(record))

    def run_from_memory(self):
        out = []
        batch = []
        for sample in self.generate_sample(None)():
            batch.append(sample)
            if len(batch) == self.batch_size_:
                for r in self.generate_batch(batch)():
                    out.append(self._gen_str(r))
                batch = []
        if batch:
            for r in self.generate_batch(batch)():
                out.append(self._gen_str(r))
        for s in out:
            sys.stdout.write(s)


class MultiSlotDataGenerator(DataGenerator):
    """reference data_generator.py:285: 'slot:n v0 ... vn-1 ...' lines."""

    def _gen_str(self, line):
        parts = []
        for name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line):
        parts = []
        for name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
