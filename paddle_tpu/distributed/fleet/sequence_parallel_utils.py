"""Sequence parallelism (Megatron-SP) utilities.

Reference: fleet/utils/sequence_parallel_utils.py — ScatterOp/GatherOp/
AllGatherOp/ReduceScatterOp PyLayers (:85-137) and
ColumnSequenceParallelLinear (:429).  TPU-native: the scatter/gather pair is
a pair of sharding annotations on the sequence dim over the mp axis; GSPMD
turns the transitions into reduce-scatter / all-gather on ICI, including
the reversed collectives in backward — identical comm volume to the
reference's hand-placed ops.

Layout contract: activations are [batch, seq, hidden] (batch-first,
matching this framework's layers; the reference uses [s, b, h]).
"""
from __future__ import annotations

from ...nn.layer import Layer
from ...nn import functional as F
from ..shard_ops import sharding_constraint
from ..mesh import get_mesh

__all__ = ["scatter", "all_gather", "identity_in_model_parallel",
           "mark_as_sequence_parallel_parameter",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "GatherOp", "ScatterOp", "AllGatherOp", "ReduceScatterOp",
           "register_sequence_parallel_allreduce_hooks"]


def _axis():
    m = get_mesh()
    if m is not None and "mp" in m.dim_names:
        return "mp"
    return None


def scatter(x, axis=None):
    """Split the sequence dim across mp (reference ScatterOp)."""
    a = axis or _axis()
    if a is None:
        return x
    return sharding_constraint(x, (None, a) + (None,) * (x.ndim - 2))


def all_gather(x, axis=None):
    """Gather the sequence dim (reference GatherOp/AllGatherOp)."""
    a = axis or _axis()
    if a is None:
        return x
    return sharding_constraint(x, (None,) * x.ndim)


class ScatterOp:
    apply = staticmethod(scatter)


class GatherOp:
    apply = staticmethod(all_gather)


class AllGatherOp:
    apply = staticmethod(all_gather)


class ReduceScatterOp:
    apply = staticmethod(scatter)


def identity_in_model_parallel(x):
    return x


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, *a, **k):
    """Grad sync for SP params is emitted by GSPMD — kept for API parity."""
    return model


class ColumnSequenceParallelLinear(Layer):
    """reference :429 — input arrives sequence-sharded, all-gather then
    column-parallel matmul (annotation-driven here)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        from .mp_layers import _shard_param
        self._axis = _axis()
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        _shard_param(self.weight, 1, self._axis)
        self.bias = None if has_bias is False else self.create_parameter(
            [out_features], is_bias=True)
        if self.bias is not None:
            _shard_param(self.bias, 0, self._axis)
        self.gather_output = gather_output

    def forward(self, x):
        # sequence-sharded in → gather seq, shard hidden out
        x = all_gather(x)
        out = F.linear(x, self.weight, self.bias)
        if self._axis is not None and not self.gather_output:
            out = sharding_constraint(
                out, (None,) * (out.ndim - 1) + (self._axis,))
        return out


class RowSequenceParallelLinear(Layer):
    """Row-parallel matmul whose output reduce-scatters over the sequence
    dim (reference RowSequenceParallelLinear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        from .mp_layers import _shard_param
        self._axis = _axis()
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        _shard_param(self.weight, 0, self._axis)
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None

    def forward(self, x):
        if self._axis is not None:
            x = sharding_constraint(
                x, (None,) * (x.ndim - 1) + (self._axis,))
        out = F.linear(x, self.weight, None)
        out = scatter(out)  # reduce-scatter over sequence
        if self.bias is not None:
            out = out + self.bias
        return out
