"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py
(ElasticManager:125 — etcd registration with TTL leases, scale in/out
watch, ELASTIC_EXIT_CODE=101 signalling the launcher to relaunch).

TPU formulation: the KV substrate is the framework TCPStore (csrc/
tcp_store.cc) instead of etcd; ranks enroll with heartbeats, the manager
detects missing heartbeats or world-size changes, and signals the
launcher via the same dedicated exit code.  On TPU pods the coordinator
restart + dist-checkpoint resume path replaces per-rank NCCL rebuild.
"""
from __future__ import annotations

import os
import threading
import time

ELASTIC_EXIT_CODE = 101
ELASTIC_TTL = 60


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, store=None, job_id=None, np=None, ttl=ELASTIC_TTL,
                 heartbeat_interval=3):
        from ..store import create_or_get_global_tcp_store

        self.store = store if store is not None else \
            create_or_get_global_tcp_store()
        self.job_id = job_id or os.getenv("PADDLE_ELASTIC_JOB_ID",
                                          "default")
        self.np = int(np or os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self.rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self.ttl = ttl
        self.interval = heartbeat_interval
        self._stop = threading.Event()
        self._thread = None
        self.enrolled = False

    # ---------------------------------------------------------- enrol
    def _key(self, rank):
        return f"/elastic/{self.job_id}/{rank}"

    def enroll(self):
        self.store.set(self._key(self.rank), str(time.time()))
        self.enrolled = True

    def start_heartbeat(self):
        self.enroll()

        def beat():
            while not self._stop.wait(self.interval):
                self.store.set(self._key(self.rank), str(time.time()))

        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    # ---------------------------------------------------------- watch
    def alive_ranks(self):
        now = time.time()
        alive = []
        for r in range(self.np):
            try:
                ts = float(self.store.get(self._key(r)))
            except Exception:
                continue
            # cross-process comparison: heartbeats are written by OTHER
            # hosts, so wall clock is the only shared timebase here
            # tpu-lint: disable=wall-clock-duration
            if now - ts <= self.ttl:
                alive.append(r)
        return alive

    def health_check(self):
        """ElasticStatus for the current gang (reference:
        manager.py watch loop)."""
        alive = self.alive_ranks()
        if len(alive) == self.np:
            return ElasticStatus.COMPLETED if self._stop.is_set() else \
                ElasticStatus.HOLD
        if len(alive) == 0:
            return ElasticStatus.EXIT
        return ElasticStatus.RESTART

    def exit_for_restart(self):
        """Signal the launcher to relaunch this gang."""
        os._exit(ELASTIC_EXIT_CODE)
