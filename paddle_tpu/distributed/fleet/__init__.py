"""paddle.distributed.fleet (reference: python/paddle/distributed/fleet)."""
from .base import fleet, init, DistributedStrategy, ParallelMode, \
    get_hybrid_communicate_group, Fleet
from .topology import CommunicateTopology, HybridCommunicateGroup
from . import mp_layers
from .mp_layers import VocabParallelEmbedding, ColumnParallelLinear, \
    RowParallelLinear, ParallelCrossEntropy
from . import meta_parallel
from .hybrid_optimizer import HybridParallelOptimizer, \
    HybridParallelGradScaler
from .recompute import recompute, recompute_sequential
from . import sequence_parallel_utils
from . import elastic
from .elastic import ElasticManager

# top-level fleet API shape
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker

from .role_maker import (Role, PaddleCloudRoleMaker,  # noqa: F401
                         UserDefinedRoleMaker, UtilBase, DataGenerator,
                         MultiSlotDataGenerator,
                         MultiSlotStringDataGenerator)

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "UtilBase", "DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator",
           "fleet", "init", "DistributedStrategy", "ParallelMode",
           "CommunicateTopology", "HybridCommunicateGroup",
           "VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "meta_parallel",
           "HybridParallelOptimizer", "HybridParallelGradScaler",
           "recompute", "recompute_sequential", "distributed_model",
           "elastic", "ElasticManager",
           "distributed_optimizer", "get_hybrid_communicate_group"]
