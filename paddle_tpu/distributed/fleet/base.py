"""Fleet basics (reference: python/paddle/distributed/fleet/fleet.py:218
fleet.init, base/distributed_strategy.py, meta_parallel ParallelMode)."""
from __future__ import annotations

__all__ = ["ParallelMode", "DistributedStrategy", "Fleet", "fleet",
           "init", "get_hybrid_communicate_group"]


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class DistributedStrategy:
    """reference: paddle/fluid/framework/distributed_strategy.proto:364 —
    strategy toggles; the hybrid_configs dict carries parallel degrees."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
            "mp_configs": {}, "pp_configs": {}, "sharding_configs": {},
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


_hcg = None
_fleet_initialized = False
_strategy = None


class Fleet:
    """Singleton facade (reference fleet/fleet.py Fleet)."""

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        global _hcg, _fleet_initialized, _strategy
        from .topology import HybridCommunicateGroup
        strategy = strategy or DistributedStrategy()
        _strategy = strategy
        hc = strategy.hybrid_configs
        _hcg = HybridCommunicateGroup(
            dp_degree=hc.get("dp_degree", 1),
            mp_degree=hc.get("mp_degree", 1),
            pp_degree=hc.get("pp_degree", 1),
            sharding_degree=hc.get("sharding_degree", 1),
            sep_degree=hc.get("sep_degree", 1))
        _fleet_initialized = True
        from .. import env
        env._initialized = True
        return self

    def is_first_worker(self):
        from .. import env
        return env.get_rank() == 0

    def worker_index(self):
        from .. import env
        return env.get_rank()

    def worker_num(self):
        from .. import env
        return env.get_world_size()

    def get_hybrid_communicate_group(self):
        return _hcg

    @property
    def strategy(self):
        return _strategy

    def distributed_model(self, model):
        """Wrap per parallel mode (reference fleet/model.py:32)."""
        from .meta_parallel import TensorParallel, PipelineParallel, \
            ShardingParallel, SegmentParallel
        from .meta_parallel.pp_layers import PipelineLayer
        if _hcg is None:
            return model
        mode = _hcg.get_parallel_mode()
        if mode == ParallelMode.PIPELINE_PARALLEL or \
                isinstance(model, PipelineLayer):
            return PipelineParallel(model, _hcg, _strategy)
        if mode == ParallelMode.TENSOR_PARALLEL:
            return TensorParallel(model, _hcg, _strategy)
        if mode == ParallelMode.SHARDING_PARALLEL:
            return ShardingParallel(model, _hcg, _strategy)
        if mode == ParallelMode.SEGMENT_PARALLEL:
            return SegmentParallel(model, _hcg, _strategy)
        # pure DP: batch-sharded inputs under GSPMD need no wrapper
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        from .hybrid_optimizer import HybridParallelOptimizer
        if _hcg is None:
            return optimizer
        return HybridParallelOptimizer(optimizer, _hcg,
                                       strategy or _strategy)


fleet = Fleet()
init = fleet.init


def get_hybrid_communicate_group():
    return _hcg
