"""Tensor-parallel layers.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:49, ColumnParallelLinear:336, RowParallelLinear:543,
ParallelCrossEntropy:744.  The reference splits weights per rank and calls
explicit identity/allreduce/allgather PyLayers (mp_ops.py).  TPU-native:
weights are GLOBAL arrays with a NamedSharding over the 'mp' axis;
activations carry sharding constraints; GSPMD inserts the collectives
(forward allreduce for row-parallel, backward allreduce for
column-parallel) — same math, compiler-placed comms on ICI.
"""
from __future__ import annotations

import numpy as np

from ...nn.layer import Layer
from ...nn import functional as F
from ...nn.initializer import XavierUniform, Constant, Normal
from ..mesh import get_mesh, ProcessMesh
from ..placement import Shard, Replicate
from ..auto_parallel.api import shard_tensor
from ..shard_ops import sharding_constraint

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_axis(mp_group):
    if mp_group is not None and mp_group.axis_names:
        return mp_group.axis_names[0]
    m = get_mesh()
    if m is not None and "mp" in m.dim_names:
        return "mp"
    return None


def _mesh():
    return get_mesh()


def _shard_param(p, dim, axis):
    """Give parameter a sharded placement along `axis` at tensor dim."""
    m = _mesh()
    if m is None or axis is None:
        return p
    placements = [Replicate()] * len(m.dim_names)
    placements[m.dim_names.index(axis)] = Shard(dim)
    sharded = shard_tensor(p, m, placements)
    p._data = sharded._data
    return p


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        _shard_param(self.weight, 0, self._axis)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        if self._axis is not None:
            out = sharding_constraint(out, (None,) * out.ndim)  # replicated
        return out


class ColumnParallelLinear(Layer):
    """W: [in, out] sharded on out (columns).  gather_output=False leaves
    activations sharded on the last dim over mp (feeding RowParallel)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self.gather_output = gather_output
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        _shard_param(self.weight, 1, self._axis)
        if has_bias is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_features], is_bias=True)
            if self.bias is not None:
                _shard_param(self.bias, 0, self._axis)

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self._axis is not None:
            if self.gather_output:
                out = sharding_constraint(out, (None,) * out.ndim)
            else:
                out = sharding_constraint(
                    out, (None,) * (out.ndim - 1) + (self._axis,))
        return out


class RowParallelLinear(Layer):
    """W: [in, out] sharded on in (rows); input arrives sharded on last dim;
    GSPMD inserts the forward allreduce on the partial matmul result."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self.input_is_parallel = input_is_parallel
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        _shard_param(self.weight, 0, self._axis)
        self.bias = self.create_parameter([out_features], is_bias=True) \
            if has_bias else None

    def forward(self, x):
        if self._axis is not None and self.input_is_parallel:
            x = sharding_constraint(
                x, (None,) * (x.ndim - 1) + (self._axis,))
        out = F.linear(x, self.weight, None)
        if self._axis is not None:
            out = sharding_constraint(out, (None,) * out.ndim)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits (reference mp_layers.py:744 →
    c_softmax_with_cross_entropy kernel).  GSPMD partitions the logsumexp
    reduction over the sharded class dim into a psum over mp."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        if self._axis is not None:
            input = sharding_constraint(
                input, (None,) * (input.ndim - 1) + (self._axis,))
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
