"""paddle.distributed.io (reference: python/paddle/distributed/io.py —
persistables save/load for static distributed programs; here thin wrappers
over the framework state-dict IO)."""
from __future__ import annotations

import os

__all__ = ["save_persistables", "load_persistables",
           "save_inference_model_distributed", "is_persistable"]


def is_persistable(var):
    return getattr(var, "persistable", True)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Save all parameters of a static Program (reference io.py
    save_persistables)."""
    from ..framework.io import save
    if main_program is None:
        from ..static import default_main_program
        main_program = default_main_program()
    state = {p.name: p for p in main_program.all_parameters()}
    os.makedirs(dirname, exist_ok=True)
    save(state, os.path.join(dirname, filename or "__params__.pdparams"))


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..framework.io import load
    if main_program is None:
        from ..static import default_main_program
        main_program = default_main_program()
    state = load(os.path.join(dirname, filename or "__params__.pdparams"))
    for p in main_program.all_parameters():
        if p.name in state:
            val = state[p.name]
            p.set_value(val)


def save_inference_model_distributed(dirname, feeded_var_names,
                                     target_vars, executor,
                                     main_program=None, **kwargs):
    from ..static import save_inference_model, default_main_program
    prog = main_program or default_main_program()
    feed_vars = [prog.vars[n] if isinstance(n, str) else n
                 for n in feeded_var_names]
    path = os.path.join(dirname, "model")
    return save_inference_model(path, feed_vars, target_vars, executor)
