"""Sharding-annotation ops, tape-differentiable.

The TPU replacement for the reference's identity/allreduce PyLayers
(fleet/layers/mpu/mp_ops.py): instead of inserting explicit collectives,
layers annotate the sharding they want and GSPMD inserts the collective in
both forward and backward.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..ops.registry import op
from .mesh import get_mesh

__all__ = ["sharding_constraint", "annotate"]


@op
def sharding_constraint(x, spec_entries, mesh=None):
    """Constrain x's sharding to PartitionSpec(*spec_entries) on the mesh.

    spec_entries: tuple like (None, 'mp') — hashable/static.
    """
    m = mesh or (get_mesh().jax_mesh if get_mesh() is not None else None)
    if m is None:
        return x
    spec = PartitionSpec(*spec_entries)
    return jax.lax.with_sharding_constraint(x, NamedSharding(m, spec))


def annotate(tensor, *entries):
    """Convenience: annotate(t, None, 'mp')."""
    return sharding_constraint(tensor, tuple(entries))
