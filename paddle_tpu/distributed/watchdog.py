"""Communication watchdog (reference: paddle/phi/core/distributed/
comm_task_manager.h:37 CommTaskManager + nccl_comm_task.cc — an async loop
that detects hung/errored NCCL collectives and aborts with diagnostics).

TPU formulation: XLA collectives can't error mid-flight the way NCCL ring
ops can, but a *hung* collective (peer died, coordination service wedged)
blocks the Python thread on a device fetch forever.  The watchdog is a
host-side monitor: collectives register a CommTask around the blocking
region; a daemon thread flags tasks that exceed their timeout, logs every
in-flight task, and (optionally) aborts the process so the elastic
launcher's exit-code path can relaunch (fleet/elastic.py)."""
from __future__ import annotations

import os
import threading
import time

from .. import observability as _obs

__all__ = ["CommTask", "CommTaskManager", "get_comm_task_manager",
           "comm_guard"]

_M_TASKS = _obs.counter(
    "comm_tasks_total", "communication tasks registered with the watchdog")
_M_IN_FLIGHT = _obs.gauge(
    "comm_tasks_in_flight", "comm tasks currently inside their blocking "
    "region")
_M_FLAGGED = _obs.gauge(
    "comm_hung_tasks", "comm tasks currently flagged as hung (exceeded "
    "timeout, not yet finished)")
_M_HANGS = _obs.counter(
    "comm_hangs_total", "comm tasks that ever exceeded their timeout",
    ("name",))


class CommTask:
    """One in-flight communication op (reference nccl_comm_task.cc
    NCCLCommTask)."""

    __slots__ = ("name", "group", "start_time", "timeout", "done",
                 "flagged", "seq")

    def __init__(self, name, group=None, timeout=None, seq=0):
        self.name = name
        self.group = group
        self.start_time = time.monotonic()
        self.timeout = timeout
        self.done = False
        self.flagged = False
        self.seq = seq

    def elapsed(self):
        return time.monotonic() - self.start_time

    def __repr__(self):
        state = "done" if self.done else (
            "HUNG" if self.flagged else "in-flight")
        return (f"CommTask(#{self.seq} {self.name} group={self.group} "
                f"{self.elapsed():.1f}s {state})")


class CommTaskManager:
    """Registry + monitor loop (reference comm_task_manager.h:55
    CommTaskLoop).  Default timeout from FLAGS or
    PADDLE_COMM_TIMEOUT_SECONDS (the reference reads the process-group
    timeout); abort-on-hang mirrors FLAGS_enable_async_trace's abort
    path via the elastic exit code so the launcher relaunches."""

    ELASTIC_EXIT_CODE = 101  # fleet/elastic/manager.py contract

    def __init__(self, default_timeout=None, abort_on_hang=False,
                 poll_interval=5.0):
        # None = resolve per-task from env/flag at start_task time, so
        # paddle.set_flags({"FLAGS_comm_timeout_seconds": ...}) applies
        # to a manager that already exists
        self._default_timeout = default_timeout
        self.abort_on_hang = abort_on_hang
        self.poll_interval = poll_interval
        self._tasks: dict[int, CommTask] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self._thread = None
        self._stop = threading.Event()
        self._hang_hooks = []

    @property
    def default_timeout(self):
        if self._default_timeout is not None:
            return self._default_timeout
        env = os.environ.get("PADDLE_COMM_TIMEOUT_SECONDS")
        if env:
            return float(env)
        try:
            from ..flags import FLAGS
            return float(FLAGS.get("FLAGS_comm_timeout_seconds", 1800.0))
        except Exception:   # pragma: no cover — flags always importable
            return 1800.0

    @default_timeout.setter
    def default_timeout(self, v):
        self._default_timeout = v

    # ------------------------------------------------------------ tasks
    def start_task(self, name, group=None, timeout=None):
        with self._lock:
            self._seq += 1
            task = CommTask(name, group,
                            timeout if timeout is not None
                            else self.default_timeout, self._seq)
            self._tasks[task.seq] = task
            _M_TASKS.inc()
            _M_IN_FLIGHT.set(len(self._tasks))
        self._ensure_thread()
        return task

    def end_task(self, task):
        task.done = True
        with self._lock:
            self._tasks.pop(task.seq, None)
            _M_IN_FLIGHT.set(len(self._tasks))
            _M_FLAGGED.set(sum(1 for t in self._tasks.values()
                               if t.flagged))

    def flagged_count(self):
        """Number of currently in-flight tasks flagged as hung."""
        with self._lock:
            return sum(1 for t in self._tasks.values() if t.flagged)

    def in_flight(self):
        with self._lock:
            return list(self._tasks.values())

    def register_hang_hook(self, fn):
        """fn(task) called (once per task) when a task exceeds its
        timeout."""
        self._hang_hooks.append(fn)

    # ------------------------------------------------------------- loop
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="comm-watchdog", daemon=True)
            self._thread.start()

    def _loop(self):
        import logging
        log = logging.getLogger("paddle_tpu.comm_watchdog")
        while not self._stop.wait(self.poll_interval):
            hung = []
            with self._lock:
                if not self._tasks:
                    continue
                for task in self._tasks.values():
                    if (not task.done and not task.flagged
                            and task.timeout
                            and task.elapsed() > task.timeout):
                        task.flagged = True
                        hung.append(task)
                if hung:
                    _M_FLAGGED.set(sum(1 for t in self._tasks.values()
                                       if t.flagged))
            for task in hung:
                _M_HANGS.labels(task.name).inc()
                log.error(
                    "comm watchdog: %r exceeded its %.0fs timeout; "
                    "in-flight tasks: %r", task, task.timeout,
                    self.in_flight())
                for hook in self._hang_hooks:
                    try:
                        hook(task)
                    except Exception:   # noqa: BLE001 — keep watching
                        log.exception("hang hook failed")
                if self.abort_on_hang:
                    log.error("comm watchdog: aborting with elastic exit "
                              "code %d", self.ELASTIC_EXIT_CODE)
                    os._exit(self.ELASTIC_EXIT_CODE)

    def shutdown(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_interval)
            self._thread = None


_manager = None
_manager_lock = threading.Lock()


def get_comm_task_manager():
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = CommTaskManager()
        return _manager


class comm_guard:
    """Context manager wrapping a (potentially blocking) collective:
        with comm_guard("all_reduce", group):
            arr.block_until_ready()
    """

    def __init__(self, name, group=None, timeout=None):
        self._name = name
        self._group = group
        self._timeout = timeout
        self._task = None

    def __enter__(self):
        self._task = get_comm_task_manager().start_task(
            self._name, self._group, self._timeout)
        return self._task

    def __exit__(self, *exc):
        get_comm_task_manager().end_task(self._task)
        return False
