"""paddle.distributed namespace completion (reference:
python/paddle/distributed/__init__.py exports): object collectives,
process-group introspection, spawn, auto-parallel Strategy/DistModel/
to_static, PS-era dataset/entry configs."""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor

__all__ = [
    "ReduceType", "Strategy", "DistAttr", "DistModel", "to_static",
    "alltoall_single", "gather", "broadcast_object_list",
    "scatter_object_list", "destroy_process_group", "get_backend",
    "is_available", "gloo_init_parallel_env", "gloo_barrier",
    "gloo_release", "spawn", "split", "dtensor_from_fn", "shard_dataloader",
    "shard_scaler", "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry",
]


class ReduceType:
    """Reduce kinds for dist.reshard Partial placements (reference
    paddle/phi/core/distributed/auto_parallel/dist_attr.h ReduceType)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class Strategy:
    """Auto-parallel strategy bundle (reference
    distributed/auto_parallel/strategy.py): config groups are attribute
    namespaces with an `enable` toggle."""

    class _Config:
        def __init__(self, **defaults):
            self.__dict__.update(defaults)

    def __init__(self, config=None):
        config = config or {}

        def cfg(key, **defaults):
            return Strategy._Config(**{**defaults, **config.get(key, {})})

        self.sharding = cfg("sharding", enable=False, stage=1, degree=8)
        self.fused_passes = cfg("fused_passes", enable=False, fused_ops=[])
        self.gradient_merge = cfg("gradient_merge", enable=False, k_steps=1,
                                  avg=True)
        self.pipeline = cfg("pipeline", enable=False, schedule_mode="1F1B",
                            micro_batch_size=1, accumulate_steps=1)
        self.amp = cfg("amp", enable=False, dtype="bfloat16", level="O1")
        self.recompute = cfg("recompute", enable=False)


class DistAttr:
    """Tensor distributed attribute: mesh + per-dim placements (reference
    paddle/phi/core/distributed/auto_parallel/dist_attr.h TensorDistAttr)."""

    def __init__(self, mesh, sharding_specs):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs)

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"sharding_specs={self.sharding_specs})")


class DistModel:
    """Compiled driver over a sharded model (reference
    distributed/auto_parallel/api.py:2110 DistModel over Engine +
    static/engine.py's partition/plan pipeline).

    The d2s bridge, TPU-native: calling the DistModel compiles ONE XLA
    program per mode+signature — forward, loss, backward and optimizer
    update fused — whose distribution GSPMD plans from the parameters'
    and inputs' shardings (shard_layer/shard_tensor placements flow
    straight into the compiled step; the reference's completion/
    partitioner/cost-model pipeline is the compiler's job here).
    Parameters keep their mesh placements across steps."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self.network = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._mode = "train" if optimizer is not None else "predict"
        self._train_step = None
        self._eval_jit = {}

    def train(self):
        self._mode = "train"
        self.network.train()

    def eval(self):
        self._mode = "eval"
        self.network.eval()

    def predict(self):
        self._mode = "predict"
        self.network.eval()

    def _compiled_train(self):
        if self._train_step is None:
            from ..jit.functional import TrainStep
            loss_fn = self._loss

            def step_loss(m, *batch):
                return loss_fn(m(*batch[:-1]), batch[-1])

            self._train_step = TrainStep(self.network, self._optimizer,
                                         step_loss)
        return self._train_step

    def _compiled_eval(self, args):
        """Cached jitted forward(+loss) over the functional state."""
        import jax
        from ..framework.tensor import Tensor
        from ..framework import random as _random
        from ..jit.functional import _as_arrays

        arrays = _as_arrays(args)
        sig = (self._mode, tuple(
            (tuple(a.shape), str(a.dtype))
            for a in jax.tree_util.tree_leaves(arrays)))
        fn = self._eval_jit.get(sig)
        if fn is None:
            model, loss_fn, mode = self.network, self._loss, self._mode

            @jax.jit
            def run(state, key, *batch):
                with _random.trace_key_guard(key):
                    saved = model.functional_state()
                    model.load_functional_state(state)
                    try:
                        ins = jax.tree_util.tree_map(
                            lambda a: Tensor(a, stop_gradient=True),
                            list(batch))
                        # train-without-optimizer and eval both return
                        # the loss; predict returns the raw outputs
                        if mode != "predict" and loss_fn is not None:
                            out = loss_fn(model(*ins[:-1]),
                                          ins[-1])._data
                        else:
                            out = jax.tree_util.tree_map(
                                lambda t: t._data, model(*ins),
                                is_leaf=lambda t: isinstance(t, Tensor))
                        # buffer mutations (BN running stats in train
                        # mode) must survive the jit boundary
                        new_bufs = {k: v for k, v in
                                    model.functional_state().items()
                                    if k.startswith("buffers.")}
                        return out, new_bufs
                    finally:
                        model.load_functional_state(saved)

            fn = self._eval_jit[sig] = run
        state = dict(self.network.functional_state())
        out, new_bufs = fn(state, _random.split_key(), *arrays)
        self.network.load_functional_state(new_bufs)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out)

    def __call__(self, *args):
        if self._mode == "train" and self._optimizer is not None \
                and self._loss is not None:
            return self._compiled_train()(*args)
        return self._compiled_eval(args)

    def state_dict(self, mode="all"):
        state = dict(self.network.state_dict())
        if mode in ("all", "opt") and self._optimizer is not None:
            state.update({f"opt.{k}": v for k, v in
                          self._optimizer.state_dict().items()})
        return state

    def set_state_dict(self, state):
        opt_state = {k[4:]: v for k, v in state.items()
                     if k.startswith("opt.")}
        net_state = {k: v for k, v in state.items()
                     if not k.startswith("opt.")}
        self.network.set_state_dict(net_state)
        if opt_state and self._optimizer is not None:
            self._optimizer.set_state_dict(opt_state)

    def dist_main_program(self, mode=None):
        raise NotImplementedError(
            "there is no per-rank Program artifact: the jitted SPMD step "
            "is the compiled form (export via paddle.jit.save / StableHLO)")


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """dist.to_static (reference auto_parallel/api.py:2693): wrap a sharded
    layer into a DistModel driver."""
    return DistModel(layer, loader, loss, optimizer, strategy)


# --------------------------------------------------------- collectives etc.

def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all (reference communication/all_to_all.py
    alltoall_single).  Single-process groups: identity copy."""
    from .collective import all_to_all
    n = 1 if group is None else max(len(getattr(group, "ranks", [0])), 1)
    if n <= 1:
        out_tensor._data = (in_tensor._data if isinstance(in_tensor, Tensor)
                            else jnp.asarray(in_tensor))
        return out_tensor
    chunks = jnp.split(in_tensor._data, n, axis=0)
    gathered = all_to_all([Tensor(c) for c in chunks], group=group)
    out_tensor._data = jnp.concatenate(
        [g._data for g in gathered], axis=0)
    return out_tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather tensors to dst (reference communication/gather.py).  Over a
    mesh this is all_gather + keep-on-dst."""
    from .collective import all_gather
    from .env import get_rank
    tensors = []
    all_gather(tensors, tensor, group=group)
    if get_rank() == dst and gather_list is not None:
        gather_list.extend(tensors)
    return gather_list


def broadcast_object_list(object_list, src=0, group=None):
    """(reference communication/broadcast.py broadcast_object_list);
    single-process group: already consistent."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    from .env import get_rank, get_world_size
    if in_object_list:
        n = max(get_world_size(), 1)
        per = max(len(in_object_list) // n, 1)
        r = get_rank()
        out_object_list.extend(in_object_list[r * per:(r + 1) * per])
    return out_object_list


def destroy_process_group(group=None):
    """(reference distributed/collective.py destroy_process_group)"""
    from . import collective as _c
    if group is None:
        _c._groups.clear()
        _c._default_group = None
    else:
        _c._groups.pop(getattr(group, "id", None), None)
    return None


def get_backend(group=None):
    return "XCCL_TPU" if jax.default_backend() == "tpu" else "GLOO"


def is_available():
    return True


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-barrier env (reference parallel.py gloo_init_parallel_env) —
    the coordination-service TCPStore plays gloo's role."""
    from .store import create_or_get_global_tcp_store
    create_or_get_global_tcp_store()


def gloo_barrier():
    from .collective import barrier
    barrier()


def gloo_release():
    return None


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Launch ``func`` on nprocs local worker processes (reference
    distributed/spawn.py).  Workers rendezvous through the same
    env-variable contract as distributed.launch."""
    import multiprocessing as mp

    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_WORLD_SIZE", 1)) or 1
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_WORLD_SIZE": str(nprocs)}
        p = ctx.Process(target=_spawn_worker,
                        args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawned workers failed: exitcodes {bad}")
    return procs


def _spawn_worker(func, args, env):
    os.environ.update(env)
    func(*args)


def split(x, size, num_partitions=1, operation="linear", axis=0,
          gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """Model-parallel split op (reference distributed/collective.py split):
    builds the corresponding fleet mp layer over the current mesh."""
    from .fleet import mp_layers as _mp
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = _mp.ColumnParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False, gather_output=gather_out)
        else:
            layer = _mp.RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                input_is_parallel=False)
        return layer(x)
    if operation == "embedding":
        vocab, dim = size
        layer = _mp.VocabParallelEmbedding(vocab, dim,
                                           weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unknown operation {operation!r}")


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """(reference auto_parallel/api.py dtensor_from_fn): run a creation fn
    then shard the result."""
    from .auto_parallel.api import shard_tensor
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def shard_dataloader(dataloader, meshes, shard_dims=None, input_keys=None):
    """(reference auto_parallel/api.py:3208): yield batches with tensors
    sharded over the mesh's data axis."""
    from .auto_parallel.api import shard_tensor
    from .placement import Shard, Replicate
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes

    class _ShardedLoader:
        def __init__(self, loader):
            self._loader = loader

        def __len__(self):
            return len(self._loader)

        def __iter__(self):
            for batch in self._loader:
                yield jax.tree_util.tree_map(
                    lambda t: shard_tensor(
                        t, mesh,
                        [Shard(0)] + [Replicate()] * 0) if isinstance(
                            t, Tensor) else t,
                    batch, is_leaf=lambda t: isinstance(t, Tensor))

    return _ShardedLoader(dataloader)


def shard_scaler(scaler):
    """(reference auto_parallel/api.py shard_scaler): our GradScaler's
    found-inf reduction already runs in the sharded step; pass-through."""
    return scaler


# ------------------------------------------------------ PS-era data configs

class _EntryBase:
    def __init__(self, *a):
        self._args = a


class CountFilterEntry(_EntryBase):
    """Sparse-feature admission by count (reference
    distributed/entry_attr.py)."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        super().__init__(count_filter)
        self.count_filter = count_filter


class ProbabilityEntry(_EntryBase):
    def __init__(self, probability):
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        super().__init__(probability)
        self.probability = probability


class ShowClickEntry(_EntryBase):
    def __init__(self, show_name, click_name):
        super().__init__(show_name, click_name)
        self.show_name = show_name
        self.click_name = click_name


def _ps_dataset_stub(name):
    class _Stub:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{name} belongs to the parameter-server data path "
                "(reference distributed/fleet/dataset); on TPU use "
                "paddle.io.DataLoader with the shm-ring workers")
    _Stub.__name__ = name
    return _Stub


InMemoryDataset = _ps_dataset_stub("InMemoryDataset")
QueueDataset = _ps_dataset_stub("QueueDataset")
