"""Process meshes.

Reference: phi::distributed::ProcessMesh
(paddle/phi/core/distributed/auto_parallel/process_mesh.h:34) + python
dist.ProcessMesh.  On TPU a ProcessMesh is a thin wrapper over
jax.sharding.Mesh: dim names are mesh axis names, and every sharding /
collective below rides XLA's GSPMD over ICI/DCN.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["ProcessMesh", "get_mesh", "set_mesh", "auto_mesh",
           "init_device_mesh"]

_global_mesh: "ProcessMesh | None" = None


class ProcessMesh:
    """N-d logical view over the device set (dim_names ↔ mesh axes)."""

    def __init__(self, mesh, dim_names=None, process_ids=None):
        if isinstance(mesh, Mesh):
            self._jax_mesh = mesh
            self._shape = tuple(mesh.devices.shape)
            self._dim_names = list(mesh.axis_names)
            return
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._shape = tuple(arr.shape)
        devices = np.asarray(jax.devices())
        flat = arr.reshape(-1)
        dev_grid = devices[flat].reshape(arr.shape)
        self._jax_mesh = Mesh(dev_grid, tuple(self._dim_names))

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return [d.id for d in self._jax_mesh.devices.reshape(-1)]

    @property
    def mesh(self):
        return np.asarray(
            [d.id for d in self._jax_mesh.devices.reshape(-1)]).reshape(
                self._shape)

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, process_id):
        axis = self._dim_names.index(dim) if isinstance(dim, str) else dim
        coords = np.argwhere(self.mesh == process_id)
        return int(coords[0][axis]) if len(coords) else -1

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            self._shape == other._shape and \
            self._dim_names == other._dim_names

    def __hash__(self):
        return hash((self._shape, tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    def __enter__(self):
        self._prev = _global_mesh
        set_mesh(self)
        return self

    def __exit__(self, *exc):
        set_mesh(self._prev)
        return False


def set_mesh(mesh):
    global _global_mesh
    if isinstance(mesh, Mesh):
        mesh = ProcessMesh(mesh)
    _global_mesh = mesh


def get_mesh() -> "ProcessMesh | None":
    return _global_mesh


def auto_mesh(**axis_sizes) -> ProcessMesh:
    """Build a mesh over all devices: auto_mesh(dp=2, mp=4)."""
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    n = len(jax.devices())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    devs = np.asarray(jax.devices()[:int(np.prod(sizes))]).reshape(sizes)
    m = ProcessMesh(Mesh(devs, tuple(names)))
    set_mesh(m)
    return m


def init_device_mesh(device_type=None, mesh_shape=(), mesh_dim_names=None):
    """torch/paddle-shaped mesh constructor."""
    sizes = dict(zip(mesh_dim_names or
                     [f"d{i}" for i in range(len(mesh_shape))], mesh_shape))
    return auto_mesh(**sizes)
