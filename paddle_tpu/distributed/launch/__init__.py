"""paddle.distributed.launch — multi-process launcher CLI.

Reference: python/paddle/distributed/launch/ (main.py CLI,
controllers/collective.py rank env + spawn, controllers/master.py KV
rendezvous, fleet/elastic/manager.py restart loop).

TPU formulation: per-process env carries BOTH the Paddle-shaped vars
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER) and the jax
coordination-service vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID) so `jax.distributed.initialize()` — the TPU analog of
ProcessGroup init over TCPStore — picks them up with no arguments.
Elastic = watch children, restart the gang on a failed rank
(ELASTIC_EXIT_CODE semantics from fleet/elastic/manager.py:33).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

ELASTIC_EXIT_CODE = 101
DEFAULT_MASTER = "127.0.0.1:8765"


def build_rank_env(rank, nprocs, master, base_env=None, device_ids=None):
    """Per-rank environment (reference: controllers/collective.py
    build_pod -> _get_entrypoint env assembly)."""
    env = dict(base_env if base_env is not None else os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_MASTER": master,
        "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{6170 + rank}",
        "PADDLE_TRAINER_ENDPOINTS": ",".join(
            f"127.0.0.1:{6170 + r}" for r in range(nprocs)),
        # jax coordination service (jax.distributed.initialize reads these)
        "JAX_COORDINATOR_ADDRESS": master,
        "JAX_NUM_PROCESSES": str(nprocs),
        "JAX_PROCESS_ID": str(rank),
        "FLAGS_selected_devices": str(
            device_ids[rank] if device_ids else rank),
    })
    return env


class Launcher:
    """Spawn + watch one local gang (reference: the launcher controller
    loop launch/controllers/controller.py)."""

    def __init__(self, cmd, nprocs, master=None, log_dir=None,
                 max_restarts=0, elastic=False, device_ids=None,
                 base_env=None):
        self.cmd = cmd
        self.nprocs = nprocs
        self.master = master or DEFAULT_MASTER
        self.log_dir = log_dir
        self.max_restarts = max_restarts
        self.elastic = elastic
        self.device_ids = device_ids
        self.base_env = base_env
        self.procs: list[subprocess.Popen] = []

    def _spawn(self):
        self.procs = []
        for rank in range(self.nprocs):
            env = build_rank_env(rank, self.nprocs, self.master,
                                 base_env=self.base_env,
                                 device_ids=self.device_ids)
            stdout = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                stdout = open(os.path.join(self.log_dir,
                                           f"workerlog.{rank}"), "w")
            p = subprocess.Popen(self.cmd, env=env, stdout=stdout,
                                 stderr=subprocess.STDOUT if stdout
                                 else None)
            p._rank = rank
            self.procs.append(p)

    def _kill_all(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    def run(self):
        restarts = 0
        while True:
            self._spawn()
            code = self._watch()
            if code == 0:
                return 0
            if (self.elastic or code == ELASTIC_EXIT_CODE) and \
                    restarts < self.max_restarts:
                restarts += 1
                print(f"[launch] rank failure (exit {code}); elastic "
                      f"restart {restarts}/{self.max_restarts}",
                      file=sys.stderr)
                continue
            return code

    def _watch(self):
        """Poll children; on any failure kill the gang (reference:
        watcher loop in launch/controllers/watcher.py)."""
        while True:
            alive = False
            for p in self.procs:
                code = p.poll()
                if code is None:
                    alive = True
                elif code != 0:
                    print(f"[launch] rank {p._rank} exited with {code}; "
                          "terminating gang", file=sys.stderr)
                    self._kill_all()
                    return code
            if not alive:
                return 0
            time.sleep(0.2)
