"""paddle.distributed.launch — multi-process launcher CLI.

Reference: python/paddle/distributed/launch/ (main.py CLI,
controllers/collective.py rank env + spawn, controllers/master.py KV
rendezvous, fleet/elastic/manager.py restart loop).

TPU formulation: per-process env carries BOTH the Paddle-shaped vars
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER) and the jax
coordination-service vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID) so `jax.distributed.initialize()` — the TPU analog of
ProcessGroup init over TCPStore — picks them up with no arguments.
Elastic = watch children, restart the gang on a failed rank
(ELASTIC_EXIT_CODE semantics from fleet/elastic/manager.py:33).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

ELASTIC_EXIT_CODE = 101
DEFAULT_MASTER = "127.0.0.1:8765"


def build_rank_env(rank, nprocs, master, base_env=None, device_ids=None,
                   rank_base=0, world=None, coordinator=None, node_ip=None,
                   endpoints=None):
    """Per-rank environment (reference: controllers/collective.py
    build_pod -> _get_entrypoint env assembly).

    rank is the LOCAL rank; with multi-node, rank_base/world carry the
    node's global offset and total process count, `coordinator` is the
    jax coordination-service address (always the --master host, where
    global rank 0 lives), and `endpoints` is the GLOBAL per-rank
    endpoint list (ports keyed by global rank so co-located nodes never
    collide)."""
    env = dict(base_env if base_env is not None else os.environ)
    world = world if world is not None else nprocs
    grank = rank_base + rank
    ip = node_ip or "127.0.0.1"
    if endpoints is None:
        endpoints = [f"{ip}:{6170 + g}" for g in range(world)]
    env.update({
        "PADDLE_TRAINER_ID": str(grank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(rank),
        "PADDLE_MASTER": master,
        "PADDLE_CURRENT_ENDPOINT": endpoints[grank],
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        # jax coordination service (jax.distributed.initialize reads these)
        "JAX_COORDINATOR_ADDRESS": coordinator or master,
        "JAX_NUM_PROCESSES": str(world),
        "JAX_PROCESS_ID": str(grank),
        "FLAGS_selected_devices": str(
            device_ids[rank] if device_ids else rank),
    })
    return env


def parse_nnodes(spec):
    """'2' -> (2, 2); '2:4' -> (2, 4) (reference main.py --nnodes range
    form for elastic node membership)."""
    s = str(spec)
    if ":" in s:
        lo, hi = s.split(":")
        return int(lo), int(hi)
    return int(s), int(s)


class NodeRendezvous:
    """Cross-node rendezvous over the TCPStore — the TPU-native analog of
    the reference's etcd master (launch/controllers/master.py:87,191:
    each node registers under the job, the sorted registration order
    assigns node ranks, and a generation counter lets elastic re-form the
    world).  The store lives on the node launched with --rank 0 (or the
    first to bind when ranks are auto)."""

    # Port map relative to --master host:P (convention shared with
    # store.create_or_get_global_tcp_store): P = jax coordination
    # service (global rank 0 process), P+1 = the workers' KV store,
    # P+2 = this launcher-level node rendezvous.
    STORE_PORT_OFFSET = 2

    def __init__(self, master, nnodes_min, nnodes_max, job_id="default",
                 host_store=None, timeout=120.0):
        from ..store import TCPStore
        self.master = master
        host, port = master.rsplit(":", 1)
        self.host, self.port = host, int(port) + self.STORE_PORT_OFFSET
        self.min, self.max = nnodes_min, nnodes_max
        self.job = job_id
        self.timeout = timeout
        from ..store import _LocalStore
        if host_store is None:
            # auto: race to bind; the loser becomes a client
            try:
                self.store = TCPStore(self.host, self.port, is_master=True,
                                      world_size=nnodes_max)
                self.is_host = True
            except Exception:
                self.store = TCPStore(self.host, self.port, is_master=False)
                self.is_host = False
        else:
            self.store = TCPStore(self.host, self.port,
                                  is_master=host_store,
                                  world_size=nnodes_max)
            self.is_host = host_store
        if nnodes_max > 1 and isinstance(self.store, _LocalStore):
            # the in-process fallback cannot cross machines: every node
            # would become master of a private dict and hang the job
            raise RuntimeError(
                "multi-node launch requires the native TCPStore "
                "(csrc/tcp_store.cc); the python fallback is "
                "single-process only")

    def generation(self):
        key = f"job/{self.job}/gen"
        if not self.store.check(key):   # get() BLOCKS on missing keys
            return 0
        v = self.store.get(key)
        if isinstance(v, bytes) and len(v) == 8:
            # counters live in the store's add() wire format (8-byte LE)
            return int.from_bytes(v, "little", signed=True)
        return int(v)

    def bump_generation(self):
        """Ask every node launcher to re-form the world (elastic)."""
        return self.store.add(f"job/{self.job}/gen", 1)

    def register(self, nproc, node_ip="127.0.0.1", node_rank=-1):
        """Blocking: returns (gen, node_rank, nnodes, node_infos).

        Node rank 0 is ALWAYS the store host (the --master machine), so
        global JAX rank 0 runs where the coordination service address
        points; other nodes take explicit --rank or arrival order.  The
        HOST alone commits the world size (one decider — concurrent
        deadline races cannot produce nodes with different worlds);
        a straggler landing outside the committed world fails loudly."""
        deadline = time.monotonic() + self.timeout
        while True:                    # restart at a newer generation if
            gen = self.generation()    # peers bump while we wait
            pre = f"job/{self.job}/g{gen}"
            if self.is_host:
                me = 0
            elif node_rank > 0:
                me = node_rank
            else:
                me = int(self.store.add(f"{pre}/clients", 1))  # 1-based
            self.store.set(f"{pre}/node/{me}", f"{node_ip}|{nproc}")
            self.store.add(f"{pre}/count", 1)

            if self.is_host:
                while self.generation() == gen:
                    n = int(self.store.add(f"{pre}/count", 0))
                    if n >= self.max or (n >= self.min
                                         and time.monotonic() > deadline):
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"rendezvous: {n}/{self.min} nodes after "
                            f"{self.timeout}s (job={self.job} gen={gen})")
                    time.sleep(0.2)
                n = min(int(self.store.add(f"{pre}/count", 0)), self.max)
                self.store.set(f"{pre}/world", str(n))
            else:
                while self.generation() == gen:
                    if self.store.check(f"{pre}/world"):
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"rendezvous: no world commit from the "
                            f"master after {self.timeout}s "
                            f"(job={self.job} gen={gen})")
                    time.sleep(0.2)
                if self.generation() != gen:
                    time.sleep(0.2)
                    continue           # re-register at the new generation
                n = int(self.store.get(f"{pre}/world"))
            if self.generation() == gen:
                break
            time.sleep(0.2)            # gen moved: re-register there
        if me >= n:
            raise RuntimeError(
                f"node rank {me} is outside the committed world of {n} "
                f"nodes (job={self.job} gen={gen}); this node arrived "
                "after membership closed — relaunch to join the next "
                "generation")
        infos = []
        for r in range(n):
            self.store.wait([f"job/{self.job}/g{gen}/node/{r}"])
            ip, np_ = self.store.get(
                f"job/{self.job}/g{gen}/node/{r}").decode().split("|")
            infos.append((ip, int(np_)))
        return gen, me, n, infos


class Launcher:
    """Spawn + watch one local gang (reference: the launcher controller
    loop launch/controllers/controller.py)."""

    def __init__(self, cmd, nprocs, master=None, log_dir=None,
                 max_restarts=0, elastic=False, device_ids=None,
                 base_env=None, nnodes="1", node_rank=-1,
                 job_id="default", node_ip="127.0.0.1",
                 rendezvous_timeout=120.0):
        self.cmd = cmd
        self.nprocs = nprocs
        self.master = master or DEFAULT_MASTER
        self.log_dir = log_dir
        self.max_restarts = max_restarts
        self.elastic = elastic
        self.device_ids = device_ids
        self.base_env = base_env
        self.nnodes_min, self.nnodes_max = parse_nnodes(nnodes)
        self.node_rank = node_rank
        self.job_id = job_id
        self.node_ip = node_ip
        self.rendezvous_timeout = rendezvous_timeout
        self.rdzv: NodeRendezvous | None = None
        self.gen = 0
        self.procs: list[subprocess.Popen] = []

    @property
    def multi_node(self):
        return self.nnodes_max > 1

    def _rendezvous(self):
        """Form (or re-form) the node gang; compute this node's global
        rank window.  jax coordination rides the --master address, so
        the world that comes out of this is exactly what
        init_parallel_env's jax.distributed.initialize expects."""
        if self.rdzv is None:
            host_store = True if self.node_rank == 0 else (
                None if self.node_rank < 0 else False)
            self.rdzv = NodeRendezvous(
                self.master, self.nnodes_min, self.nnodes_max,
                job_id=self.job_id, host_store=host_store,
                timeout=self.rendezvous_timeout)
        gen, me, nnodes, infos = self.rdzv.register(
            self.nprocs, self.node_ip, node_rank=self.node_rank)
        self.gen = gen
        self._node_rank_now = me
        self._world = sum(np_ for _, np_ in infos)
        self._rank_base = sum(np_ for _, np_ in infos[:me])
        eps, g = [], 0
        for ip_, np_ in infos:
            for _ in range(np_):
                eps.append(f"{ip_}:{6170 + g}")
                g += 1
        self._endpoints = eps
        print(f"[launch] node {me}/{nnodes} (gen {gen}): global ranks "
              f"[{self._rank_base}, {self._rank_base + self.nprocs})"
              f" of {self._world}", file=sys.stderr)

    def _spawn(self):
        if self.multi_node:
            self._rendezvous()
            rank_base, world = self._rank_base, self._world
        else:
            rank_base, world = 0, self.nprocs
        self.procs = []
        for rank in range(self.nprocs):
            env = build_rank_env(rank, self.nprocs, self.master,
                                 base_env=self.base_env,
                                 device_ids=self.device_ids,
                                 rank_base=rank_base, world=world,
                                 coordinator=self.master,
                                 node_ip=self.node_ip,
                                 endpoints=getattr(self, "_endpoints",
                                                   None))
            # which elastic world incarnation this process belongs to
            env["PADDLE_JOB_GENERATION"] = str(self.gen)
            stdout = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                stdout = open(os.path.join(
                    self.log_dir, f"workerlog.{rank_base + rank}"), "w")
            p = subprocess.Popen(self.cmd, env=env, stdout=stdout,
                                 stderr=subprocess.STDOUT if stdout
                                 else None)
            p._rank = rank_base + rank
            self.procs.append(p)

    def _kill_all(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 10
        for p in self.procs:
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()

    RESTART_SENTINEL = -9999   # another node asked for a world re-form

    def run(self):
        restarts = 0
        while True:
            self._spawn()
            code = self._watch()
            if code == 0:
                return 0
            if code == self.RESTART_SENTINEL:
                # peer-initiated re-form (doesn't count against local
                # restarts: the failing node accounts for its own)
                print("[launch] peer node requested re-rendezvous; "
                      "restarting gang", file=sys.stderr)
                continue
            if (self.elastic or code == ELASTIC_EXIT_CODE) and \
                    restarts < self.max_restarts:
                restarts += 1
                print(f"[launch] rank failure (exit {code}); elastic "
                      f"restart {restarts}/{self.max_restarts}",
                      file=sys.stderr)
                if self.multi_node and self.rdzv is not None:
                    self.rdzv.bump_generation()   # pull peers along
                continue
            return code

    def _watch(self):
        """Poll children; on any failure kill the gang (reference:
        watcher loop in launch/controllers/watcher.py).  Multi-node:
        also watch the rendezvous generation — a peer bumping it means
        the world must re-form (reference elastic/manager.py watch)."""
        last_gen_check = time.monotonic()
        while True:
            alive = False
            for p in self.procs:
                code = p.poll()
                if code is None:
                    alive = True
                elif code != 0:
                    print(f"[launch] rank {p._rank} exited with {code}; "
                          "terminating gang", file=sys.stderr)
                    self._kill_all()
                    return code
            if not alive:
                return 0
            if self.multi_node and self.rdzv is not None and \
                    time.monotonic() - last_gen_check > 1.0:
                last_gen_check = time.monotonic()
                if self.rdzv.generation() != self.gen:
                    self._kill_all()
                    return self.RESTART_SENTINEL
            time.sleep(0.2)
