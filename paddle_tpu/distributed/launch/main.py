"""CLI entry: python -m paddle_tpu.distributed.launch [...] script.py args

Reference: python/paddle/distributed/launch/main.py argument surface
(--nnodes, --nproc_per_node, --master, --log_dir, --elastic_level,
--max_restart).  --nnodes > 1 (or a min:max range) runs the TCPStore
node rendezvous (see __init__.NodeRendezvous): every node launches this
same command pointing --master at one reachable host; node ranks, the
global JAX process world, and elastic re-forms are negotiated there,
and workers land in jax.distributed.initialize via the env contract
(__init__.build_rank_env).
"""
from __future__ import annotations

import argparse
import sys

from . import Launcher


def parse_args(argv=None):
    ap = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    ap.add_argument("--nnodes", type=str, default="1",
                    help="node count or range (elastic)")
    ap.add_argument("--nproc_per_node", type=int, default=None)
    ap.add_argument("--devices", type=str, default=None,
                    help="comma-separated device ids")
    ap.add_argument("--master", type=str, default=None,
                    help="coordinator host:port")
    ap.add_argument("--rank", type=int, default=-1,
                    help="node rank (-1: auto via rendezvous order)")
    ap.add_argument("--host", type=str, default=None,
                    help="this node's reachable IP")
    ap.add_argument("--log_dir", type=str, default=None)
    ap.add_argument("--run_mode", type=str, default="collective")
    ap.add_argument("--job_id", type=str, default="default")
    ap.add_argument("--max_restart", type=int, default=3)
    ap.add_argument("--elastic_level", type=int, default=-1)
    ap.add_argument("script", type=str)
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    device_ids = None
    if args.devices:
        device_ids = [int(d) for d in args.devices.split(",")]
    if args.nproc_per_node is None:
        nprocs = len(device_ids) if device_ids else 1
    else:
        nprocs = args.nproc_per_node
    cmd = [sys.executable, "-u", args.script] + args.script_args
    launcher = Launcher(
        cmd, nprocs, master=args.master, log_dir=args.log_dir,
        max_restarts=args.max_restart,
        elastic=args.elastic_level >= 0, device_ids=device_ids,
        nnodes=args.nnodes, node_rank=args.rank, job_id=args.job_id,
        node_ip=args.host or "127.0.0.1")
    return launcher.run()


if __name__ == "__main__":
    sys.exit(main())
