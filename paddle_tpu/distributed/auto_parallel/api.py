"""Semi-automatic SPMD API (reference:
python/paddle/distributed/auto_parallel/api.py — shard_tensor:206,
reshard:705, shard_layer:806, shard_optimizer:1591, dtensor_from_local:619).

TPU-native translation: a "DistTensor" IS a jax.Array with a NamedSharding —
no separate runtime type. shard_tensor = device_put with a NamedSharding;
reshard = device_put to the new sharding (XLA emits the collective:
s→r allgather, p→r allreduce, s→s all-to-all — the reference's 12 reshard
functions in paddle/phi/core/distributed/auto_parallel/reshard/ collapse
into GSPMD's resharding); SPMD *rules* (infermeta/spmd_rules, 113 files)
collapse into GSPMD propagation through jit.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..mesh import ProcessMesh, get_mesh
from ..placement import Shard, Replicate, Partial, placements_to_spec, \
    spec_to_placements
from ...framework.tensor import Tensor

__all__ = ["shard_tensor", "reshard", "shard_layer", "shard_optimizer",
           "dtensor_from_local", "dtensor_to_local", "unshard_dtensor",
           "ShardingStage1", "ShardingStage2", "ShardingStage3"]


def _as_mesh(mesh):
    if mesh is None:
        mesh = get_mesh()
    if isinstance(mesh, ProcessMesh):
        return mesh
    return ProcessMesh(mesh)


def _sharding(mesh, placements, ndim):
    spec = placements_to_spec(mesh, placements, ndim)
    return NamedSharding(mesh.jax_mesh, spec)


def shard_tensor(data, mesh=None, placements=None, dtype=None,
                 stop_gradient=None):
    """Place a tensor on the mesh with the given placements."""
    mesh = _as_mesh(mesh)
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    placements = placements or [Replicate() for _ in mesh.dim_names]
    sh = _sharding(mesh, placements, t.ndim)
    arr = jax.device_put(t._data, sh)
    out = Tensor(arr, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient)
    out._grad_node = t._grad_node
    out._out_index = t._out_index
    out.name = t.name
    return out


def reshard(x, mesh=None, placements=None):
    """Convert placements; XLA inserts the matching collective."""
    mesh = _as_mesh(mesh)
    for p in (placements or []):
        if isinstance(p, Partial):
            raise ValueError(
                "reshard to Partial is not expressible at the API level on "
                "TPU; Partial exists transiently inside shard_map regions")
    sh = _sharding(mesh, placements or [], x.ndim)
    arr = jax.device_put(x._data, sh)
    out = Tensor(arr, stop_gradient=x.stop_gradient)
    out._grad_node = x._grad_node
    out._out_index = x._out_index
    return out


def get_placements(x, mesh=None):
    """Inverse: read a tensor's placements from its jax sharding."""
    mesh = _as_mesh(mesh)
    sh = getattr(x._data, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return [Replicate() for _ in mesh.dim_names]
    return spec_to_placements(mesh, sh.spec, x.ndim)


def shard_layer(layer, process_mesh=None, shard_fn=None,
                input_fn=None, output_fn=None):
    """Shard every parameter of `layer` (reference api.py:806).  Default:
    replicate everything; `shard_fn(name, layer, mesh)` customizes."""
    mesh = _as_mesh(process_mesh)

    def default_shard(sub_name, sub_layer, m):
        for pname, p in list(sub_layer._parameters.items()):
            if p is None:
                continue
            sharded = shard_tensor(p, m,
                                   [Replicate() for _ in m.dim_names])
            p._data = sharded._data
    fn = shard_fn or default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, mesh)
    if input_fn is not None or output_fn is not None:
        orig_forward = layer.forward

        def wrapped(*args, **kwargs):
            if input_fn is not None:
                args = input_fn(args, mesh)
            out = orig_forward(*args, **kwargs)
            if output_fn is not None:
                out = output_fn(out, mesh)
            return out
        layer.forward = wrapped
    return layer


def dtensor_from_local(local_tensor, mesh=None, placements=None):
    """Assemble a global sharded array from this process's local shard
    (reference api.py:619).  Single-process SPMD: uses
    jax.make_array_from_single_device_arrays across local devices."""
    mesh = _as_mesh(mesh)
    t = local_tensor if isinstance(local_tensor, Tensor) else Tensor(local_tensor)
    placements = placements or [Replicate() for _ in mesh.dim_names]
    # global shape: multiply sharded dims by mesh size
    gshape = list(t._data.shape)
    for ax, p in enumerate(placements):
        if isinstance(p, Shard):
            gshape[p.dim] *= mesh.shape[ax]
    sh = _sharding(mesh, placements, len(gshape))
    n_shards = len(mesh.process_ids)
    local = np.asarray(t._data)
    # replicate/tile local shards onto each device slot
    devices = mesh.jax_mesh.devices.reshape(-1)
    arrs = [jax.device_put(local, d) for d in devices]
    arr = jax.make_array_from_single_device_arrays(tuple(gshape), sh, arrs)
    return Tensor(arr, stop_gradient=t.stop_gradient)


def dtensor_to_local(dist_tensor, mesh=None, placements=None):
    """This process's local shard as a dense tensor."""
    arr = dist_tensor._data
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return dist_tensor
    return Tensor(shards[0].data, stop_gradient=dist_tensor.stop_gradient)


def unshard_dtensor(dist_tensor):
    """Gather to a fully replicated dense tensor."""
    mesh = get_mesh()
    if mesh is None:
        return dist_tensor
    return reshard(dist_tensor, mesh,
                   [Replicate() for _ in mesh.dim_names])


# ----------------------------------------------------------- optimizer
class _ShardingStage:
    """Configuration token (reference api.py ShardingStage1/2/3:1301+)."""

    stage = 0

    def __init__(self, sharding_mesh_dim=None, mesh=None):
        self.mesh_dim = sharding_mesh_dim or "dp"
        self.mesh = mesh


class ShardingStage1(_ShardingStage):
    stage = 1


class ShardingStage2(_ShardingStage):
    stage = 2


class ShardingStage3(_ShardingStage):
    stage = 3


def shard_optimizer(optimizer, shard_fn=None):
    """Shard optimizer states over the sharding mesh dim (reference
    api.py:1591; fleet analogs group_sharded_optimizer_stage2.py /
    group_sharded_stage3.py).  TPU mapping of the ZeRO ladder:

      stage 1 — optimizer state sharded over the axis; grads stay
        replicated (allreduce), each device updates with its state shard.
      stage 2 — + gradients resharded onto the state sharding before the
        update (XLA lowers the replicated-grad -> sharded-grad transition
        as the reduce-scatter the reference codes by hand), and the
        updated shards gather back into the replicated parameter.
      stage 3 — + parameters live sharded; every consumer op's GSPMD
        gather materializes the full weight transiently (the reference's
        param broadcast/release in group_sharded_stage3.py:1 maps to
        XLA's allgather + buffer lifetime).

    shard_fn may be a ShardingStage instance/class, or a plain function
    `(name, param, accumulator_array) -> array` applied to every state
    (reference's custom shard_fn form)."""
    if shard_fn is None:
        cfg = ShardingStage1()
    elif isinstance(shard_fn, _ShardingStage):
        cfg = shard_fn
    elif isinstance(shard_fn, type) and issubclass(shard_fn, _ShardingStage):
        cfg = shard_fn()
    elif callable(shard_fn):
        def custom_acc(p, name, init=None):
            key = optimizer._param_key(p)
            slot = optimizer._accumulators.setdefault(key, {})
            if name not in slot:
                base = init if init is not None else \
                    jax.numpy.zeros(p._data.shape, jax.numpy.float32)
                slot[name] = shard_fn(name, p, base)
            return slot[name]
        optimizer._acc = custom_acc
        return optimizer
    else:
        raise TypeError(f"unsupported shard_fn: {shard_fn!r}")
    mesh = _as_mesh(cfg.mesh)
    axis = cfg.mesh_dim if cfg.mesh_dim in mesh.dim_names else mesh.dim_names[0]
    axis_idx = mesh.dim_names.index(axis)

    def shard_state(arr):
        # shard along the largest dim divisible by the axis size
        size = mesh.shape[axis_idx]
        for d, s in enumerate(arr.shape):
            if s % size == 0 and s >= size:
                placements = [Replicate()] * len(mesh.dim_names)
                placements[axis_idx] = Shard(d)
                sh = _sharding(mesh, placements, arr.ndim)
                return jax.device_put(arr, sh)
        return arr

    optimizer._shard_state_fn = shard_state

    def sharded_acc(p, name, init=None):
        key = optimizer._param_key(p)
        slot = optimizer._accumulators.setdefault(key, {})
        if name not in slot:
            base = init if init is not None else \
                jax.numpy.zeros(p._data.shape, jax.numpy.float32)
            slot[name] = shard_state(base)
        return slot[name]

    optimizer._acc = sharded_acc
    if cfg.stage >= 2:
        optimizer._grad_transform = shard_state
        optimizer._param_restore = lambda p, arr: (
            jax.device_put(arr, p._data.sharding)
            if getattr(p._data, "sharding", None) is not None else arr)
        # params must be mesh-committed so the sharded-grad update math
        # has one device set.  Only single-device params are (re)placed —
        # an existing mesh sharding (e.g. tensor-parallel weights) is
        # preserved; stage 3 shards params itself below, so skip the
        # transient full-replication there
        if cfg.stage == 2:
            rep = [Replicate()] * len(mesh.dim_names)
            for p in optimizer._parameter_list:
                sh = getattr(p._data, "sharding", None)
                if isinstance(sh, NamedSharding) and \
                        sh.mesh.devices.size == mesh.jax_mesh.devices.size:
                    continue
                p._data = jax.device_put(
                    p._data, _sharding(mesh, rep, p._data.ndim))
    if cfg.stage >= 3:
        # parameters live sharded; lazily-created master weights inherit
        # the sharding from p._data.astype
        for p in optimizer._parameter_list:
            p._data = shard_state(p._data)
    return optimizer
