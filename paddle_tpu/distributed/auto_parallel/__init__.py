from .api import shard_tensor, reshard, shard_layer, shard_optimizer, \
    dtensor_from_local, dtensor_to_local, unshard_dtensor, ShardingStage1, \
    ShardingStage2, ShardingStage3
from .planner import ChipSpec, ModelSpec, Plan, Planner, plan_parallel

__all__ = ["shard_tensor", "reshard", "shard_layer", "shard_optimizer",
           "dtensor_from_local", "dtensor_to_local", "unshard_dtensor",
           "ShardingStage1", "ShardingStage2", "ShardingStage3",
           "ChipSpec", "ModelSpec", "Plan", "Planner", "plan_parallel"]
