"""Analytical parallelism planner — the static cost-model pass.

Reference: python/paddle/distributed/auto_parallel/static/{cost/,
planner_v2.py, tuner/parallel_tuner.py} — per-op compute/comm cost
models driving a search over distributed attributes, so a plan exists
BEFORE anything runs.  (`distributed/auto_tuner.py` is the measured
complement: it times real trials; this module ranks candidates
analytically and can seed/prune that search.)

TPU formulation (the scaling-book roofline): a config's step time is
  max(compute, HBM streaming) + TP collectives (ride ICI) + DP grad
  sync (overlappable) and a pipeline-bubble multiplier; memory is the
sharded params/optimizer/activation sum.  Chip numbers come from
:class:`ChipSpec` presets (v5e / v5p measured-or-nominal values) so the
same model spec plans differently on different parts — exactly the
role of the reference's cluster description
(auto_parallel/static/cluster.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

from ..auto_tuner import candidate_configs

__all__ = ["ChipSpec", "ModelSpec", "Plan", "Planner", "plan_parallel"]


@dataclass(frozen=True)
class ChipSpec:
    """One accelerator chip (reference cluster.py device description)."""
    name: str = "tpu-v5e"
    flops: float = 197e12           # bf16 peak
    hbm_bytes: float = 16e9
    hbm_bw: float = 819e9
    ici_bw: float = 186e9           # per-direction per-link
    mfu_ceiling: float = 0.6        # achievable fraction on big matmuls

    @classmethod
    def v5e(cls):
        return cls()

    @classmethod
    def v5p(cls):
        return cls(name="tpu-v5p", flops=459e12, hbm_bytes=95e9,
                   hbm_bw=2765e9, ici_bw=600e9)


@dataclass(frozen=True)
class ModelSpec:
    """Decoder-LM shape (enough to derive params/flops/bytes; the
    reference cost model walks the program — here the program IS this
    uniform stack, SURVEY §7 ladder rung 4)."""
    num_layers: int = 32
    hidden: int = 4096
    intermediate: int = 11008
    num_heads: int = 32
    num_kv_heads: int = 32
    vocab: int = 32000
    seq: int = 4096
    global_batch: int = 64          # sequences per step

    @property
    def head_dim(self):
        return self.hidden // self.num_heads

    def params(self) -> float:
        h, f = self.hidden, self.intermediate
        kv = self.num_kv_heads * self.head_dim
        per_layer = (h * h + 2 * h * kv + h * h      # q, k, v, o
                     + 3 * h * f                     # swiglu w1/w3/w2
                     + 2 * h)                        # norms
        return (self.num_layers * per_layer
                + 2 * self.vocab * self.hidden)      # embed + head

    def step_flops(self) -> float:
        """6·P·tokens + attention quadratic term."""
        tokens = self.global_batch * self.seq
        attn = (self.num_layers * 12 * self.global_batch
                * self.num_heads * self.seq ** 2 * self.head_dim) / 2
        return 6.0 * self.params() * tokens + attn


@dataclass
class Plan:
    cfg: dict
    step_ms: float
    hbm_gb: float
    breakdown: dict = field(default_factory=dict)

    @property
    def valid(self):
        return math.isfinite(self.step_ms)

    def __repr__(self):
        c = self.cfg
        return (f"Plan(dp={c['dp']} tp={c['tp']} pp={c['pp']} "
                f"stage={c['sharding_stage']} micro={c['micro_batch']} "
                f"~{self.step_ms:.1f} ms, {self.hbm_gb:.1f} GB/chip)")


class Planner:
    """Rank every (dp, tp, pp, sharding, micro) factorization by the
    analytical step time; reject configs whose per-chip memory exceeds
    HBM (reference planner_v2 + prune rules)."""

    def __init__(self, model: ModelSpec, chip: ChipSpec | None = None,
                 remat=True):
        self.model = model
        self.chip = chip or ChipSpec.v5e()
        self.remat = remat

    # ------------------------------------------------------------ memory
    def hbm_bytes(self, cfg) -> float:
        m, c = self.model, cfg
        tp, pp, dp = c["tp"], c["pp"], c["dp"]
        stage = c["sharding_stage"]
        p_local = m.params() / (tp * pp)
        # params bf16 + grads f32 + adam m/v f32 (+ master f32)
        bytes_param = 2.0
        bytes_grad = 4.0 / (dp if stage >= 2 else 1)
        bytes_opt = 12.0 / (dp if stage >= 1 else 1)
        if stage >= 3:
            bytes_param = 2.0 / dp + 2.0   # sharded store + gathered live
        fixed = p_local * (bytes_param + bytes_grad + bytes_opt)
        # activations: micro-batch slice resident per pp stage; remat
        # keeps ~2 live tensors per layer, else ~12 (attn+mlp residuals)
        tokens_local = m.global_batch * m.seq / (dp * c["micro_batch"])
        live_layers = m.num_layers / pp * (1 if not self.remat else
                                           1.0 / max(1, m.num_layers // pp))
        per_tok = m.hidden * 2.0 * (2 if self.remat else 12)
        act = tokens_local * per_tok * max(1.0, live_layers) \
            * (c["micro_batch"] if not self.remat else 1)
        return fixed + act

    # ------------------------------------------------------------- time
    def step_time_ms(self, cfg) -> tuple[float, dict]:
        m, ch, c = self.model, self.chip, cfg
        tp, pp, dp = c["tp"], c["pp"], c["dp"]
        n = tp * pp * dp
        # compute: per-chip flops at the achievable ceiling, derated when
        # tp slices matmuls thin (N/tp < 1024 starves the MXU)
        eff = ch.mfu_ceiling
        n_min = min(m.hidden, m.intermediate) / tp
        if n_min < 1024:
            # thin matmuls starve the MXU lanes (measured v5e behavior)
            eff *= max(0.05, n_min / 1024)
        t_compute = m.step_flops() / (n * ch.flops * eff)
        # hbm streaming floor: params read once per micro-batch pass —
        # a roofline bound, overlapped with compute (max, not sum)
        t_hbm = (m.params() / (tp * pp)) * 2 * c["micro_batch"] / ch.hbm_bw
        t_compute = max(t_compute, t_hbm)
        # TP: 2 allreduces per layer fwd (+2 bwd) over activations
        tokens_local = m.global_batch * m.seq / dp
        ar_bytes = tokens_local * m.hidden * 2.0
        t_tp = 0.0
        if tp > 1:
            per_ar = 2 * (tp - 1) / tp * ar_bytes / ch.ici_bw
            t_tp = 4 * m.num_layers / pp * per_ar
        # DP grad sync: reduce-scatter+allgather of local shard grads,
        # largely overlapped with bwd compute (0.3 exposed)
        t_dp = 0.0
        if dp > 1:
            sync = 2 * (dp - 1) / dp * (m.params() / (tp * pp)) * 2 \
                / ch.ici_bw
            t_dp = 0.3 * sync
        # PP bubble multiplier (1F1B): (pp-1)/micro extra idle
        micro = c["micro_batch"]
        bubble = 1.0 + (pp - 1) / max(micro, 1)
        total = (t_compute + t_tp) * bubble + t_dp
        return total * 1e3, {
            "compute_ms": t_compute * 1e3, "tp_ms": t_tp * 1e3,
            "dp_ms": t_dp * 1e3, "hbm_ms": t_hbm * 1e3,
            "bubble_x": bubble}

    # ------------------------------------------------------------- plan
    def plan(self, num_devices, top_k=5) -> list[Plan]:
        out = []
        for cfg in candidate_configs(num_devices):
            if cfg["pp"] > self.model.num_layers:
                continue
            if self.model.num_heads % cfg["tp"] \
                    or self.model.num_kv_heads % cfg["tp"]:
                # GQA: k/v projections shard by kv head, not query head
                continue
            if self.model.global_batch % (cfg["dp"] * cfg["micro_batch"]):
                continue
            hbm = self.hbm_bytes(cfg)
            if hbm > self.chip.hbm_bytes:
                continue
            ms, br = self.step_time_ms(cfg)
            out.append(Plan(cfg, ms, hbm / 1e9, br))
        out.sort(key=lambda p: p.step_ms)
        return out[:top_k]

    def best(self, num_devices) -> Plan:
        plans = self.plan(num_devices, top_k=1)
        if not plans:
            raise ValueError(
                f"no valid parallel config for {num_devices} devices: "
                f"model does not fit {self.chip.name} HBM under any "
                f"candidate (try more devices, remat, or sharding)")
        return plans[0]

    def to_strategy(self, plan: Plan):
        """Materialize a fleet DistributedStrategy from a plan
        (reference: planner writes dist attrs; here degrees drive
        fleet.init / build_mesh)."""
        from ..fleet.base import DistributedStrategy

        s = DistributedStrategy()
        s.hybrid_configs["dp_degree"] = plan.cfg["dp"]
        s.hybrid_configs["mp_degree"] = plan.cfg["tp"]
        s.hybrid_configs["pp_degree"] = plan.cfg["pp"]
        stage = plan.cfg["sharding_stage"]
        if stage:
            s.sharding = True
            s.sharding_configs = {"stage": stage,
                                  "degree": plan.cfg["dp"]}
            s.hybrid_configs["sharding_degree"] = plan.cfg["dp"]
        if plan.cfg["pp"] > 1:
            s.pipeline = True
        s.pipeline_configs["accumulate_steps"] = plan.cfg["micro_batch"]
        s.recompute = self.remat
        return s


def plan_parallel(model: ModelSpec, num_devices, chip: ChipSpec = None,
                  remat=True, top_k=5):
    """One-call surface: ranked plans for a model on N chips."""
    return Planner(model, chip, remat=remat).plan(num_devices, top_k)
