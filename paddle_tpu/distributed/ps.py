"""paddle.distributed.ps — parameter-server stack (documented stub).

Reference: paddle/fluid/distributed/ps/ (brpc PS server/client, sparse/
dense tables, heter PS) + python/paddle/distributed/ps/.

Out of scope for the TPU rebuild (SURVEY §7: "PS stack out-of-scope for
TPU v1 — document, stub API"): the PS architecture exists to stream
terabyte-scale sparse embeddings through CPU parameter servers for
recommendation workloads; on TPU the idiomatic equivalents are
  * sharded embeddings over the mesh (`fleet.VocabParallelEmbedding`,
    `dist.shard_tensor` with row sharding), and
  * host-offloaded lookups via `jax.pure_callback` +
    `utils.cpp_extension` for out-of-HBM tables.
Every entry point raises with that guidance rather than half-working.
"""
from __future__ import annotations

__all__ = ["PsProgramBuilder", "TheOnePSRuntime", "DistributedInfer"]

_MSG = ("the brpc parameter-server stack is not part of the TPU build; "
        "use mesh-sharded embeddings (fleet.VocabParallelEmbedding / "
        "dist.shard_tensor) or host-offloaded tables via jax.pure_callback "
        "(see paddle_tpu.utils.cpp_extension)")


def _stub(name):
    class _Stub:
        def __init__(self, *a, **k):
            raise NotImplementedError(f"{name}: {_MSG}")
    _Stub.__name__ = name
    return _Stub


PsProgramBuilder = _stub("PsProgramBuilder")
TheOnePSRuntime = _stub("TheOnePSRuntime")
DistributedInfer = _stub("DistributedInfer")
