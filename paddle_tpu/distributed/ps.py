"""paddle.distributed.ps — parameter-server stack, TPU-native formulation.

Reference: paddle/fluid/distributed/ps/ (brpc PS: dense/sparse tables with
per-row optimizers, pull/push RPC, `the_one_ps.py` runtime) +
python/paddle/distributed/ps/.  The reference streams terabyte-scale
sparse embeddings through CPU servers for recommendation workloads;
trainers pull the rows a batch touches and push row gradients back
(async SGD).

TPU formulation: the *device* math stays jax (lookups/backprop produce
:class:`~paddle_tpu.framework.selected_rows.RowSparseGrad` row grads —
never a dense [V, D] buffer), while tables live host-side in numpy on PS
processes reachable over :mod:`paddle_tpu.distributed.rpc` (the brpc
analog).  Row optimizers run on the server exactly like the reference's
sparse SGD/Adagrad rules (paddle/fluid/distributed/ps/table/
memory_sparse_table.cc, sparse_sgd_rule.cc).

Scale note: one table shards across multiple servers by row hash
(reference: `shard_num` in the table config) — :class:`PsClient` routes
pull/push per shard.
"""
from __future__ import annotations

import threading

import numpy as np

from . import rpc

__all__ = ["SparseTable", "DenseTable", "PsServer", "PsClient",
           "DistributedLookup", "PsProgramBuilder", "TheOnePSRuntime",
           "DistributedInfer"]


# ---------------------------------------------------------------- tables
class SparseTable:
    """Host-side sparse embedding table with lazy row init and a per-row
    optimizer rule (reference memory_sparse_table + sparse_sgd_rule)."""

    def __init__(self, dim, initializer="normal", init_scale=0.01,
                 optimizer="sgd", lr=0.01, seed=0, adagrad_eps=1e-6):
        self.dim = int(dim)
        self._rows: dict[int, np.ndarray] = {}
        self._acc: dict[int, np.ndarray] = {}   # adagrad accumulator
        self._rng = np.random.default_rng(seed)
        self._init = initializer
        self._scale = float(init_scale)
        self._opt = optimizer
        self._lr = float(lr)
        self._eps = float(adagrad_eps)
        self._lock = threading.Lock()

    def _row(self, r):
        v = self._rows.get(r)
        if v is None:
            if self._init == "zeros":
                v = np.zeros(self.dim, np.float32)
            else:
                v = (self._rng.standard_normal(self.dim) *
                     self._scale).astype(np.float32)
            # caller holds self._lock (pull/push/state all enter _row
            # under it); _row itself stays lock-free to avoid RLock cost
            # tpu-lint: disable=lock-unlocked-write
            self._rows[r] = v
        return v

    def pull(self, rows):
        rows = np.asarray(rows, np.int64).reshape(-1)
        if len(rows) == 0:
            return np.empty((0, self.dim), np.float32)
        with self._lock:
            return np.stack([self._row(int(r)) for r in rows])

    def push(self, rows, grads, lr=None):
        """Apply row gradients with the table's optimizer rule (server-side
        update — reference sparse_sgd_rule.cc / sparse_adagrad)."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(rows), self.dim)
        lr = self._lr if lr is None else float(lr)
        with self._lock:
            for r, g in zip(rows, grads):
                r = int(r)
                v = self._row(r)
                if self._opt == "adagrad":
                    acc = self._acc.get(r)
                    if acc is None:
                        acc = np.zeros(self.dim, np.float32)
                    acc += g * g
                    self._acc[r] = acc
                    v -= lr * g / (np.sqrt(acc) + self._eps)
                else:
                    v -= lr * g

    def state(self):
        # deep-copy: pushes mutate rows in place, a snapshot must not alias
        with self._lock:
            return {"rows": {k: v.copy() for k, v in self._rows.items()},
                    "acc": {k: v.copy() for k, v in self._acc.items()}}

    def load_state(self, st):
        with self._lock:
            self._rows = {int(k): np.array(v, np.float32)
                          for k, v in st["rows"].items()}
            self._acc = {int(k): np.array(v, np.float32)
                         for k, v in st.get("acc", {}).items()}

    def __len__(self):
        return len(self._rows)


class DenseTable:
    """Whole-parameter table (reference dense table: trainers pull the full
    value, push summed grads)."""

    def __init__(self, value, lr=0.01):
        self.value = np.asarray(value, np.float32)
        self._lr = float(lr)
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self.value.copy()

    def push(self, grad, lr=None):
        with self._lock:
            self.value -= (self._lr if lr is None else float(lr)) \
                * np.asarray(grad, np.float32)


# ------------------------------------------------------------ server side
_SERVER: "PsServer | None" = None


class PsServer:
    """Table host.  Call :meth:`serve` after ``rpc.init_rpc`` — the
    module-level handlers below then execute in this process via the rpc
    layer (reference BrpcPsServer::Start)."""

    def __init__(self):
        self.tables: dict[str, SparseTable | DenseTable] = {}

    def add_sparse_table(self, name, dim, **kw):
        self.tables[name] = SparseTable(dim, **kw)
        return self.tables[name]

    def add_dense_table(self, name, value, **kw):
        self.tables[name] = DenseTable(value, **kw)
        return self.tables[name]

    def serve(self):
        global _SERVER
        _SERVER = self

    def stop(self):
        global _SERVER
        if _SERVER is self:
            _SERVER = None


def _srv():
    if _SERVER is None:
        raise RuntimeError("no PsServer serving in this process "
                           "(call PsServer().serve() after init_rpc)")
    return _SERVER


# module-level handlers: rpc pickles the function object by reference, so
# these run on the callee process against its _SERVER
def _handle_pull_sparse(table, rows):
    return _srv().tables[table].pull(rows)


def _handle_push_sparse(table, rows, grads, lr=None):
    _srv().tables[table].push(rows, grads, lr)
    return True


def _handle_pull_dense(table):
    return _srv().tables[table].pull()


def _handle_push_dense(table, grad, lr=None):
    _srv().tables[table].push(grad, lr)
    return True


def _handle_table_len(table):
    return len(_srv().tables[table])


def _handle_dim(table):
    return _srv().tables[table].dim


def _handle_ready(tables):
    """True when this process serves and has every named table (worker
    startup gate — reference the_one_ps init_server/init_worker order)."""
    return _SERVER is not None and all(t in _SERVER.tables for t in tables)


def _handle_save(table):
    return _srv().tables[table].state()


def _handle_load(table, st):
    _srv().tables[table].load_state(st)
    return True


# ------------------------------------------------------------ client side
class PsClient:
    """Trainer-side handle (reference BrpcPsClient): pull/push against one
    server, or shard by row hash across several (``servers=[...]``)."""

    def __init__(self, server=None, servers=None):
        if servers is None:
            servers = [server if server is not None else "ps0"]
        self.servers = list(servers)

    # -------- sparse
    def _shard(self, rows):
        rows = np.asarray(rows, np.int64).reshape(-1)
        return rows % len(self.servers)

    def wait_server_ready(self, tables=(), timeout=60):
        """Block until every server process serves the named tables
        (reference: trainers wait for init_server before init_worker)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        for srv in self.servers:
            while not rpc.rpc_sync(srv, _handle_ready, args=(list(tables),)):
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"PS {srv} not ready with tables {tables} "
                        f"after {timeout}s")
                _time.sleep(0.05)

    def pull_sparse(self, table, rows):
        rows = np.asarray(rows, np.int64).reshape(-1)
        if len(self.servers) == 1:
            return rpc.rpc_sync(self.servers[0], _handle_pull_sparse,
                                args=(table, rows))
        sh = self._shard(rows)
        futs = [(i, srv, rpc.rpc_async(srv, _handle_pull_sparse,
                                       args=(table, rows[sh == i])))
                for i, srv in enumerate(self.servers) if (sh == i).any()]
        out = None
        for i, srv, f in futs:
            part = f.result()
            if out is None:
                out = np.empty((len(rows), part.shape[1]), np.float32)
            out[sh == i] = part
        if out is None:   # empty row set
            out = np.empty((0, self.dim(table)), np.float32)
        return out

    def push_sparse(self, table, rows, grads, lr=None):
        rows = np.asarray(rows, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32)
        if len(self.servers) == 1:
            return rpc.rpc_sync(self.servers[0], _handle_push_sparse,
                                args=(table, rows, grads, lr))
        sh = self._shard(rows)
        futs = []
        for i, srv in enumerate(self.servers):
            m = sh == i
            if m.any():
                futs.append(rpc.rpc_async(
                    srv, _handle_push_sparse,
                    args=(table, rows[m], grads[m], lr)))
        for f in futs:
            f.result()
        return True

    def dim(self, table):
        return rpc.rpc_sync(self.servers[0], _handle_dim, args=(table,))

    # -------- dense
    def pull_dense(self, table):
        return rpc.rpc_sync(self.servers[0], _handle_pull_dense,
                            args=(table,))

    def push_dense(self, table, grad, lr=None):
        return rpc.rpc_sync(self.servers[0], _handle_push_dense,
                            args=(table, np.asarray(grad, np.float32), lr))

    def table_len(self, table):
        return sum(rpc.rpc_sync(s, _handle_table_len, args=(table,))
                   for s in self.servers)

    def save(self, table):
        return [rpc.rpc_sync(s, _handle_save, args=(table,))
                for s in self.servers]

    def load(self, table, states):
        """Restore a saved table.  Rows are re-sharded by the CURRENT row
        hash, so a snapshot from N servers loads correctly into M servers
        (otherwise rows land on shards the router never reads)."""
        merged_rows, merged_acc = {}, {}
        for st in states:
            merged_rows.update({int(k): v for k, v in st["rows"].items()})
            merged_acc.update({int(k): v for k, v in
                               st.get("acc", {}).items()})
        n = len(self.servers)
        for i, s in enumerate(self.servers):
            part = {"rows": {k: v for k, v in merged_rows.items()
                             if k % n == i},
                    "acc": {k: v for k, v in merged_acc.items()
                            if k % n == i}}
            rpc.rpc_sync(s, _handle_load, args=(table, part))


# ----------------------------------------------------------- device bridge
class DistributedLookup:
    """PS-backed embedding lookup for device math.

    forward: pull the batch's unique rows to the device and gather
    locally; backward row grads come out of the framework's sparse
    embedding path (RowSparseGrad) and :meth:`apply_grad` pushes them to
    the servers — the reference's pull_sparse → forward →
    push_sparse_grad trainer loop (python/paddle/distributed/ps/
    the_one_ps.py, worker side).
    """

    def __init__(self, client, table, dim):
        self.client = client
        self.table = table
        self.dim = dim
        self._w = None
        self._uniq = None

    def __call__(self, ids):
        import jax.numpy as jnp
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from ..framework.tensor import Tensor

        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        vals = self.client.pull_sparse(self.table, uniq)      # [U, D]
        w = Tensor(jnp.asarray(vals), stop_gradient=False)
        local_ids = paddle.to_tensor(inv.reshape(ids_np.shape))
        out = F.embedding(local_ids, w, sparse=True)
        self._w, self._uniq = w, uniq
        return out

    def apply_grad(self, lr=None):
        """Push the recorded row grads of the last forward to the PS."""
        g = None if self._w is None else self._w._grad
        if g is None:
            return
        m = g.merged()
        rows_l = np.asarray(m.rows)
        vals = np.asarray(m.values, np.float32)
        keep = rows_l < len(self._uniq)   # drop merge sentinels
        self.client.push_sparse(self.table, self._uniq[rows_l[keep]],
                                vals[keep], lr)
        self._w._grad = None


# --------------------------------------------------- reference-shaped glue
class TheOnePSRuntime:
    """Minimal `the_one_ps` runtime shape: role-driven server/worker setup
    over rpc (reference python/paddle/distributed/ps/the_one_ps.py)."""

    def __init__(self, role, rank, world_size, master_endpoint=None):
        if role not in ("server", "worker"):
            raise ValueError(f"role must be server|worker, got {role}")
        self.role = role
        self.name = f"ps{rank}" if role == "server" else f"trainer{rank}"
        rpc.init_rpc(self.name, rank=rank, world_size=world_size,
                     master_endpoint=master_endpoint)
        self.server = PsServer() if role == "server" else None
        if self.server is not None:
            self.server.serve()

    def client(self, servers=("ps0",)):
        return PsClient(servers=list(servers))

    def shutdown(self):
        if self.server is not None:
            self.server.stop()
        rpc.shutdown()


class PsProgramBuilder:
    """Reference PsProgramBuilder splits a static program into worker/PS
    parts; here the split is explicit (DistributedLookup on workers,
    tables on servers), so the builder materializes table specs on the
    right role and hands workers a client."""

    def __init__(self, runtime: TheOnePSRuntime):
        self.runtime = runtime

    def build(self, tables: dict):
        if self.runtime.role == "server":
            for name, spec in tables.items():
                if spec.get("type", "sparse") == "sparse":
                    self.runtime.server.add_sparse_table(
                        name, spec["dim"],
                        **{k: v for k, v in spec.items()
                           if k not in ("type", "dim")})
                else:
                    self.runtime.server.add_dense_table(
                        name, spec["value"],
                        **{k: v for k, v in spec.items()
                           if k not in ("type", "value")})
            return self.runtime.server
        client = self.runtime.client()
        client.wait_server_ready(list(tables))
        return client


class DistributedInfer:
    """Inference-side pull-only view (reference DistributedInfer wraps the
    trainer program to pull the latest params before infer)."""

    def __init__(self, client: PsClient):
        self.client = client

    def lookup(self, table, ids):
        import jax.numpy as jnp
        ids_np = np.asarray(ids)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        vals = self.client.pull_sparse(table, uniq)
        return jnp.asarray(vals)[inv].reshape(ids_np.shape + (-1,))
