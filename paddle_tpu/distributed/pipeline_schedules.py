"""Hand-scheduled pipeline parallelism: 1F1B and interleaved (VPP).

Reference analog: fleet/meta_parallel/pipeline_parallel.py (1F1B schedule
:575, interleaved VPP :1174) + pp_utils/p2p_communication.py, and the
zero-bubble pass (passes/pipeline_scheduler_pass/pipeline_zero_bubble.py).
The reference drives per-rank Python schedules exchanging activations with
isend/irecv.  The TPU-native formulation here is a single SPMD program:

  * stages live on the 'pp' mesh axis; activations/cotangents hop along the
    ring with `lax.ppermute` (ICI neighbours);
  * a tick does at most one forward unit AND one backward unit per device
    ("fused-tick 1F1B");
  * backward is MANUAL `jax.vjp` per tick — no AD through the scan — so
    in-flight residuals are bounded by the schedule (a ring buffer of
    ~2·pp stage inputs), not by the number of microbatches (GPipe/AD's
    profile);
  * the loss head runs inside the pipeline at the last stage so backward
    for microbatch j starts the moment its forward leaves the last stage
    — the defining property of 1F1B.

Schedule (S stages, v chunks/virtual stages per device, m microbatches,
tick t, device s):
  forward  of chunk c, microbatch j=g·S+r  at  t = g·v·S + c·S + r + s
  backward mirrors it:                        t_b = 2·t_last(j) - t_f
so the last virtual stage backpropagates a microbatch in the same tick
that computed its forward.  v=1 is plain 1F1B; v>1 is the circular
(interleaved/VPP) variant: device s owns virtual stages {c·S+s},
microbatches visit the ring v times and the fill/drain cost per slot
drops by 1/v.  Activation lifetime is ≤ 2·v·S - 2 ticks, so the ring
buffer holds 2 groups per chunk regardless of m.

Bubble handling: the tick timeline splits into three statically-known
phases — warmup ticks [0, vS-1) where no device has a backward unit,
steady ticks, and drain ticks [mv+S-1, end) where no device has a
forward unit.  Each phase is its own `lax.scan` whose body only contains
the work that phase can have, so warmup costs ~a forward and drain ~a
backward (the classic 1F1B profile) with no garbage compute and no
data-dependent conditionals (which would deadlock GSPMD collectives
inserted for tp/dp inside diverging branches).  Within the steady phase
the per-stage stagger is masked arithmetic — those ticks are the
unavoidable SPMD bubble.

Zero-bubble (ZB-H1) note: splitting dx from dW to fill the drain is a
scheduling refinement of the same engine (run the dW vjp of tick t's
microbatch in a later otherwise-idle tick); XLA already overlaps the
per-tick ppermute with compute, which captures part of that win.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_1f1b", "pipeline_1f1b_hetero", "stack_stage_params",
           "schedule_grid"]


def schedule_grid(S, m, zero_bubble=False):
    """Pure-Python model of the fused-tick schedule: grid[s][t] is the
    set of unit types device s runs at tick t ('F', 'B' = dx, 'W' = dW).

    1F1B fuses W with B; zero-bubble (ZB-H1,
    passes/pipeline_scheduler_pass/pipeline_zero_bubble.py) defers each
    device's LAST s microbatches' W units into its tail idle window
    [T-s, T) — exactly the drain ticks that device would otherwise
    spend idle — so the grid has strictly fewer idle (device, tick)
    slots.  Tests and the executable engine share this placement."""
    T = m + 2 * (S - 1)
    grid = [[set() for _ in range(T)] for _ in range(S)]
    for s in range(S):
        for j in range(m):
            grid[s][j + s].add("F")
            tb = j + 2 * (S - 1) - s
            grid[s][tb].add("B")
            if zero_bubble and j >= m - s:
                grid[s][T - (m - j)].add("W")     # deferred into tail idle
            else:
                grid[s][tb].add("W")              # fused with B
    return grid


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _dyn(leaf, i):
    return jax.lax.dynamic_index_in_dim(leaf, i, axis=0, keepdims=False)


def stack_stage_params(layer_params_list, n_stages, n_virtual=1):
    """Stack a list of L identical-shape per-layer pytrees into the
    [S, v, lps, ...] layout pipeline_1f1b expects (device s owns virtual
    stages {c*S+s : c}, reference interleaved assignment
    pipeline_parallel.py:1174)."""
    L = len(layer_params_list)
    sv = n_stages * n_virtual
    assert L % sv == 0, (L, n_stages, n_virtual)
    lps = L // sv
    rows = []
    for s in range(n_stages):
        chunks = []
        for c in range(n_virtual):
            k = c * n_stages + s
            grp = layer_params_list[k * lps:(k + 1) * lps]
            chunks.append(_tmap(lambda *xs: jnp.stack(xs), *grp))
        rows.append(_tmap(lambda *xs: jnp.stack(xs), *chunks))
    return _tmap(lambda *xs: jnp.stack(xs), *rows)


def pipeline_1f1b(stage_fn: Callable, first_fn: Callable, last_fn: Callable,
                  stacked_params, first_params, last_params, aux, mesh,
                  axis_name: str = "pp", n_virtual: int = 1,
                  zero_bubble: bool = False):
    """One 1F1B forward+backward pass. Returns
    (loss_sum, d_stacked, d_first, d_last).

    zero_bubble=True runs the ZB-H1 unit placement from
    `schedule_grid`: the backward tick computes dx immediately but
    defers the dW of each device's last s microbatches into that
    device's tail idle ticks, filling the drain (reference
    passes/pipeline_scheduler_pass/pipeline_zero_bubble.py).  Gradients
    are bit-identical to 1F1B, and composes with interleaved VPP
    (n_virtual > 1; the deferred units are always the last chunk's, so
    their dW lands on chunk 0).

    The deferred dW does NOT re-run the stage forward (VERDICT r3 #5):
    the backward tick stashes the vjp pullback's ACTIVATION residuals
    (param and stage-input leaves are recognized by trace identity and
    rebuilt at the drain tick from the live params / the x stash, so
    only true intermediates occupy the S-1-deep ring), and the drain
    tick replays the pullback from the stash — its program contains no
    stage_fn forward.

    stage_fn(chunk_params, x) -> x'     homogeneous trunk chunk
    first_fn(first_params, aux_j) -> x  stage-0 input (e.g. embedding)
    last_fn(last_params, y, aux_j) -> scalar loss for one microbatch
    stacked_params: leaves [S, v, ...] (S = mesh pp size, v = n_virtual);
                    see stack_stage_params.
    first_params/last_params: replicated pytrees.
    aux: per-microbatch inputs, leaves [m, ...] (replicated over pp).

    Losses are summed over microbatches; bake any 1/(tokens) scaling into
    last_fn so gradients match the equivalent whole-batch loss.
    """
    S = mesh.shape[axis_name]
    v = int(n_virtual)
    m = jax.tree_util.tree_leaves(aux)[0].shape[0]
    if v > 1:
        assert m % S == 0, \
            f"interleaved schedule needs n_micro % pp == 0, got {m} % {S}"
    if zero_bubble:
        assert m >= S, f"zero_bubble needs n_micro >= pp, got {m} < {S}"
    vS = v * S
    n_buf = 2  # groups per chunk live at once (lifetime <= 2*v*S - 2)
    total_ticks = m * v + 2 * (S - 1) + (v - 1) * S
    warmup_end = min(vS - 1, total_ticks)          # no bwd unit before
    drain_start = min(m * v + S - 1, total_ticks)  # no fwd unit after

    # probe shapes: one microbatch through first_fn (eval_shape only)
    aux0 = _tmap(lambda a: jax.eval_shape(lambda x: x[0], a), aux)
    x_shape = jax.eval_shape(first_fn, first_params, aux0)

    def per_device(stk, fp, lp, aux):
        local = _tmap(lambda a: a[0], stk)      # [v, lps, ...]
        s = jax.lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        def aux_at(j):
            return _tmap(lambda a: _dyn(a, j), aux)

        def chunk_params(c):
            return _tmap(lambda a: _dyn(a, c), local)

        def mask(active, tree):
            return _tmap(
                lambda a: jnp.where(active, a, jnp.zeros_like(a)), tree)

        # ---- ZB residual-slot classification (trace-time, DCE'd) ------
        # The deferred-dW unit replays the backward tick's vjp PULLBACK
        # instead of re-running the stage forward.  A pullback's residual
        # leaves are (a) param leaves and (b) the stage input — both
        # recoverable at drain time without storage — plus (c) true
        # intermediates, the only thing the stash ring must hold.  Param
        # and input leaves are recognized by trace identity here; the
        # flatten order is deterministic, so the tick bodies share it.
        res_slots = res_tree = act_shapes = None
        if zero_bubble:
            cp_t = chunk_params(0)
            x_t = jnp.zeros(x_shape.shape, x_shape.dtype)
            _, pull_t = jax.vjp(stage_fn, cp_t, x_t)
            leaves_t, res_tree = jax.tree_util.tree_flatten(pull_t)
            cp_ids = {id(l): i for i, l in
                      enumerate(jax.tree_util.tree_leaves(cp_t))}
            res_slots, act_shapes = [], []
            for l in leaves_t:
                if id(l) in cp_ids:
                    res_slots.append(("param", cp_ids[id(l)]))
                elif l is x_t:
                    res_slots.append(("x", 0))
                else:
                    res_slots.append(("act", len(act_shapes)))
                    act_shapes.append(jax.ShapeDtypeStruct(l.shape,
                                                           l.dtype))

        def tick(carry, t, do_fwd, do_bwd, do_tail, do_w=False):
            (fwd_state, bwd_state, xbuf, dstk, dfp, dlp, loss_acc,
             carry_w) = carry
            dy_tail = None

            if do_fwd:
                # ---- forward unit indices ---------------------------
                q = t - s
                g_f = q // vS
                c_f = (q % vS) // S
                r_f = q % S
                j_f = g_f * S + r_f
                f_act = jnp.logical_and(q >= 0, q < m * v)
                jf_c = jnp.clip(j_f, 0, m - 1)
                inject = jnp.logical_and(s == 0, c_f == 0)

                x_in = jnp.where(inject, first_fn(fp, aux_at(jf_c)),
                                 fwd_state)
                y = stage_fn(chunk_params(c_f), x_in)
                y = mask(f_act, y)

                # save stage input for this microbatch's backward tick
                slot_f = (g_f % n_buf) * S + r_f
                write = jnp.where(f_act, c_f * (n_buf * S) + slot_f, 0)
                xbuf = jax.lax.dynamic_update_index_in_dim(
                    xbuf, jnp.where(f_act, x_in, xbuf[write]), write,
                    axis=0)

                if do_tail:
                    # ---- loss head at the last virtual stage ---------
                    tail_act = jnp.logical_and(
                        f_act, jnp.logical_and(s == S - 1, c_f == v - 1))
                    (loss_j, (dy_tail, dlp_j)) = jax.value_and_grad(
                        lambda yy, ll: last_fn(ll, yy, aux_at(jf_c)),
                        argnums=(0, 1))(y, lp)
                    loss_acc = loss_acc + jnp.where(
                        tail_act, loss_j.astype(jnp.float32), 0.0)
                    dlp = _tmap(lambda a, g: a + g.astype(jnp.float32),
                                dlp, mask(tail_act, dlp_j))
                    dy_tail = mask(tail_act, dy_tail)
            else:
                y = jnp.zeros_like(fwd_state)

            if do_bwd:
                # ---- backward unit indices (mirror schedule) ---------
                w = t - (2 * (S - 1) - s) - (v - 1) * S
                g_b = w // vS
                c_b = (v - 1) - (w % vS) // S
                r_b = w % S
                j_b = g_b * S + r_b
                b_act = jnp.logical_and(w >= 0, w < m * v)
                cb_c = jnp.clip(c_b, 0, v - 1)

                # at the tail, j_b == j_f: the cotangent is this tick's
                tail_b = jnp.logical_and(s == S - 1, c_b == v - 1)
                dy = bwd_state
                if dy_tail is not None:
                    dy = jnp.where(tail_b, dy_tail, dy)
                dy = mask(b_act, dy)

                slot_b = (g_b % n_buf) * S + r_b
                read = jnp.where(b_act, cb_c * (n_buf * S) + slot_b, 0)
                x_saved = xbuf[read]

                _, pull = jax.vjp(stage_fn, chunk_params(cb_c), x_saved)
                dcp_j, dx = pull(dy)
                if zero_bubble:
                    # ZB-H1: the last s microbatches' dW (always the
                    # LAST chunk backward, c_b == 0) defers to the tail
                    # idle window; stash (x, dy, activation residuals)
                    # for the pullback replay at the W unit
                    defer = jnp.logical_and(
                        b_act, jnp.logical_and(c_b == 0, j_b >= m - s))
                    k_w = jnp.where(defer, j_b - (m - s), 0)

                    def stash(ring, val):
                        return jax.lax.dynamic_update_index_in_dim(
                            ring, jnp.where(defer, val, ring[k_w]),
                            k_w, axis=0)

                    res_leaves = jax.tree_util.tree_leaves(pull)
                    assert len(res_leaves) == len(res_slots), \
                        (len(res_leaves), len(res_slots))
                    wq_acts = list(carry_w[2])
                    for slot, leaf in zip(res_slots, res_leaves):
                        if slot[0] == "act":
                            wq_acts[slot[1]] = stash(wq_acts[slot[1]],
                                                     leaf)
                    carry_w = (stash(carry_w[0], x_saved),
                               stash(carry_w[1], dy), tuple(wq_acts))
                    dcp_j = mask(jnp.logical_not(defer), dcp_j)
                dstk = _tmap(
                    lambda acc, g: jax.lax.dynamic_update_index_in_dim(
                        acc, _dyn(acc, cb_c) + g.astype(jnp.float32),
                        cb_c, axis=0),
                    dstk, dcp_j)

                # stage-0 chunk-0 backward feeds the first_fn vjp
                head_b = jnp.logical_and(
                    b_act, jnp.logical_and(s == 0, c_b == 0))
                _, pull_f = jax.vjp(
                    lambda f: first_fn(f, aux_at(jnp.clip(j_b, 0, m - 1))),
                    fp)
                (dfp_j,) = pull_f(mask(head_b, dx))
                dfp = _tmap(lambda a, g: a + g.astype(jnp.float32),
                            dfp, dfp_j)
            else:
                dx = jnp.zeros_like(fwd_state)

            if do_w and zero_bubble:
                # ---- deferred dW unit (drain ticks [T-s, T)) ---------
                # pullback REPLAY from the stash: param slots rebuild
                # from the live chunk-0 params, the x slot from the x
                # ring, act slots from the act rings — no stage forward
                back = total_ticks - t            # in [1, s] when active
                w_act = jnp.logical_and(back <= s, back >= 1)
                j_w = m - back
                k_w = jnp.where(w_act, j_w - (m - s), 0)
                cp0_leaves = jax.tree_util.tree_leaves(chunk_params(0))
                leaves_w = []
                for slot in res_slots:
                    if slot[0] == "param":
                        leaves_w.append(cp0_leaves[slot[1]])
                    elif slot[0] == "x":
                        leaves_w.append(carry_w[0][k_w])
                    else:
                        leaves_w.append(carry_w[2][slot[1]][k_w])
                pull_w = jax.tree_util.tree_unflatten(res_tree, leaves_w)
                dy_w = mask(w_act, carry_w[1][k_w])
                dcp_w, _dx_unused = pull_w(dy_w)
                dstk = _tmap(
                    lambda acc, g: jax.lax.dynamic_update_index_in_dim(
                        acc, _dyn(acc, 0) + g.astype(jnp.float32),
                        0, axis=0),
                    dstk, mask(w_act, dcp_w))

            # ---- ring communication ---------------------------------
            fwd_state = jax.lax.ppermute(y, axis_name, fwd_perm)
            bwd_state = jax.lax.ppermute(dx, axis_name, bwd_perm)
            return (fwd_state, bwd_state, xbuf, dstk, dfp, dlp,
                    loss_acc, carry_w), None

        x_dtype = x_shape.dtype
        zeros_x = jnp.zeros(x_shape.shape, x_dtype)
        s_max = max(S - 1, 1)
        wq = (jnp.zeros((s_max,) + x_shape.shape, x_dtype),
              jnp.zeros((s_max,) + x_shape.shape, x_dtype),
              tuple(jnp.zeros((s_max,) + a.shape, a.dtype)
                    for a in act_shapes)) \
            if zero_bubble else (jnp.zeros((1, 1)), jnp.zeros((1, 1)), ())
        carry = (
            zeros_x,                                   # fwd activation in
            zeros_x,                                   # bwd cotangent in
            jnp.zeros((v * n_buf * S,) + x_shape.shape, x_dtype),
            _tmap(lambda a: jnp.zeros(a.shape, jnp.float32), local),
            _tmap(lambda a: jnp.zeros(a.shape, jnp.float32), fp),
            _tmap(lambda a: jnp.zeros(a.shape, jnp.float32), lp),
            jnp.zeros((), jnp.float32),
            wq,                                        # deferred-W stash
        )
        # three statically-bounded phases: fwd-only / 1F1B / bwd-only
        # (the tail's first possible tick is vS-1 = warmup_end, so warmup
        # provably skips the loss-head compute too; deferred W units all
        # live inside the drain window)
        for lo, hi, do_f, do_b in (
                (0, warmup_end, True, False),
                (warmup_end, drain_start, True, True),
                (drain_start, total_ticks, False, True)):
            if hi > lo:
                carry, _ = jax.lax.scan(
                    lambda c, t, _f=do_f, _b=do_b: tick(
                        c, t, _f, _b, do_tail=_f and _b,
                        do_w=(not _f) and _b),
                    carry, jnp.arange(lo, hi))
        _, _, _, dstk, dfp, dlp, loss_acc, _ = carry

        # stage grads stay pp-sharded; first/last grads + loss reduce
        loss_acc = jax.lax.psum(loss_acc, axis_name)
        dfp = _tmap(lambda a: jax.lax.psum(a, axis_name), dfp)
        dlp = _tmap(lambda a: jax.lax.psum(a, axis_name), dlp)
        dstk = _tmap(lambda a: a[None], dstk)   # [1, v, lps, ...]
        return loss_acc, dstk, dfp, dlp

    stage_spec = _tmap(
        lambda a: P(axis_name, *([None] * (a.ndim - 1))), stacked_params)
    rep = lambda tree: _tmap(lambda a: P(*([None] * a.ndim)), tree)  # noqa

    fn = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(stage_spec, rep(first_params), rep(last_params),
                  rep(aux)),
        out_specs=(P(), stage_spec, rep(first_params), rep(last_params)),
        axis_names=frozenset({axis_name}), check_vma=False)

    loss, dstk, dfp, dlp = fn(stacked_params, first_params, last_params,
                              aux)
    # match value_and_grad's dtype contract (grads in param dtype)
    cast = lambda g, p: _tmap(  # noqa: E731
        lambda gg, pp: gg.astype(pp.dtype), g, p)
    return (loss, cast(dstk, stacked_params), cast(dfp, first_params),
            cast(dlp, last_params))


def pipeline_1f1b_hetero(stage_fns, last_fn, params, aux, mesh,
                         axis_name: str = "pp", n_virtual: int = 1):
    """1F1B over HETEROGENEOUS stages (fleet PipelineLayer segments),
    with interleaved VPP when n_virtual > 1.

    stage_fns: list of S*n_virtual callables;
      stage_fns[k](params, x, aux_j) -> h for segment k in model order.
      Device s owns virtual chunks {c*S+s : c} (reference interleaved
      assignment pipeline_parallel.py:1174).  Segment 0 usually ignores
      x and builds its input from aux_j (the raw microbatch); every
      segment's OUTPUT must have one common shape/dtype (the ring
      activation).  The FINAL segment belongs in last_fn, not here —
      pass its slot as the identity (the builder in fleet/meta_parallel
      does this).
    last_fn(params, y, aux_j) -> scalar microbatch loss: the final
      segment + loss head, run on the last device's last chunk.
    params: ONE replicated pytree; returned grads are psum'd over pp so
      each stage's contribution (zeros elsewhere) sums to the total.
    aux: per-microbatch inputs, leaves [m, ...] (replicated over pp).

    Returns (loss_sum, grads).

    Per-device compute goes through `lax.switch` on the segment index —
    branches are traced once and only the resident segment executes at
    run time.  Same fused-tick mirror schedule, three-phase bubble
    structure, and bounded ring buffer as pipeline_1f1b.
    """
    S = mesh.shape[axis_name]
    v = int(n_virtual)
    assert len(stage_fns) == S * v, (len(stage_fns), S, v)
    m = jax.tree_util.tree_leaves(aux)[0].shape[0]
    if v > 1:
        assert m % S == 0, \
            f"interleaved schedule needs n_micro % pp == 0, got {m} % {S}"
    vS = v * S
    n_buf = 2
    total_ticks = m * v + 2 * (S - 1) + (v - 1) * S
    warmup_end = min(vS - 1, total_ticks)
    drain_start = min(m * v + S - 1, total_ticks)

    aux0 = _tmap(lambda a: jax.eval_shape(lambda x: x[0], a), aux)
    h_shape = jax.eval_shape(
        lambda p, a: stage_fns[0](p, None, a), params, aux0)

    def per_device(params, aux):
        s = jax.lax.axis_index(axis_name)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        def aux_at(j):
            return _tmap(lambda a: _dyn(a, j), aux)

        def mask(active, tree):
            return _tmap(
                lambda a: jnp.where(active, a, jnp.zeros_like(a)), tree)

        def run_stage(c, p, x, aux_j):
            # resident segment for chunk c on this device: c*S + s
            return jax.lax.switch(
                c * S + s, [lambda pp_, x_, a_, _f=f: _f(pp_, x_, a_)
                            for f in stage_fns], p, x, aux_j)

        def tick(carry, t, do_fwd, do_bwd, do_tail):
            (fwd_state, bwd_state, xbuf, dparams, loss_acc) = carry
            dy_tail = None

            if do_fwd:
                q = t - s
                g_f = q // vS
                c_f = jnp.clip((q % vS) // S, 0, v - 1)
                r_f = q % S
                j_f = g_f * S + r_f
                f_act = jnp.logical_and(q >= 0, q < m * v)
                jf_c = jnp.clip(j_f, 0, m - 1)
                x_in = fwd_state
                y = mask(f_act, run_stage(c_f, params, x_in, aux_at(jf_c)))

                slot_f = (g_f % n_buf) * S + r_f
                write = jnp.where(f_act, c_f * (n_buf * S) + slot_f, 0)
                xbuf = jax.lax.dynamic_update_index_in_dim(
                    xbuf, jnp.where(f_act, x_in, xbuf[write]), write,
                    axis=0)

                if do_tail:
                    tail_act = jnp.logical_and(
                        f_act, jnp.logical_and(s == S - 1, c_f == v - 1))
                    (loss_j, (dy_tail, dp_tail)) = jax.value_and_grad(
                        lambda yy, p: last_fn(p, yy, aux_at(jf_c)),
                        argnums=(0, 1))(y, params)
                    loss_acc = loss_acc + jnp.where(
                        tail_act, loss_j.astype(jnp.float32), 0.0)
                    dparams = _tmap(
                        lambda a, g: a + g.astype(jnp.float32),
                        dparams, mask(tail_act, dp_tail))
                    dy_tail = mask(tail_act, dy_tail)
            else:
                y = jnp.zeros_like(fwd_state)

            if do_bwd:
                w = t - (2 * (S - 1) - s) - (v - 1) * S
                g_b = w // vS
                c_b = jnp.clip((v - 1) - (w % vS) // S, 0, v - 1)
                r_b = w % S
                j_b = g_b * S + r_b
                b_act = jnp.logical_and(w >= 0, w < m * v)
                jb_c = jnp.clip(j_b, 0, m - 1)

                tail_b = jnp.logical_and(s == S - 1, c_b == v - 1)
                dy = bwd_state
                if dy_tail is not None:
                    dy = jnp.where(tail_b, dy_tail, dy)
                dy = mask(b_act, dy)

                slot_b = (g_b % n_buf) * S + r_b
                read = jnp.where(b_act, c_b * (n_buf * S) + slot_b, 0)
                x_saved = xbuf[read]

                _, pull = jax.vjp(
                    lambda p, x: run_stage(c_b, p, x, aux_at(jb_c)),
                    params, x_saved)
                dp_j, dx = pull(dy)
                dparams = _tmap(lambda a, g: a + g.astype(jnp.float32),
                                dparams, mask(b_act, dp_j))
            else:
                dx = jnp.zeros_like(fwd_state)

            fwd_state = jax.lax.ppermute(y, axis_name, fwd_perm)
            bwd_state = jax.lax.ppermute(dx, axis_name, bwd_perm)
            return (fwd_state, bwd_state, xbuf, dparams, loss_acc), None

        zeros_h = jnp.zeros(h_shape.shape, h_shape.dtype)
        carry = (
            zeros_h, zeros_h,
            jnp.zeros((v * n_buf * S,) + h_shape.shape, h_shape.dtype),
            _tmap(lambda a: jnp.zeros(a.shape, jnp.float32), params),
            jnp.zeros((), jnp.float32),
        )
        for lo, hi, do_f, do_b in (
                (0, warmup_end, True, False),
                (warmup_end, drain_start, True, True),
                (drain_start, total_ticks, False, True)):
            if hi > lo:
                carry, _ = jax.lax.scan(
                    lambda c, t, _f=do_f, _b=do_b: tick(c, t, _f, _b,
                                                        do_tail=_f and _b),
                    carry, jnp.arange(lo, hi))
        _, _, _, dparams, loss_acc = carry
        loss_acc = jax.lax.psum(loss_acc, axis_name)
        dparams = _tmap(lambda a: jax.lax.psum(a, axis_name), dparams)
        return loss_acc, dparams

    rep = lambda tree: _tmap(lambda a: P(*([None] * a.ndim)), tree)  # noqa

    fn = jax.shard_map(
        per_device, mesh=mesh, in_specs=(rep(params), rep(aux)),
        out_specs=(P(), rep(params)),
        axis_names=frozenset({axis_name}), check_vma=False)
    loss, grads = fn(params, aux)
    grads = _tmap(lambda g, p: g.astype(p.dtype), grads, params)
    return loss, grads
