"""String-tensor ops.

Reference: paddle/phi/kernels/strings/ (strings_empty_kernel.cc,
strings_lower_upper_kernel.h, case_utils.h, unicode.cc) + the op schema
paddle/phi/ops/yaml/strings_ops.yaml — four ops over a StringTensor:
``empty``, ``empty_like``, ``lower(x, use_utf8_encoding)``,
``upper(x, use_utf8_encoding)``.

TPU formulation: strings have no device representation (the reference's
GPU kernels also serialize through pinned host memory); a StringTensor
here is an N-d numpy object array of ``str`` living host-side, feeding
tokenizers whose OUTPUT (ids) is what reaches the TPU.  Case mapping:
``use_utf8_encoding=False`` converts ASCII bytes only (reference
AsciiCaseConverter); ``True`` applies unicode case mapping via Python's
str.  Divergence note: Python performs FULL case mapping (one-to-many:
``'ß'.upper() == 'SS'``), while the reference's UTF8CaseConverter maps
codepoint-to-codepoint from its own tables and leaves such characters
unchanged — full mapping is the Unicode-correct behavior, so it is kept
deliberately.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "empty", "empty_like", "lower", "upper"]


class StringTensor:
    """N-d tensor of python strings (reference phi::StringTensor)."""

    def __init__(self, data, name=None):
        if isinstance(data, StringTensor):
            data = data._data
        # copy: normalization must not mutate the caller's buffer, and
        # the tensor must not alias it
        arr = np.array(data, dtype=object)
        flat = arr.reshape(-1)
        for i, v in enumerate(flat):
            if not isinstance(v, str):
                if isinstance(v, (list, tuple, np.ndarray)):
                    raise ValueError(
                        "ragged string data: all rows must have the same "
                        f"length (element {i} is a {type(v).__name__})")
                flat[i] = "" if v is None else str(v)
        self._data = flat.reshape(arr.shape)
        self.name = name

    @classmethod
    def _adopt(cls, arr, name=None):
        """Internal: wrap an all-str object array without copy/rescan."""
        t = cls.__new__(cls)
        t._data = arr
        t.name = name
        return t

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return "pstring"

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __eq__(self, other):
        o = other._data if isinstance(other, StringTensor) else other
        return bool(np.array_equal(self._data, np.asarray(o, object)))

    __hash__ = object.__hash__   # identity; __eq__ is whole-tensor

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._data!r})"


def empty(shape, name=None):
    """StringTensor of empty strings (strings_empty kernel)."""
    arr = np.empty(tuple(int(s) for s in shape), dtype=object)
    arr[...] = ""
    return StringTensor(arr, name=name)


def empty_like(x, name=None):
    """Same-shape empty StringTensor (strings_empty_like kernel)."""
    return empty(x.shape if isinstance(x, StringTensor)
                 else np.asarray(x, object).shape, name=name)


def _case_map(x, fn_unicode, fn_ascii, use_utf8_encoding):
    if not isinstance(x, StringTensor):
        x = StringTensor(x)
    out = np.empty(x._data.shape, dtype=object)
    src = x._data.reshape(-1)
    dst = out.reshape(-1)
    for i, s in enumerate(src):
        dst[i] = fn_unicode(s) if use_utf8_encoding else fn_ascii(s)
    return StringTensor._adopt(out)


def _ascii_lower(s: str) -> str:
    return "".join(chr(ord(c) + 32) if "A" <= c <= "Z" else c for c in s)


def _ascii_upper(s: str) -> str:
    return "".join(chr(ord(c) - 32) if "a" <= c <= "z" else c for c in s)


def lower(x, use_utf8_encoding=False, name=None):
    """strings_lower: ASCII-only by default, full unicode with
    ``use_utf8_encoding=True`` (reference strings_lower_upper_kernel.h)."""
    return _case_map(x, str.lower, _ascii_lower, use_utf8_encoding)


def upper(x, use_utf8_encoding=False, name=None):
    """strings_upper (see :func:`lower`)."""
    return _case_map(x, str.upper, _ascii_upper, use_utf8_encoding)
