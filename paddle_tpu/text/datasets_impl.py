"""paddle.text datasets over LOCAL archives.

Reference: python/paddle/text/datasets/{uci_housing,imdb,imikolov,
movielens}.py.  This environment has no egress, so `download=True`
without a `data_file` raises; given the reference's own archive format
on disk (`data_file=`), parsing and item semantics match the reference:

  UCIHousing  — whitespace floats, 14 per row; features mean-centered /
                range-scaled on the FULL data; 80/20 train/test split.
  Imdb        — aclImdb tar; vocabulary from train+test docs with
                frequency > cutoff, sorted by (-freq, word), '<unk>'
                last; items (word ids, [label]) with pos=0, neg=1.
  Imikolov    — PTB simple-examples tar; vocab from train+valid with
                freq > min_word_freq (plus '<s>'/'<e>' markers, '<unk>'
                last); NGRAM windows or SEQ (src, trg) pairs.
  Movielens   — ml-1m zip; user (id, gender, age-bucket, job) + movie
                (id, category ids, title-word ids) + [rating*2-5],
                random train/test split by `test_ratio`.
"""
from __future__ import annotations

import collections
import re
import string
import tarfile
import zipfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens",
           "MovieInfo", "UserInfo"]

_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


def _need_file(data_file, name):
    if data_file is None:
        raise RuntimeError(
            f"paddle.text.datasets.{name}: this environment has no "
            "egress to download the archive; pass data_file= pointing "
            "at a local copy (same archive the reference downloads)")
    return data_file


class UCIHousing(Dataset):
    """reference uci_housing.py; data_file: the whitespace-float file."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(data_file, "UCIHousing")
        self._load()

    def _load(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins, avgs = (data.max(axis=0), data.min(axis=0),
                            data.mean(axis=0))
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype(np.float32), row[-1:].astype(np.float32))

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """reference imdb.py; data_file: the aclImdb tar archive."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.data_file = _need_file(data_file, "Imdb")
        by_split = self._tokenize_all()      # ONE decompression pass
        self.word_idx = self._build_dict(cutoff, by_split)
        self._load(by_split)

    def _tokenize_all(self):
        """One pass over the archive: docs keyed by (split, kind) — the
        dict build and both label passes reuse it (the real aclImdb tar
        holds ~100k members; re-scanning per pass triples load time)."""
        pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        strip = string.punctuation.encode("latin-1")
        by_split = collections.defaultdict(list)
        with tarfile.open(self.data_file) as tarf:
            for tf in tarf:
                m = pat.match(tf.name)
                if m:
                    raw = tarf.extractfile(tf).read().rstrip(b"\n\r")
                    by_split[m.groups()].append(
                        raw.translate(None, strip).lower().split())
        return by_split

    def _build_dict(self, cutoff, by_split):
        freq = collections.defaultdict(int)
        for docs in by_split.values():
            for doc in docs:
                for w in doc:
                    freq[w] += 1
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self, by_split):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, kind in ((0, "pos"), (1, "neg")):
            for doc in by_split.get((self.mode, kind), []):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """reference imikolov.py; data_file: the PTB simple-examples tar."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ"), data_type
        assert mode.lower() in ("train", "test"), mode
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = "train" if mode.lower() == "train" else "valid"
        self.data_file = _need_file(data_file, "Imikolov")
        self.word_idx = self._build_dict(min_word_freq)
        self._load()

    @staticmethod
    def _count(f, freq):
        for line in f:
            for w in line.strip().split():
                freq[w] += 1
            freq[b"<s>"] += 1
            freq[b"<e>"] += 1
        return freq

    def _build_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            freq = collections.defaultdict(int)
            self._count(tf.extractfile(
                "./simple-examples/data/ptb.train.txt"), freq)
            self._count(tf.extractfile(
                "./simple-examples/data/ptb.valid.txt"), freq)
        freq.pop(b"<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load(self):
        unk = self.word_idx[b"<unk>"]
        self.data = []
        name = {"train": "train", "valid": "valid"}[self.mode]
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(f"./simple-examples/data/ptb.{name}.txt")
            for line in f:
                if self.data_type == "NGRAM":
                    assert self.window_size > -1, "Invalid gram length"
                    toks = [b"<s>", *line.strip().split(), b"<e>"]
                    if len(toks) >= self.window_size:
                        ids = [self.word_idx.get(w, unk) for w in toks]
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(
                                tuple(ids[i - self.window_size:i]))
                else:
                    ids = [self.word_idx.get(w, unk)
                           for w in line.strip().split()]
                    src = [self.word_idx[b"<s>"], *ids]
                    trg = [*ids, self.word_idx[b"<e>"]]
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class MovieInfo:
    """reference movielens.py MovieInfo."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    """reference movielens.py UserInfo."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = _AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), age({_AGE_TABLE[self.age]}),"
                f" job({self.job_id})>")


class Movielens(Dataset):
    """reference movielens.py; data_file: the ml-1m zip archive."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode.lower() in ("train", "test"), mode
        self.mode = mode.lower()
        self.test_ratio = test_ratio
        self.rand_seed = rand_seed
        self.data_file = _need_file(data_file, "Movielens")
        # per-instance stream: reseeding the GLOBAL numpy RNG would
        # clobber the user's reproducibility state
        self._rng = np.random.RandomState(rand_seed)
        self._load_meta()
        self._load()

    def _load_meta(self):
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info, self.user_info = {}, {}
        title_words, categories = set(), set()
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode(
                        "latin").strip().split("::")
                    cats = cats.split("|")
                    categories.update(cats)
                    title = pattern.match(title).group(1)
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
                    title_words.update(w.lower() for w in title.split())
            self.movie_title_dict = {w: i for i, w in
                                     enumerate(title_words)}
            self.categories_dict = {c: i for i, c in enumerate(categories)}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode(
                        "latin").strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age,
                                                        job)

    def _load(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (self._rng.random_sample() < self.test_ratio) \
                            != is_test:
                        continue
                    uid, mid, rating, _ = line.decode(
                        "latin").strip().split("::")
                    rating = float(rating) * 2 - 5.0
                    self.data.append(
                        self.user_info[int(uid)].value()
                        + self.movie_info[int(mid)].value(
                            self.categories_dict, self.movie_title_dict)
                        + [[rating]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)
