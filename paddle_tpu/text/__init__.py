"""paddle.text — text-domain utilities.

Reference: python/paddle/text/ (datasets needing downloads are gated —
zero-egress environment) + paddle.text.ViterbiDecoder
(python/paddle/text/viterbi_decode.py; kernel
paddle/phi/kernels/cpu/viterbi_decode_kernel.cc).

TPU formulation: Viterbi forward recursion is one lax.scan over time
(max-product messages), backtrace a reverse scan over the argmax trail —
no dynamic shapes, jit-compilable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn.layer import Layer
from ..ops.registry import op

__all__ = ["ViterbiDecoder", "viterbi_decode", "datasets", "Conll05st",
           "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


@op
def viterbi_decode(potentials, transition, lengths,
                   include_bos_eos_tag=True):
    """potentials: [B, T, N] emissions; transition: [N, N];
    lengths: [B] int.  Returns (scores [B], paths [B, T]).
    Reference semantics: viterbi_decode_kernel.cc (with BOS/EOS rows
    last-2/last-1 of the transition matrix when include_bos_eos_tag)."""
    B, T, N = potentials.shape
    trans = transition.astype(jnp.float32)
    emis = potentials.astype(jnp.float32)

    if include_bos_eos_tag:
        bos, eos = N - 2, N - 1
        init = emis[:, 0] + trans[bos][None, :]
    else:
        init = emis[:, 0]

    def step(carry, t):
        alpha, hist_dummy = carry
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + emis[b, t, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)            # [B, N]
        best_score = jnp.max(scores, axis=1) + emis[:, t]
        # masked steps (t >= length) carry alpha through unchanged
        mask = (t < lengths)[:, None]
        alpha_new = jnp.where(mask, best_score, alpha)
        return (alpha_new, None), jnp.where(mask, best_prev, -1)

    (alpha, _), back = jax.lax.scan(
        step, (init, None), jnp.arange(1, T))
    # back: [T-1, B, N] argmax trail
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]

    last_tag = jnp.argmax(alpha, axis=-1)                 # [B]
    scores = jnp.max(alpha, axis=-1)

    def backstep(tag, bp):
        # bp: [B, N]; -1 rows (masked) keep current tag
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        tag_new = jnp.where(prev < 0, tag, prev)
        return tag_new, tag

    # reversed scan emits [tag_{T-1} ... tag_1]; the final carry is tag_0
    first_tag, path_rev = jax.lax.scan(backstep, last_tag, back[::-1])
    paths = jnp.concatenate(
        [first_tag[:, None], path_rev[::-1].T], axis=1)   # [B, T]
    return scores, paths.astype(jnp.int64)


class ViterbiDecoder(Layer):
    """Reference: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from .datasets_impl import (UCIHousing, Imdb, Imikolov, Movielens,
                            MovieInfo, UserInfo)


def _dataset_stub(name):
    class _Stub:
        def __init__(self, *a, **k):
            raise RuntimeError(
                f"paddle.text.datasets.{name} needs its BPE/SRL archive "
                "layout; pass data through paddle_tpu.io.Dataset, or use "
                "the implemented local-file datasets (UCIHousing/Imdb/"
                "Imikolov/Movielens with data_file=)")
    _Stub.__name__ = name
    return _Stub


class datasets:
    Imdb = Imdb
    Imikolov = Imikolov
    Movielens = Movielens
    UCIHousing = UCIHousing
    WMT14 = _dataset_stub("WMT14")
    WMT16 = _dataset_stub("WMT16")
    Conll05st = _dataset_stub("Conll05st")


# top-level aliases (reference python/paddle/text/__init__.py exports)
Conll05st = datasets.Conll05st
Imdb = datasets.Imdb
Imikolov = datasets.Imikolov
Movielens = datasets.Movielens
UCIHousing = datasets.UCIHousing
WMT14 = datasets.WMT14
WMT16 = datasets.WMT16
