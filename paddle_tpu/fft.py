"""paddle.fft (reference: python/paddle/fft.py — pocketfft-backed C++
kernels paddle/phi/kernels/cpu/fft_kernel.cc; on TPU these lower to XLA's
FFT HLO directly)."""
from __future__ import annotations

import jax.numpy.fft as jfft

from .ops.registry import op

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
           "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _mk(name, fn, arity="1d"):
    if arity == "1d":
        @op(name="fft_" + name)
        def body(x, n=None, axis=-1, norm="backward"):
            return fn(x, n=n, axis=axis, norm=norm)
    elif arity == "2d":
        @op(name="fft_" + name)
        def body(x, s=None, axes=(-2, -1), norm="backward"):
            return fn(x, s=s, axes=axes, norm=norm)
    else:
        @op(name="fft_" + name)
        def body(x, s=None, axes=None, norm="backward"):
            return fn(x, s=s, axes=axes, norm=norm)
    body.__name__ = name
    return body


fft = _mk("fft", jfft.fft)
ifft = _mk("ifft", jfft.ifft)
rfft = _mk("rfft", jfft.rfft)
irfft = _mk("irfft", jfft.irfft)
hfft = _mk("hfft", jfft.hfft)
ihfft = _mk("ihfft", jfft.ihfft)
fft2 = _mk("fft2", jfft.fft2, "2d")
ifft2 = _mk("ifft2", jfft.ifft2, "2d")
rfft2 = _mk("rfft2", jfft.rfft2, "2d")
irfft2 = _mk("irfft2", jfft.irfft2, "2d")
fftn = _mk("fftn", jfft.fftn, "nd")
ifftn = _mk("ifftn", jfft.ifftn, "nd")
rfftn = _mk("rfftn", jfft.rfftn, "nd")
irfftn = _mk("irfftn", jfft.irfftn, "nd")


def _hfftn_body(x, s=None, axes=None, norm="backward"):
    # c2r over the last transform axis, c2c forward over the rest
    # (reference python/paddle/fft.py fftn_c2r)
    import jax.numpy as jnp
    if axes is None:
        axes = list(range(x.ndim)) if s is None else \
            list(range(x.ndim - len(s), x.ndim))
    axes = list(axes)
    sizes = list(s) if s is not None else [None] * len(axes)
    for ax, n_ in zip(axes[:-1], sizes[:-1]):
        x = jfft.fft(x, n=n_, axis=ax, norm=norm)
    return jfft.hfft(x, n=sizes[-1], axis=axes[-1], norm=norm)


def _ihfftn_body(x, s=None, axes=None, norm="backward"):
    if axes is None:
        axes = list(range(x.ndim)) if s is None else \
            list(range(x.ndim - len(s), x.ndim))
    axes = list(axes)
    sizes = list(s) if s is not None else [None] * len(axes)
    x = jfft.ihfft(x, n=sizes[-1], axis=axes[-1], norm=norm)
    for ax, n_ in zip(axes[:-1], sizes[:-1]):
        x = jfft.ifft(x, n=n_, axis=ax, norm=norm)
    return x


hfftn = _mk("hfftn", _hfftn_body, "nd")
ihfftn = _mk("ihfftn", _ihfftn_body, "nd")
hfft2 = _mk("hfft2", lambda x, s=None, axes=(-2, -1), norm="backward":
            _hfftn_body(x, s, axes, norm), "2d")
ihfft2 = _mk("ihfft2", lambda x, s=None, axes=(-2, -1), norm="backward":
             _ihfftn_body(x, s, axes, norm), "2d")


@op(name="fftshift")
def fftshift(x, axes=None):
    return jfft.fftshift(x, axes=axes)


@op(name="ifftshift")
def ifftshift(x, axes=None):
    return jfft.ifftshift(x, axes=axes)


def fftfreq(n, d=1.0, dtype=None):
    from .framework.tensor import Tensor
    return Tensor(jfft.fftfreq(n, d), dtype=dtype)


def rfftfreq(n, d=1.0, dtype=None):
    from .framework.tensor import Tensor
    return Tensor(jfft.rfftfreq(n, d), dtype=dtype)
