"""paddle.signal (reference: python/paddle/signal.py — frame/overlap_add/
stft/istft on the fft kernels)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops.registry import op

__all__ = ["frame", "overlap_add", "stft", "istft"]


@op(name="signal_frame")
def frame(x, frame_length, hop_length, axis=-1):
    """Slice overlapping frames (reference signal.frame): signal on `axis`.
    axis=-1 -> [..., frame_length, n_frames]; axis=0 ->
    [n_frames, frame_length, ...]."""
    if axis in (0,) and x.ndim > 0:
        sig_last = jnp.moveaxis(x, 0, -1)
    else:
        sig_last = x
    n = sig_last.shape[-1]
    n_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    out = sig_last[..., idx]               # [..., n_frames, frame_length]
    if axis in (-1, x.ndim - 1):
        return jnp.swapaxes(out, -1, -2)   # [..., frame_length, n_frames]
    # axis == 0: [n_frames, frame_length, ...]
    return jnp.moveaxis(jnp.moveaxis(out, -1, 0), -1, 1)


@op(name="signal_overlap_add")
def overlap_add(x, hop_length, axis=-1):
    """Inverse of frame.  axis=-1 input [..., frame_length, n_frames] ->
    [..., seq]; axis=0 input [n_frames, frame_length, ...] -> [seq, ...]."""
    if axis == 0:
        # -> [..., frame_length, n_frames]
        x = jnp.moveaxis(jnp.moveaxis(x, 0, -1), 0, -2)
    fl, nf = x.shape[-2], x.shape[-1]
    out_len = (nf - 1) * hop_length + fl
    batch = x.shape[:-2]
    flat = x.reshape((-1, fl, nf))

    def one(sig):
        buf = jnp.zeros((out_len,), x.dtype)

        def body(i, b):
            return jax.lax.dynamic_update_slice(
                b, jax.lax.dynamic_slice(b, (i * hop_length,), (fl,))
                + sig[:, i], (i * hop_length,))
        return jax.lax.fori_loop(0, nf, body, buf)

    out = jax.vmap(one)(flat).reshape(batch + (out_len,))
    if axis == 0:
        out = jnp.moveaxis(out, -1, 0)
    return out


@op(name="stft")
def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        window = jnp.pad(window, (pad, n_fft - win_length - pad))
    if center:
        pads = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pads, mode=pad_mode)
    frames = frame.__op_body__(x, n_fft, hop_length)   # [..., n_fft, n]
    frames = jnp.swapaxes(frames, -1, -2) * window     # [..., n, n_fft]
    if onesided and not jnp.iscomplexobj(x):
        spec = jnp.fft.rfft(frames, axis=-1)
    else:
        spec = jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return jnp.swapaxes(spec, -1, -2)                  # [..., freq, n]


@op(name="istft")
def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        window = jnp.pad(window, (pad, n_fft - win_length - pad))
    spec = jnp.swapaxes(x, -1, -2)                     # [..., n, freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, axis=-1).real
    frames = frames * window
    sig = overlap_add.__op_body__(
        jnp.swapaxes(frames, -1, -2), hop_length)
    # window envelope normalization
    env = overlap_add.__op_body__(
        jnp.broadcast_to(jnp.square(window)[:, None],
                         (n_fft, x.shape[-1])), hop_length)
    sig = sig / jnp.maximum(env, 1e-11)
    if center:
        sig = sig[..., n_fft // 2:]
        if length is None:
            sig = sig[..., :sig.shape[-1] - n_fft // 2]
    if length is not None:
        sig = sig[..., :length]
    return sig
