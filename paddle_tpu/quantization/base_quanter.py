"""BaseQuanter + the quanter factory decorator.

Reference: python/paddle/quantization/base_quanter.py:29 (abstract
quanter Layer: forward simulates quantization, scales/zero_points
expose the learned parameters) and factory.py:78 (the ``quanter``
decorator wraps a BaseQuanter subclass in a QuanterFactory so configs
hold (class, args) pairs and instantiate per observed layer).
"""
from __future__ import annotations

import abc

import numpy as np

from ..nn.layer import Layer

__all__ = ["BaseQuanter", "QuanterFactory", "quanter"]


class BaseQuanter(Layer, metaclass=abc.ABCMeta):
    """Abstract simulated-quantization layer (reference
    base_quanter.py:29): forward fake-quantizes its input; scales /
    zero_points expose the quantization parameters."""

    def __init__(self):
        super().__init__()

    @abc.abstractmethod
    def forward(self, input):
        ...

    @abc.abstractmethod
    def scales(self):
        """Quantization scales: Tensor or ndarray, or None."""
        ...

    @abc.abstractmethod
    def zero_points(self):
        """Quantization zero points: Tensor or ndarray, or None."""
        ...

    def quant_axis(self):
        """Channel axis for per-channel quantization (-1 = per-tensor)."""
        return -1

    def bit_length(self):
        return 8


class _ClassWithArguments(metaclass=abc.ABCMeta):
    def __init__(self, *args, **kwargs):
        self._args = args
        self._kwargs = kwargs

    @property
    def args(self):
        return self._args

    @property
    def kwargs(self):
        return self._kwargs

    @abc.abstractmethod
    def _get_class(self):
        ...

    def __str__(self):
        args_str = ",".join(
            [str(a) for a in self.args]
            + [f"{k}={v}" for k, v in self.kwargs.items()])
        return f"{self.__class__.__name__}({args_str})"

    __repr__ = __str__


class QuanterFactory(_ClassWithArguments):
    """Holds (quanter class, ctor args); ``_instance(layer)`` builds the
    concrete quanter for one observed layer (reference factory.py)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.partial_class = None

    def _get_class(self):
        return self.partial_class

    def _instance(self, layer) -> BaseQuanter:
        return self.partial_class(layer, *self.args, **self.kwargs) \
            if _wants_layer(self.partial_class) \
            else self.partial_class(*self.args, **self.kwargs)


def _wants_layer(cls):
    import inspect
    try:
        params = list(inspect.signature(cls.__init__).parameters)
        return len(params) > 1 and params[1] == "layer"
    except (TypeError, ValueError):
        return False


def quanter(class_name: str):
    """Class decorator (reference factory.py:78): registers a
    BaseQuanter subclass and synthesizes a same-module QuanterFactory
    subclass named ``class_name`` whose instances carry the ctor args::

        @quanter("MyQuanter")
        class MyQuanterLayer(BaseQuanter): ...

        q_config = QuantConfig(activation=MyQuanter(bits=8), ...)
    """
    def wrapper(cls):
        import sys

        def factory_init(self, *args, **kwargs):
            QuanterFactory.__init__(self, *args, **kwargs)
            self.partial_class = cls

        factory = type(class_name, (QuanterFactory,),
                       {"__init__": factory_init})
        mod = sys.modules[cls.__module__]
        setattr(mod, class_name, factory)
        # visible from paddle.quantization like the reference
        from . import __dict__ as _pkg
        _pkg.setdefault(class_name, factory)
        return cls

    return wrapper
