"""paddle.quantization — QAT / PTQ framework.

Reference: python/paddle/quantization/ (QuantConfig config.py, QAT
qat.py, PTQ ptq.py, quanters/ FakeQuanterWithAbsMaxObserver, observers/,
quanted layers in nn/quant/) — 3.9k LoC of the dygraph quantization
stack (the static-graph variant lives in python/paddle/static/quantization).

TPU formulation: fake-quant is a pure jax op with a straight-through
estimator via jax.custom_vjp (reference: fake_quantize_dequantize kernels
paddle/phi/kernels/fake_quantize_kernel.*); int8 deployment maps onto
XLA's native int8 matmul support — `convert` keeps weights int8 +
per-tensor scale.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..framework.tensor import Tensor
from ..ops.registry import op

from .base_quanter import BaseQuanter, QuanterFactory, quanter  # noqa: F401

__all__ = ["BaseQuanter", "quanter", "QuantConfig", "QAT", "PTQ", "quanters", "observers",
           "fake_quant_dequant_abs_max"]


# ------------------------------------------------------------ fake quant
@jax.custom_vjp
def _fqdq(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


def _fqdq_fwd(x, scale, qmax):
    return _fqdq(x, scale, qmax), (x, scale)


def _fqdq_bwd(res, g):
    x, scale = res
    # straight-through estimator, zeroed outside the clip range
    mask = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * mask, jnp.zeros_like(scale), None


_fqdq.defvjp(_fqdq_fwd, _fqdq_bwd)


@op
def fake_quant_dequant_abs_max(x, bit_length=8, scale=None):
    """Quantize-dequantize with abs-max scale + STE gradient."""
    qmax = float(2 ** (bit_length - 1) - 1)
    if scale is None:
        scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-9).astype(jnp.float32)
    return _fqdq(x.astype(jnp.float32), scale, qmax).astype(x.dtype)


# -------------------------------------------------------------- observers
class BaseObserver(Layer):
    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = None

    def scales(self):
        return self._scale

    def quant_axis(self):
        return None

    def zero_points(self):
        return 0.0


class AbsmaxObserver(BaseObserver):
    """Running abs-max (reference: observers/abs_max.py)."""

    def forward(self, x):
        m = float(jnp.max(jnp.abs(x._data)))
        self._scale = m if self._scale is None else max(self._scale, m)
        return x


class EMAObserver(BaseObserver):
    """Exponential-moving-average abs-max (reference:
    quanters/abs_max.py moving-average state)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def forward(self, x):
        m = float(jnp.max(jnp.abs(x._data)))
        self._scale = m if self._scale is None else (
            self.moving_rate * self._scale + (1 - self.moving_rate) * m)
        return x


class observers:
    AbsmaxObserver = AbsmaxObserver
    EMAObserver = EMAObserver


# --------------------------------------------------------------- quanters
class FakeQuanterWithAbsMaxObserver(Layer):
    """QAT fake-quant node (reference:
    quanters/abs_max.py FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self.bit_length = bit_length
        self.moving_rate = moving_rate
        self._scale = None

    def forward(self, x):
        m = float(jnp.max(jnp.abs(x._data)))
        self._scale = m if self._scale is None else (
            self.moving_rate * self._scale + (1 - self.moving_rate) * m)
        scale = jnp.float32(max(self._scale, 1e-9))
        return fake_quant_dequant_abs_max(x, bit_length=self.bit_length,
                                          scale=scale)

    def scales(self):
        return self._scale


class FakeQuanterChannelWiseAbsMaxObserver(Layer):
    """Per-output-channel weight quanter (reference:
    quanters/abs_max.py channel-wise variant)."""

    def __init__(self, bit_length=8, quant_axis=0, **kwargs):
        super().__init__()
        self.bit_length = bit_length
        self._quant_axis = quant_axis
        self._scale = None

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        axes = tuple(i for i in range(x.ndim) if i != self._quant_axis)
        arr = x._data.astype(jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(arr), axis=axes, keepdims=True),
                            1e-9)
        self._scale = np.asarray(scale).squeeze()
        out = _fqdq(arr, scale, qmax)
        return Tensor(out.astype(x._data.dtype),
                      stop_gradient=x.stop_gradient)


class quanters:
    FakeQuanterWithAbsMaxObserver = FakeQuanterWithAbsMaxObserver
    FakeQuanterChannelWiseAbsMaxObserver = \
        FakeQuanterChannelWiseAbsMaxObserver


# ----------------------------------------------------------------- config
class QuantConfig:
    """Reference: python/paddle/quantization/config.py."""

    def __init__(self, activation=None, weight=None):
        self._global_activation = activation
        self._global_weight = weight
        self._layer_configs = []       # (layer ids, act, weight)
        self._type_configs = []        # (layer types, act, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        self._layer_configs.append((layers, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else \
            [layer_type]
        self._type_configs.append((tuple(types), activation, weight))

    def _config_for(self, layer):
        for layers, a, w in self._layer_configs:
            if any(layer is l for l in layers):
                return a, w
        for types, a, w in self._type_configs:
            if isinstance(layer, types):
                return a, w
        return self._global_activation, self._global_weight


def _make(factory):
    if factory is None:
        return None
    return factory() if callable(factory) else factory


# --------------------------------------------------------- quanted layers
class QuantedLayer(Layer):
    """Wraps a leaf layer with activation/weight quant nodes (reference:
    paddle/nn/quant/qat/ QuantedLinear/QuantedConv2D)."""

    def __init__(self, inner, act_quanter, weight_quanter):
        super().__init__()
        self.inner = inner
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self.inner, "weight"):
            w = self.inner.weight
            qw = self.weight_quanter(w)
            # swap the ATTRIBUTE (not w._data): qw keeps its tape node,
            # so backward flows through the quanter's STE mask into the
            # real Parameter; _parameters/state_dict still hold w
            object.__setattr__(self.inner, "weight", qw)
            try:
                return self.inner(x)
            finally:
                object.__setattr__(self.inner, "weight", w)
        return self.inner(x)


class ConvertedLayer(Layer):
    """Deploy form: int8 weights + scale (reference: nn/quant convert —
    weight_quantize + int8 kernels; XLA handles int8 matmul natively)."""

    def __init__(self, inner, bit_length=8):
        super().__init__()
        self.inner = inner
        qmax = float(2 ** (bit_length - 1) - 1)
        w = inner.weight._data.astype(jnp.float32)
        scale = jnp.maximum(jnp.max(jnp.abs(w)), 1e-9)
        self.register_buffer(
            "qweight", Tensor(jnp.clip(jnp.round(w / scale * qmax),
                                       -qmax, qmax).astype(jnp.int8)))
        self.register_buffer("wscale", Tensor(scale / qmax))
        self._wdtype = inner.weight._data.dtype

    def forward(self, x):
        w = (self.qweight._data.astype(jnp.float32)
             * self.wscale._data).astype(self._wdtype)
        orig = self.inner.weight._data
        self.inner.weight._data = w
        try:
            return self.inner(x)
        finally:
            self.inner.weight._data = orig


_QUANTABLE = ("Linear", "Conv2D", "Conv1D", "Conv3D")


def _swap_layers(model, make_wrapper):
    for name, sub in list(model._sub_layers.items()):
        if type(sub).__name__ == "QuantedLayer":
            continue
        if type(sub).__name__ in _QUANTABLE:
            repl = make_wrapper(sub)
            if repl is not None:
                # setattr, not _sub_layers[name]: Layer.__setattr__ keeps
                # the registry AND the instance attribute in sync (a
                # _sub_layers-only write leaves `self.fc` resolving to
                # the original layer)
                setattr(model, name, repl)
        else:
            _swap_layers(sub, make_wrapper)
    return model


def _quantize_model(config, model, inplace):
    import copy
    if not inplace:
        model = copy.deepcopy(model)

    def wrap(layer):
        a, w = config._config_for(layer)
        if a is None and w is None:
            return None
        return QuantedLayer(layer, _make(a), _make(w))

    return _swap_layers(model, wrap)


class QAT:
    """Quantization-aware training (reference: quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model, inplace=False):
        return _quantize_model(self._config, model, inplace)

    def convert(self, model, inplace=False):
        return PTQ(self._config).convert(model, inplace=inplace)


class PTQ:
    """Post-training quantization (reference: quantization/ptq.py)."""

    def __init__(self, config: QuantConfig = None):
        self._config = config or QuantConfig(
            activation=AbsmaxObserver, weight=AbsmaxObserver)

    def quantize(self, model, inplace=False):
        return _quantize_model(self._config, model, inplace)

    def convert(self, model, inplace=False):
        """Replace observed/quanted layers with int8-weight deploy form."""
        import copy
        if not inplace:
            model = copy.deepcopy(model)

        def convert_in(m):
            for name, sub in list(m._sub_layers.items()):
                if isinstance(sub, QuantedLayer):
                    setattr(m, name, ConvertedLayer(sub.inner))
                else:
                    convert_in(sub)
        convert_in(model)
        return model
