"""paddle.utils — misc utilities (cpp_extension custom-op toolchain,
deprecations, install checks).  Reference: python/paddle/utils/."""
from __future__ import annotations

from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension", "run_check", "try_import", "unique_name"]


def run_check():
    """Reference: paddle.utils.run_check — smoke-test the install."""
    import jax
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones((2, 2), "float32"))
    y = paddle.matmul(x, x)
    assert float(y.sum()) == 8.0
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed successfully! "
          f"backend={dev.platform} device={dev.device_kind}")


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is not installed")


class _UniqueName:
    def __init__(self):
        self._ids = {}

    def generate(self, key="tmp"):
        i = self._ids.get(key, 0)
        self._ids[key] = i + 1
        return f"{key}_{i}"


unique_name = _UniqueName()
