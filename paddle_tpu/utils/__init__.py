"""paddle.utils — misc utilities (cpp_extension custom-op toolchain,
deprecations, install checks).  Reference: python/paddle/utils/."""
from __future__ import annotations

from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension", "run_check", "try_import", "unique_name"]


def run_check():
    """Reference: paddle.utils.run_check — smoke-test the install."""
    import jax
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones((2, 2), "float32"))
    y = paddle.matmul(x, x)
    assert float(y.sum()) == 8.0
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed successfully! "
          f"backend={dev.platform} device={dev.device_kind}")


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is not installed")


class _UniqueName:
    def __init__(self):
        self._ids = {}

    def generate(self, key="tmp"):
        i = self._ids.get(key, 0)
        self._ids[key] = i + 1
        return f"{key}_{i}"


unique_name = _UniqueName()


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference
    utils/deprecated.py)."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__name__} is deprecated since {since or '?'}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f": {reason}"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def require_version(min_version, max_version=None):
    """Check the installed framework version (reference
    utils/install_check.py require_version)."""
    from .. import __version__

    def as_tuple(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = as_tuple(__version__)
    if as_tuple(min_version) > cur:
        raise Exception(
            f"version {__version__} < required minimum {min_version}")
    if max_version and as_tuple(max_version) < cur:
        raise Exception(
            f"version {__version__} > allowed maximum {max_version}")


__all__ += ["deprecated", "require_version"]
