"""Custom C++ op toolchain (paddle.utils.cpp_extension).

Reference: python/paddle/utils/cpp_extension/ (JIT-compiles user C++/CUDA
into a loadable op library; registration via PD_BUILD_OP in
paddle/fluid/framework/custom_operator.cc).

TPU formulation: user C++ compiles to a shared library with the system
toolchain (g++ -O3 -shared -fPIC — no nvcc); exported `extern "C"`
kernels bind through ctypes and surface as framework ops whose body is a
`jax.pure_callback`, so they compose with jit/grad-stop like any host
callback (XLA custom-call-to-host being the TPU analog of a CPU PHI
kernel).  The C ABI:

    extern "C" void my_op(const void* x, void* out, int64_t n);

operating elementwise-contiguously, or the shaped variant taking
explicit dims.  For on-device performance the answer is Pallas, not C++
— this path exists for host-side ops (IO, CPU preprocessing, legacy
kernels), mirroring how the reference's custom-op path targets CPU too.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["load", "CppExtension", "get_build_directory", "CustomOpModule"]

_DEFAULT_CFLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared"]


def get_build_directory():
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Source bundle (API parity with reference setup() flow)."""

    def __init__(self, sources, extra_compile_args=None, name=None):
        self.sources = sources
        self.extra_compile_args = extra_compile_args or []
        self.name = name


def _compile(name, sources, extra_cflags):
    src_key = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            src_key.update(f.read())
    out = os.path.join(get_build_directory(),
                       f"{name}_{src_key.hexdigest()[:12]}.so")
    if not os.path.exists(out):
        cmd = ["g++"] + _DEFAULT_CFLAGS + list(extra_cflags or []) + \
            list(sources) + ["-o", out]
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"cpp_extension compile failed:\n{r.stderr}")
    return out


class CustomOpModule:
    """Loaded extension; exported symbols become framework ops."""

    def __init__(self, name, lib_path):
        self.__name__ = name
        self._lib_path = lib_path
        self._lib = ctypes.CDLL(lib_path)
        self._ops = {}

    def elementwise_op(self, symbol, out_dtype=None):
        """Wrap `extern "C" void f(const void* x, void* y, int64_t n)` as
        a same-shape framework op."""
        if symbol in self._ops:
            return self._ops[symbol]
        cfn = getattr(self._lib, symbol)
        cfn.restype = None
        cfn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]

        def host_impl(x):
            x = np.ascontiguousarray(x)
            out = np.empty_like(
                x, dtype=out_dtype if out_dtype else x.dtype)
            cfn(x.ctypes.data_as(ctypes.c_void_p),
                out.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(x.size))
            return out

        from ..ops.registry import op

        @op(name=f"custom_{self.__name__}_{symbol}", external=True)
        def custom_op(x):
            return jax.pure_callback(
                host_impl,
                jax.ShapeDtypeStruct(x.shape,
                                     out_dtype or x.dtype),
                x, vmap_method="sequential")

        self._ops[symbol] = custom_op
        return custom_op

    def binary_op(self, symbol, out_dtype=None):
        """`extern "C" void f(const void* a, const void* b, void* y,
        int64_t n)` — same-shape binary op."""
        if symbol in self._ops:
            return self._ops[symbol]
        cfn = getattr(self._lib, symbol)
        cfn.restype = None
        cfn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                        ctypes.c_int64]

        def host_impl(a, b):
            a = np.ascontiguousarray(a)
            b = np.ascontiguousarray(b)
            out = np.empty_like(
                a, dtype=out_dtype if out_dtype else a.dtype)
            cfn(a.ctypes.data_as(ctypes.c_void_p),
                b.ctypes.data_as(ctypes.c_void_p),
                out.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(a.size))
            return out

        from ..ops.registry import op

        @op(name=f"custom_{self.__name__}_{symbol}", external=True)
        def custom_op(a, b):
            return jax.pure_callback(
                host_impl,
                jax.ShapeDtypeStruct(a.shape, out_dtype or a.dtype),
                a, b, vmap_method="sequential")

        self._ops[symbol] = custom_op
        return custom_op

    def raw(self, symbol):
        return getattr(self._lib, symbol)


def load(name, sources, extra_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose=False):
    """JIT-compile + load (reference: cpp_extension.load)."""
    flags = list(extra_cflags or [])
    for inc in extra_include_paths or []:
        flags.append(f"-I{inc}")
    flags += list(extra_ldflags or [])
    lib = _compile(name, sources, flags)
    if verbose:
        print(f"[cpp_extension] built {lib}")
    return CustomOpModule(name, lib)
