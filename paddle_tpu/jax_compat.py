"""Compatibility shims over the installed jax version.

The framework targets the modern jax surface (``jax.shard_map`` with the
``check_vma`` kwarg).  Older runtimes ship the same primitive as
``jax.experimental.shard_map.shard_map`` with the kwarg spelled
``check_rep`` — install a forwarding wrapper onto the ``jax`` module so
both ``jax.shard_map(...)`` and ``from jax import shard_map`` resolve
everywhere (module attribute assignment covers both forms)."""
from __future__ import annotations

import functools

import jax


def install():
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                  axis_names=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        if axis_names is not None and "auto" not in kw:
            # modern axis_names = the manually-mapped axes; the old API
            # spells the complement as auto
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map
