"""paddle.io: Dataset / DataLoader (reference: python/paddle/io/reader.py:262,
dataloader/).  The multi-process worker pool + shared-memory ring of the
reference maps to a thread-based prefetcher here (TPU input pipelines are
host-CPU bound on decode, and jax arrays are materialized on device
asynchronously); a C++ shared-memory DataLoader core is planned in
runtime/ (SURVEY §8)."""
from .dataset import Dataset, IterableDataset, TensorDataset, ChainDataset, \
    ComposeDataset, ConcatDataset, Subset, random_split
from .sampler import Sampler, SequenceSampler, RandomSampler, \
    BatchSampler, DistributedBatchSampler, WeightedRandomSampler, \
    SubsetRandomSampler
from .dataloader import DataLoader, default_collate_fn

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ChainDataset",
           "ComposeDataset", "ConcatDataset", "Subset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
           "DistributedBatchSampler", "WeightedRandomSampler",
           "SubsetRandomSampler", "DataLoader", "default_collate_fn"]


class WorkerInfo:
    """Info for the current DataLoader worker (reference
    python/paddle/io/dataloader/worker.py get_worker_info)."""

    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """Returns None in the main process, WorkerInfo inside a worker."""
    return _worker_info
