"""Multiprocess DataLoader workers over the native shared-memory ring.

Reference analog: python/paddle/io/dataloader/dataloader_iter.py's
_DataLoaderIterMultiProcess + the mmap shm channel
(paddle/phi/core/memory/allocation/mmap_allocator.cc).  Worker processes run
`dataset[i]` (decode/augment — the Python-bound part) and push pickled
sample lists into a process-shared shm ring (csrc/shm_ring.cc); the trainer
process pops, collates, and hands batches to jax.  Workers never touch jax,
so forking after XLA initialization is safe.
"""
from __future__ import annotations

import os
import pickle
import signal

from ..core.native import ShmRing, available

__all__ = ["ShmWorkerPool", "available"]

_SENTINEL_SEQ = 0xFFFFFFFF


def _set_pdeathsig():  # kill worker if the trainer dies
    try:
        import ctypes
        libc = ctypes.CDLL(None)
        libc.prctl(1, signal.SIGKILL)  # PR_SET_PDEATHSIG
    except Exception:
        pass


class ShmWorkerPool:
    """Fork `num_workers` processes; feed them (seq, indices) tasks; collect
    (seq, samples) results in order."""

    def __init__(self, dataset, num_workers: int, capacity: int = 64 << 20,
                 worker_init_fn=None):
        self.dataset = dataset
        self.num_workers = num_workers
        uid = f"{os.getpid()}_{id(self):x}"
        self._task_ring = ShmRing(f"/pt_task_{uid}", capacity=4 << 20,
                                  create=True)
        self._res_ring = ShmRing(f"/pt_res_{uid}", capacity=capacity,
                                 create=True)
        self._pids = []
        self._worker_init_fn = worker_init_fn
        import warnings
        for wid in range(num_workers):
            with warnings.catch_warnings():
                # workers never touch jax; fork-after-XLA-init is safe here
                warnings.simplefilter("ignore", RuntimeWarning)
                warnings.simplefilter("ignore", DeprecationWarning)
                pid = os.fork()
            if pid == 0:
                try:
                    self._worker_main(wid)
                finally:
                    os._exit(0)
            self._pids.append(pid)

    # ------------------------------------------------------------- worker
    def _worker_main(self, wid: int) -> None:
        _set_pdeathsig()
        task_ring = ShmRing(self._task_ring.name)
        res_ring = ShmRing(self._res_ring.name)
        import paddle_tpu.io as _io
        _io._worker_info = _io.WorkerInfo(
            id=wid, num_workers=self.num_workers,
            seed=getattr(self, "_base_seed", 0) + wid,
            dataset=self.dataset)
        if self._worker_init_fn is not None:
            self._worker_init_fn(wid)
        while True:
            try:
                task = task_ring.pop()
            except (EOFError, BrokenPipeError):
                break
            seq, indices = pickle.loads(task)
            if seq == _SENTINEL_SEQ:
                break
            try:
                samples = [self.dataset[i] for i in indices]
                payload = pickle.dumps((seq, samples), protocol=4)
            except Exception as e:  # surface the error in the parent
                try:
                    payload = pickle.dumps((seq, e), protocol=4)
                except Exception:
                    payload = pickle.dumps(
                        (seq, RuntimeError(f"worker {wid}: unpicklable "
                                           f"exception {type(e).__name__}: "
                                           f"{e}")), protocol=4)
            try:
                res_ring.push(payload)
            except ValueError:
                # batch pickles larger than the ring: report, don't vanish
                res_ring.push(pickle.dumps(
                    (seq, RuntimeError(
                        f"worker {wid}: batch of {len(indices)} samples "
                        f"({len(payload)} bytes pickled) exceeds the shm "
                        f"ring capacity; lower batch_size or raise the "
                        f"DataLoader shm capacity")), protocol=4))

    # ------------------------------------------------------------- parent
    def run(self, batch_indices_iter, prefetch: int = 4):
        """Yield sample-lists in submission order.  `prefetch` bounds the
        number of in-flight tasks per worker."""
        inflight = {}
        next_submit = 0
        next_yield = 0
        done_submitting = False
        it = iter(batch_indices_iter)
        reorder = {}
        max_inflight = max(2, prefetch) * self.num_workers

        def submit_one():
            nonlocal next_submit, done_submitting
            if done_submitting:
                return False
            try:
                indices = next(it)
            except StopIteration:
                done_submitting = True
                return False
            self._task_ring.push(pickle.dumps((next_submit, list(indices)),
                                              protocol=4))
            inflight[next_submit] = True
            next_submit += 1
            return True

        for _ in range(max_inflight):
            if not submit_one():
                break
        while inflight or reorder:
            if next_yield in reorder:
                result = reorder.pop(next_yield)
            else:
                seq, result = pickle.loads(self._res_ring.pop(timeout=300))
                inflight.pop(seq, None)
                if seq != next_yield:
                    reorder[seq] = result
                    continue
            if isinstance(result, Exception):
                raise result
            yield result
            next_yield += 1
            submit_one()

    def shutdown(self) -> None:
        for _ in self._pids:
            try:
                self._task_ring.push(pickle.dumps((_SENTINEL_SEQ, []),
                                                  protocol=4), timeout=1.0)
            except Exception:
                pass
        self._task_ring.close()
        self._res_ring.close()
        for pid in self._pids:
            try:
                os.waitpid(pid, 0)
            except ChildProcessError:
                pass
        self._task_ring.free()
        self._res_ring.free()
        self._pids = []

    def __del__(self):  # pragma: no cover - gc timing
        try:
            if self._pids:
                for pid in self._pids:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
        except Exception:
            pass
