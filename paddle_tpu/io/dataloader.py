"""DataLoader (reference: python/paddle/io/reader.py:262 +
dataloader/dataloader_iter.py).  Like the reference, num_workers>0 with
use_shared_memory=True runs true multi-process workers over a native
shared-memory ring (csrc/shm_ring.cc via io/shm_workers.py) so
decode/augment escapes the GIL; with use_shared_memory=False (or when the
native core is unavailable) a thread-pool prefetcher feeds a bounded queue,
which suffices when the pipeline is numpy-bound.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..framework.tensor import Tensor, to_tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        import jax.numpy as jnp
        return to_tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([s[i] for s in batch])
                for i in range(len(sample))]
    return to_tensor(np.asarray(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = max(2, prefetch_factor)
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable:
            yield from self._iter_iterable()
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self._fetch(indices)
            return
        if self.use_shared_memory:
            from . import shm_workers
            if shm_workers.available():
                yield from self._iter_multiprocess()
                return
        yield from self._iter_threaded()

    def _iter_multiprocess(self):
        """Reference _DataLoaderIterMultiProcess path: fork workers, samples
        cross process boundaries via the native shm ring; collate happens in
        the trainer process (jax arrays must be created post-fork)."""
        from .shm_workers import ShmWorkerPool
        pool = ShmWorkerPool(self.dataset, self.num_workers,
                             worker_init_fn=self.worker_init_fn)
        try:
            for samples in pool.run(iter(self.batch_sampler),
                                    prefetch=self.prefetch_factor):
                yield self.collate_fn(samples)
        finally:
            pool.shutdown()

    def _iter_threaded(self):
        """Pipelined fetch: submit up to num_workers*prefetch_factor batches
        ahead, yield in order."""
        sentinel = object()
        out_q: "queue.Queue" = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)

        def producer():
            with ThreadPoolExecutor(self.num_workers) as pool:
                futures = []
                for indices in self.batch_sampler:
                    futures.append(pool.submit(self._fetch, indices))
                    while len(futures) >= self.num_workers * self.prefetch_factor:
                        out_q.put(futures.pop(0).result())
                for f in futures:
                    out_q.put(f.result())
            out_q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = out_q.get()
            if item is sentinel:
                break
            yield item
        t.join()
