"""paddle.Model (reference: python/paddle/hapi/model.py:1472 — Model over a
Layer with prepare/fit/evaluate/predict/save/load).

TPU twist: `fit` drives the compiled train-step path (jit/functional.py) —
forward+backward+update is one XLA executable per epoch loop, matching the
reference's intent of `Model` as the performant curated loop.
"""
from __future__ import annotations

import os
import warnings

import numpy as np

from ..framework.tensor import Tensor
from ..io.dataloader import DataLoader
from .. import framework
from ..jit.functional import TrainStep
from .callbacks import CallbackList, ProgBarLogger, ModelCheckpoint

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------ prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        self._amp_configs = amp_configs
        self._train_step = None
        return self

    # ------------------------------------------------------------- batches
    def _loss_fn(self, model, *batch):
        n_labels = len(self._labels) if self._labels else 1
        inputs, labels = batch[:-n_labels], batch[-n_labels:]
        outputs = model(*inputs)
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return self._loss(*outs, *labels)

    def train_batch(self, inputs, labels=None, update=True):
        batch = list(_as_list(inputs)) + list(_as_list(labels))
        if not update:
            # gradient-accumulation micro-step: eager backward, no update
            loss = self._loss_fn(self.network,
                                 *[_to_tensor(b) for b in batch])
            loss.backward()
            return [float(loss)]
        if self._train_step is None:
            self._train_step = TrainStep(self.network, self._optimizer,
                                         self._loss_fn)
        loss = self._train_step(*batch)
        return [float(loss)]

    def eval_batch(self, inputs, labels=None):
        was_training = self.network.training
        self.network.eval()
        inputs = _as_list(inputs)
        labels = _as_list(labels)
        outputs = self.network(*[_to_tensor(t) for t in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        loss = self._loss(*outs, *[_to_tensor(t) for t in labels]) \
            if self._loss else None
        metrics = []
        for m in self._metrics:
            res = m.compute(*outs, *[_to_tensor(t) for t in labels])
            m.update(*[np.asarray(r._data if isinstance(r, Tensor) else r)
                       for r in _as_list(res)])
            metrics.append(m.accumulate())
        if was_training:
            self.network.train()
        return ([float(loss)] if loss is not None else []), metrics

    def predict_batch(self, inputs):
        was_training = self.network.training
        self.network.eval()
        inputs = _as_list(inputs)
        out = self.network(*[_to_tensor(t) for t in inputs])
        if was_training:
            self.network.train()
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o.numpy() for o in outs]

    # ----------------------------------------------------------------- fit
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) \
                else DataLoader(eval_data, batch_size=batch_size)

        cbks = CallbackList(callbacks, model=self, verbose=verbose,
                            log_freq=log_freq,
                            default_progbar=verbose > 0,
                            save_dir=save_dir, save_freq=save_freq)
        cbks.on_begin("train", {"epochs": epochs,
                                "steps": _safe_len(loader),
                                "verbose": verbose,
                                "metrics": ["loss"] + [
                                    m.name() for m in self._metrics]})
        it = 0
        self.stop_training = False
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            logs = {}
            acc = max(1, accumulate_grad_batches)
            for step, batch in enumerate(loader):
                cbks.on_batch_begin("train", step, logs)
                ins, labs = _split_batch(batch, self._labels)
                if acc > 1:
                    losses = self.train_batch(ins, labs, update=False)
                    if (step + 1) % acc == 0:
                        self._optimizer.step()
                        self._optimizer.clear_grad()
                else:
                    losses = self.train_batch(ins, labs)
                logs = {"loss": losses[0], "step": step,
                        "batch_size": _batch_len(ins)}
                cbks.on_batch_end("train", step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
            if self._optimizer is not None and \
                    self._optimizer._lr_scheduler is not None:
                self._optimizer._lr_scheduler.step()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
                logs.update(eval_logs)
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cbks.on_end("train", logs)
        return self

    def _run_eval(self, loader, cbks=None):
        for m in self._metrics:
            m.reset()
        losses, n = 0.0, 0
        for batch in loader:
            ins, labs = _split_batch(batch, self._labels)
            ls, _ = self.eval_batch(ins, labs)
            if ls:
                losses += ls[0]
                n += 1
        logs = {}
        if n:
            logs["eval_loss"] = losses / n
        for m in self._metrics:
            logs["eval_" + m.name()] = m.accumulate()
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size,
                       num_workers=num_workers)
        return self._run_eval(loader)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size,
                       num_workers=num_workers)
        outputs = []
        for batch in loader:
            ins, _ = _split_batch(batch, self._labels, allow_no_label=True)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # ---------------------------------------------------------------- io
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        framework.io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework.io.save(self._optimizer.state_dict(),
                              path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(
            framework.io.load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(framework.io.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        return summary(self.network, input_size, dtypes=dtype)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _split_batch(batch, labels_spec, allow_no_label=False):
    batch = _as_list(batch)
    if len(batch) == 1 and allow_no_label:
        return batch, []
    n_labels = len(labels_spec) if labels_spec else 1
    return batch[:-n_labels], batch[-n_labels:]


def _batch_len(inputs):
    """Leading-dim size of the first input (samples/sec accounting)."""
    ins = _as_list(inputs)
    if not ins:
        return None
    shape = np.shape(getattr(ins[0], "_data", ins[0]))
    return int(shape[0]) if shape else None


def _safe_len(loader):
    try:
        return len(loader)
    except TypeError:
        return None
