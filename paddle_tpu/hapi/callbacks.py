"""Training callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks=None, model=None, verbose=2, log_freq=10,
                 default_progbar=True, save_dir=None, save_freq=1):
        cbs = list(callbacks or [])
        if default_progbar and not any(
                isinstance(c, ProgBarLogger) for c in cbs):
            cbs.insert(0, ProgBarLogger(log_freq, verbose=verbose))
        if save_dir and not any(
                isinstance(c, ModelCheckpoint) for c in cbs):
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        self.callbacks = cbs
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, params=None):
        for c in self.callbacks:
            c.set_params(params)
        self._call(f"on_{mode}_begin", params)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = (self.params or {}).get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._steps = 0
        self._t_epoch = time.time()

    def on_train_batch_end(self, step, logs=None):
        self._steps += 1
        if self.verbose and step % self.log_freq == 0:
            loss = (logs or {}).get("loss")
            msg = f"Epoch {self.epoch + 1}/{self.epochs} step {step}"
            if loss is not None:
                msg += f" - loss: {loss:.4f}"
            print(msg)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t_epoch
            extras = " ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                              if isinstance(v, (int, float)) and k != "step")
            print(f"Epoch {epoch + 1} done in {dt:.1f}s {extras}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and \
                (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def _better(self, cur, ref):
        if self.mode == "min":
            return cur < ref - self.min_delta
        return cur > ref + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch and not by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None)

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self._sched() is not None:
            self._sched().step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and self._sched() is not None:
            self._sched().step()
