"""Training callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "MetricsLogger"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks=None, model=None, verbose=2, log_freq=10,
                 default_progbar=True, save_dir=None, save_freq=1):
        cbs = list(callbacks or [])
        if default_progbar and not any(
                isinstance(c, ProgBarLogger) for c in cbs):
            cbs.insert(0, ProgBarLogger(log_freq, verbose=verbose))
        if save_dir and not any(
                isinstance(c, ModelCheckpoint) for c in cbs):
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        self.callbacks = cbs
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, params=None):
        for c in self.callbacks:
            c.set_params(params)
        self._call(f"on_{mode}_begin", params)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = (self.params or {}).get("epochs")
        self._t0 = time.perf_counter()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._steps = 0
        self._t_epoch = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        self._steps += 1
        if self.verbose and step % self.log_freq == 0:
            loss = (logs or {}).get("loss")
            msg = f"Epoch {self.epoch + 1}/{self.epochs} step {step}"
            if loss is not None:
                msg += f" - loss: {loss:.4f}"
            print(msg)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.perf_counter() - self._t_epoch
            extras = " ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items()
                              if isinstance(v, (int, float))
                              and k not in ("step", "batch_size"))
            print(f"Epoch {epoch + 1} done in {dt:.1f}s {extras}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and \
                (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def _better(self, cur, ref):
        if self.mode == "min":
            return cur < ref - self.min_delta
        return cur > ref + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True


def _host_rss_bytes():
    """Current host RSS (linux /proc; fallback: peak RSS from
    getrusage)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


class MetricsLogger(Callback):
    """Emit training telemetry into the observability registry: per-step
    wall time (histogram + last-value gauge), samples/sec, cumulative
    step/sample counters, per-device memory (device.memory_stats) and
    host RSS.  On train end the registry is dumped to FLAGS_metrics_dir
    (observability.dump) so a run leaves a readable artifact —
    `python tools/metrics_report.py <dir>`.

    memory_freq: poll device memory / RSS every N steps (it is a host
    round-trip per device; step timing itself is free)."""

    def __init__(self, memory_freq=1, dump_on_train_end=True):
        super().__init__()
        from .. import observability as obs
        self._obs = obs
        self.memory_freq = max(1, int(memory_freq))
        self.dump_on_train_end = dump_on_train_end
        self._h_step = obs.histogram(
            "hapi_step_seconds", "train step wall time")
        self._g_step = obs.gauge(
            "hapi_last_step_seconds", "most recent train step wall time")
        self._g_sps = obs.gauge(
            "hapi_samples_per_second", "throughput of the most recent "
            "train step")
        self._c_steps = obs.counter(
            "hapi_steps_total", "train steps completed")
        self._c_samples = obs.counter(
            "hapi_samples_total", "samples consumed by train steps")
        self._g_mem = obs.gauge(
            "device_bytes_in_use", "live device memory", ("device",))
        self._g_peak = obs.gauge(
            "device_peak_bytes_in_use", "peak device memory", ("device",))
        self._g_rss = obs.gauge("host_rss_bytes", "host process RSS")
        self._t0 = None

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._h_step.observe(dt)
        self._g_step.set(dt)
        self._c_steps.inc()
        n = (logs or {}).get("batch_size")
        if n:
            self._c_samples.inc(n)
            if dt > 0:
                self._g_sps.set(n / dt)
        if int(self._c_steps.value) % self.memory_freq == 0:
            self._poll_memory()

    def _poll_memory(self):
        import jax
        for d in jax.devices():
            stats = getattr(d, "memory_stats", lambda: {})() or {}
            key = f"{d.platform}:{d.id}"
            if "bytes_in_use" in stats:
                self._g_mem.labels(key).set(stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                self._g_peak.labels(key).set(stats["peak_bytes_in_use"])
        rss = _host_rss_bytes()
        if rss:
            self._g_rss.set(rss)

    def on_train_end(self, logs=None):
        self._poll_memory()
        if self.dump_on_train_end:
            self._obs.dump()    # no-op unless FLAGS_metrics_dir is set


class LRScheduler(Callback):
    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch and not by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None)

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self._sched() is not None:
            self._sched().step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and self._sched() is not None:
            self._sched().step()
