"""High-level API (reference: python/paddle/hapi — Model:1472, fit:2200,
callbacks, summary)."""
from .model import Model  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
    MetricsLogger)
from .summary import summary  # noqa: F401
