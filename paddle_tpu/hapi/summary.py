"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter/size report.  Returns {'total_params': n,
    'trainable_params': n}; prints a per-layer table."""
    rows = []
    total, trainable = 0, 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if getattr(p, "trainable", True):
            trainable += n
        rows.append((name, list(p.shape), n))
    w = max((len(r[0]) for r in rows), default=20) + 2
    print(f"{'Layer (param)':<{w}}{'Shape':<20}{'Params':>12}")
    print("-" * (w + 32))
    for name, shape, n in rows:
        print(f"{name:<{w}}{str(shape):<20}{n:>12,}")
    print("-" * (w + 32))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
