"""paddle.incubate.autotune (reference: python/paddle/incubate/autotune.py
set_config for kernel/layout/dataloader tuning).

On TPU, kernel algorithm search is XLA's autotuner (always on) and
layout tuning is XLA's layout assignment; this surface records the
config and applies the dataloader knobs it can.
"""
from __future__ import annotations

import json

_config = {"kernel": {"enable": True},
           "layout": {"enable": True},
           "dataloader": {"enable": False, "tuning_steps": 0}}


def set_config(config=None):
    global _config
    if config is None:
        return
    if isinstance(config, str):          # file path per reference API
        with open(config) as f:
            config = json.load(f)
    for k, v in config.items():
        _config.setdefault(k, {}).update(v if isinstance(v, dict) else
                                         {"enable": bool(v)})


def get_config():
    return dict(_config)
