"""paddle.incubate — experimental API surface.

Reference: python/paddle/incubate/ (42.4k LoC; the load-bearing pieces
are nn/functional fused ops — fused_rms_norm, fused_dropout_add,
fused_linear, fused_rotary_position_embedding — plus asp 2:4 sparsity
and the distributed MoE models re-exported here).
"""
from __future__ import annotations

from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from . import checkpoint  # noqa: F401

from .extras import (  # noqa: F401
    LookAhead, ModelAverage, identity_loss, softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle, graph_send_recv, graph_khop_sampler,
    graph_reindex, graph_sample_neighbors, segment_sum, segment_mean,
    segment_max, segment_min)
from .. import inference  # noqa: F401  (paddle.incubate.inference alias)

__all__ = ["nn", "asp", "autotune", "checkpoint", "inference", "LookAhead",
           "ModelAverage", "identity_loss", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "graph_send_recv",
           "graph_khop_sampler", "graph_reindex", "graph_sample_neighbors",
           "segment_sum", "segment_mean", "segment_max", "segment_min"]
