"""paddle.incubate — experimental API surface.

Reference: python/paddle/incubate/ (42.4k LoC; the load-bearing pieces
are nn/functional fused ops — fused_rms_norm, fused_dropout_add,
fused_linear, fused_rotary_position_embedding — plus asp 2:4 sparsity
and the distributed MoE models re-exported here).
"""
from __future__ import annotations

from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from . import checkpoint  # noqa: F401

__all__ = ["nn", "asp", "autotune", "checkpoint"]
