"""incubate extras (reference: python/paddle/incubate/__init__.py —
LookAhead/ModelAverage optimizer wrappers, graph sampling ops,
softmax-mask fusions, identity_loss)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage", "identity_loss",
           "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "graph_send_recv", "graph_khop_sampler", "graph_reindex",
           "graph_sample_neighbors", "segment_sum", "segment_mean",
           "segment_max", "segment_min"]


class LookAhead(Optimizer):
    """k-step lookahead wrapper (reference incubate/optimizer/lookahead.py):
    slow weights interpolate toward fast weights every k steps."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        super().__init__(learning_rate=inner_optimizer.get_lr(),
                         parameters=inner_optimizer._parameter_list,
                         name=name)
        self.inner_optimizer = inner_optimizer
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        self.alpha = alpha
        self.k = int(k)
        # slow weights snapshot the INITIAL params (reference lookahead.py)
        # so the first k-step sync actually damps the fast trajectory
        self._slow = {(p.name or str(id(p))): p._data
                      for p in self._parameter_list}
        self._steps = 0

    def step(self):
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p in self._parameter_list:
                key = p.name or str(id(p))
                slow = self._slow[key]
                slow = slow + self.alpha * (p._data - slow)
                self._slow[key] = slow
                p._data = slow.astype(p._data.dtype)

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        state = self.inner_optimizer.state_dict()
        for k, v in self._slow.items():
            state[f"{k}.slow"] = Tensor(v)
        state["@lookahead_steps"] = self._steps
        return state

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage(Optimizer):
    """Running average of parameters (reference incubate/optimizer/
    modelaverage.py): apply()/restore() swap the averaged weights in."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=list(parameters or []),
                         name=name)
        self.rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        # block rotation bounds the window to <= 2*max_average_window
        # (reference modelaverage.py rotates sum_1/sum_2/sum_3 the same way)
        self._sums = {}
        self._old_sums = {}
        self._counts = 0
        self._old_counts = 0
        self._backup = {}

    def step(self):
        self._counts += 1
        for p in self._parameter_list:
            key = p.name or str(id(p))
            self._sums[key] = self._sums.get(key, 0.0) + \
                p._data.astype(jnp.float32)
        window = max(int(self.rate * (self._counts + self._old_counts)),
                     self.min_window)
        window = min(window, self.max_window)
        if self._counts >= window:
            self._old_sums = dict(self._sums)
            self._old_counts = self._counts
            self._sums = {}
            self._counts = 0

    def apply(self, executor=None, need_restore=True):
        mgr = self

        class _Guard:
            def __enter__(self):
                mgr.apply_now()
                return self

            def __exit__(self, *e):
                if need_restore:
                    mgr.restore_now()
                return False

        return _Guard()

    def apply_now(self):
        total = self._counts + self._old_counts
        if not total:
            return
        for p in self._parameter_list:
            key = p.name or str(id(p))
            s = self._sums.get(key, 0.0) + self._old_sums.get(key, 0.0)
            if not isinstance(s, float):
                self._backup[key] = p._data
                p._data = (s / total).astype(p._data.dtype)

    def restore_now(self):
        for p in self._parameter_list:
            key = p.name or str(id(p))
            if key in self._backup:
                p._data = self._backup.pop(key)

    def restore(self, executor=None):
        self.restore_now()

    def minimize(self, loss, **kw):
        self.step()


def identity_loss(x, reduction="none"):
    """Mark a loss for IPU pipelining (reference incubate identity_loss);
    numerically reduce-or-identity."""
    import paddle_tpu as P
    if reduction in (0, "sum"):
        return P.sum(x)
    if reduction in (1, "mean"):
        return P.mean(x)
    return x


def softmax_mask_fuse(x, mask, name=None):
    """Fused masked softmax (reference incubate/operators/
    softmax_mask_fuse.py; fused_softmax_mask kernel) — XLA fuses the
    chain."""
    from ..ops.registry import apply_op

    def body(xx, mm):
        return jax.nn.softmax(xx + mm, axis=-1)

    return apply_op("softmax_mask_fuse", body, (x, mask), {})


def softmax_mask_fuse_upper_triangle(x):
    from ..nn.functional import softmax_mask_fuse_upper_triangle as f
    return f(x)


# ------------------------------------------------------------- graph ops

def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """(reference incubate graph_send_recv → geometric.send_u_recv)"""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighbor sampling over CSC (reference incubate/operators/
    graph_khop_sampler.py).  Host-side sampling — eager only."""
    rng = np.random.default_rng()
    rows = np.asarray(row.numpy() if hasattr(row, "numpy") else row)
    cptr = np.asarray(colptr.numpy() if hasattr(colptr, "numpy")
                      else colptr)
    nodes = np.asarray(input_nodes.numpy() if hasattr(input_nodes, "numpy")
                       else input_nodes).reshape(-1)
    edge_src, edge_dst, edge_ids = [], [], []
    frontier = nodes
    seen = list(nodes)
    for k in sample_sizes:
        nxt = []
        for n in frontier:
            beg, end = int(cptr[n]), int(cptr[n + 1])
            neigh = rows[beg:end]
            eids = np.arange(beg, end)
            if len(neigh) > k:
                sel = rng.choice(len(neigh), size=k, replace=False)
                neigh = neigh[sel]
                eids = eids[sel]
            for m, e in zip(neigh, eids):
                edge_src.append(int(m))
                edge_dst.append(int(n))
                edge_ids.append(int(e))
                nxt.append(int(m))
        frontier = np.asarray(nxt, np.int64)
        seen += nxt
    uniq, inv = np.unique(np.asarray(seen, np.int64), return_inverse=True)
    remap = {int(u): i for i, u in enumerate(uniq)}
    es = np.asarray([remap[s] for s in edge_src], np.int64)
    ed = np.asarray([remap[d] for d in edge_dst], np.int64)
    out = (Tensor(jnp.asarray(es)), Tensor(jnp.asarray(ed)),
           Tensor(jnp.asarray(uniq)),
           Tensor(jnp.asarray(np.asarray(edge_ids, np.int64))))
    return out if return_eids else out[:3]


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, return_eids=return_eids)


def _eager_num_segments(segment_ids):
    # concrete in eager mode; under jit callers must use the geometric
    # API with an explicit out_size (XLA needs static shapes)
    return int(np.asarray(
        segment_ids.numpy() if hasattr(segment_ids, "numpy")
        else segment_ids).max()) + 1


def segment_sum(data, segment_ids, name=None):
    from ..geometric import segment_sum as f
    return f(data, segment_ids, _eager_num_segments(segment_ids))


def segment_mean(data, segment_ids, name=None):
    from ..geometric import segment_mean as f
    return f(data, segment_ids, _eager_num_segments(segment_ids))


def segment_max(data, segment_ids, name=None):
    from ..geometric import segment_max as f
    return f(data, segment_ids, _eager_num_segments(segment_ids))


def segment_min(data, segment_ids, name=None):
    from ..geometric import segment_min as f
    return f(data, segment_ids, _eager_num_segments(segment_ids))
