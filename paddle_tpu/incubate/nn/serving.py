"""Serving fused-op surface (the production LLM-inference ops).

Reference: python/paddle/incubate/nn/functional/
  block_multihead_attention.py:34, masked_multihead_attention.py,
  fused_moe.py, swiglu.py, fused_matmul_bias.py, blha_get_max_len.py,
  variable_length_memory_efficient_attention.py, fused_transformer.py:976
(CUDA kernels under paddle/phi/kernels/fusion/gpu/).

TPU formulation: the engines already exist in-repo — the paged
block-table cache + Pallas paged/decode kernels
(ops/pallas/paged_attention.py, decode_attention.py), the sort-based
MoE dispatch (distributed/moe.py), Pallas rms_norm — and these
functions give them the reference-shaped serving API so PaddleNLP-style
inference code ports unchanged.  Static shapes throughout: ragged
batches travel as padded arrays + explicit length/offset tensors (the
same protocol the reference's packed-token kernels use).

Quantized-cache / shift / smooth knobs raise NotImplementedError
loudly — nothing silently computes an unquantized answer under a quant
flag.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.registry import apply_op

__all__ = [
    "swiglu", "fused_matmul_bias", "blha_get_max_len",
    "variable_length_memory_efficient_attention",
    "masked_multihead_attention", "block_multihead_attention",
    "fused_moe", "fused_multi_transformer",
]


def _reject(**kwargs):
    bad = [k for k, v in kwargs.items() if v is not None]
    if bad:
        raise NotImplementedError(
            f"arguments not supported on the TPU backend: {bad} "
            "(quantized-cache/shift/smooth serving knobs)")


# ------------------------------------------------------------- primitives
def swiglu(x, y=None, name=None):
    """reference swiglu.py: silu(x) * y; with y=None, x splits in half."""
    def body(a, b=None):
        if b is None:
            a, b = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(a) * b

    args = (x,) if y is None else (x, y)
    return apply_op("swiglu", body, args, {})


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """reference fused_matmul_bias.py (cublasLt epilogue fusion — XLA
    fuses the bias add on TPU)."""
    def body(a, b, c=None):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out if c is None else out + c

    args = (x, y) if bias is None else (x, y, bias)
    return apply_op("fused_matmul_bias", body, args, {})


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size):
    """reference blha_get_max_len.py: (max encoder len, max decoder len)
    this step — the sizing scalars block_multihead_attention consumes."""
    def body(enc, dec):
        return (jnp.max(enc).reshape((1,)).astype(jnp.int32),
                jnp.max(dec).reshape((1,)).astype(jnp.int32))

    return apply_op("blha_get_max_len", body,
                    (seq_lens_encoder, seq_lens_decoder), {})


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0):
    """reference variable_length_memory_efficient_attention.py (cutlass
    memory-efficient kernel): padded [B, H, S, D] attention with
    per-sequence valid lengths."""
    def body(q, k, v, ql, kl, m=None):
        b, nh, s, d = q.shape
        kvh, sk = k.shape[1], k.shape[2]
        rep = nh // kvh
        kq = jnp.repeat(k, rep, axis=1) if rep > 1 else k
        vq = jnp.repeat(v, rep, axis=1) if rep > 1 else v
        sm = (1.0 / np.sqrt(d)) if scale is None else scale
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, kq,
                            preferred_element_type=jnp.float32) * sm
        qpos = jnp.arange(s)[None, :, None]
        kpos = jnp.arange(sk)[None, None, :]
        ok = (qpos < ql.reshape(-1, 1, 1)) & (kpos < kl.reshape(-1, 1, 1))
        if causal:
            ok = ok & (kpos <= qpos + pre_cache_length)
        logits = jnp.where(ok[:, None], logits, -jnp.inf)
        if m is not None:
            logits = logits + m.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        # fully-masked query rows give NaN rows; zero them (padding)
        probs = jnp.where(jnp.isfinite(probs), probs, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), vq)

    args = (query, key, value, seq_lens, kv_seq_lens)
    if mask is not None:
        args = args + (mask,)
    return apply_op("variable_length_memory_efficient_attention", body,
                    args, {})


# ------------------------------------------------------- rotary embedding
def _rot_half(x, neox):
    """The rotate-half map — ONE copy of the neox-vs-interleaved
    convention, shared with fused_rotary_position_embedding."""
    if neox:
        a, b = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([-b, a], axis=-1)
    x2 = x.reshape(*x.shape[:-1], -1, 2)
    a, b = x2[..., 0], x2[..., 1]
    return jnp.stack([-b, a], axis=-1).reshape(x.shape)


def _apply_rope(x, cos, sin, neox):
    """x [..., hd]; cos/sin broadcastable [..., hd]."""
    return x * cos + _rot_half(x, neox) * sin


def _rope_tables(rope_emb, hd, neox=False):
    """Normalize a reference-shaped rotary table to (cos [S, hd],
    sin [S, hd]).  Accepted: any layout that squeezes to [2, S, hd] or
    [2, S, hd//2] (half tables tile per the neox/interleaved style) —
    the reference's [2, 1, S, 1, hd(/2)] serving layouts included.
    Anything else (per-batch tables, no leading cos/sin axis) raises
    loudly rather than silently mis-rotating."""
    r = jnp.asarray(rope_emb)
    shape = [s for s in r.shape if s != 1]
    # squeezing ALL size-1 dims would also collapse a legitimate
    # single-position table ([2, 1, hd] layouts: S == 1, the first
    # decode step) down to [2, hd] — keep a sequence axis in that case
    if len(shape) == 2 and shape[0] == 2:
        shape = [2, 1, shape[1]]
    r = r.reshape(shape)
    if r.ndim != 3 or r.shape[0] != 2 \
            or r.shape[-1] not in (hd, hd // 2):
        raise NotImplementedError(
            f"rotary table of shape {list(jnp.asarray(rope_emb).shape)} "
            f"is not supported: expected a layout squeezing to "
            f"[2, S, {hd}] or [2, S, {hd // 2}] (per-batch rotary "
            "tables have no TPU lowering here)")
    if r.shape[-1] == hd // 2:
        r = (jnp.concatenate([r, r], axis=-1) if neox
             else jnp.repeat(r, 2, axis=-1))
    return r[0], r[1]


# --------------------------------------------------- masked MHA (decode)
def masked_multihead_attention(
        x, cache_kv=None, bias=None, src_mask=None, cum_offsets=None,
        sequence_lengths=None, rotary_tensor=None, beam_cache_offset=None,
        qkv_out_scale=None, out_shift=None, out_smooth=None, seq_len=1,
        rotary_emb_dims=0, use_neox_rotary_style=False,
        compute_dtype="default", out_scale=-1, quant_round_type=1,
        quant_max_bound=127.0, quant_min_bound=-127.0):
    """reference masked_multihead_attention.py: one decode step over a
    dense [2, B, kvh, T, hd] cache.  Writes this step's k/v at each
    sequence's position and attends over the visible prefix (the
    decode-GEMV Pallas kernel when mask-free).  Returns
    (out [B, nh*hd], updated cache_kv)."""
    _reject(qkv_out_scale=qkv_out_scale, out_shift=out_shift,
            out_smooth=out_smooth, beam_cache_offset=beam_cache_offset,
            cum_offsets=cum_offsets)
    if out_scale != -1:
        raise NotImplementedError(
            "masked_multihead_attention out_scale (int8 output "
            "quantization) is not supported on the TPU backend")
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    if sequence_lengths is None:
        raise ValueError(
            "masked_multihead_attention on TPU requires sequence_lengths "
            "(the cache write position per sequence); the reference "
            "tracks it kernel-side, here it must be explicit")

    def body(xq, cache, b_=None, m_=None, lens=None, rot=None):
        kvh, t, hd = cache.shape[2], cache.shape[3], cache.shape[4]
        bsz = xq.shape[0]
        nh = (xq.shape[1] - 2 * kvh * hd) // hd
        if b_ is not None:
            xq = xq + b_.reshape(1, -1)
        q = xq[:, :nh * hd].reshape(bsz, nh, hd)
        k = xq[:, nh * hd:(nh + kvh) * hd].reshape(bsz, kvh, hd)
        v = xq[:, (nh + kvh) * hd:].reshape(bsz, kvh, hd)
        pos = (lens.reshape(-1).astype(jnp.int32) if lens is not None
               else jnp.zeros((bsz,), jnp.int32))
        if rot is not None:
            cos_t, sin_t = _rope_tables(rot, hd, use_neox_rotary_style)
            cos = cos_t[pos][:, None, :]
            sin = sin_t[pos][:, None, :]
            q = _apply_rope(q, cos, sin, use_neox_rotary_style)
            k = _apply_rope(k, cos, sin, use_neox_rotary_style)
        bi = jnp.arange(bsz)[:, None]
        hi = jnp.arange(kvh)[None, :]
        kc = cache[0].at[bi, hi, pos[:, None]].set(k)
        vc = cache[1].at[bi, hi, pos[:, None]].set(v)
        if m_ is None:
            from ...ops.pallas.decode_attention import decode_attention
            out = decode_attention(q, kc, vc, pos)
        else:
            rep = nh // kvh
            kq = jnp.repeat(kc, rep, axis=1)
            vq = jnp.repeat(vc, rep, axis=1)
            logits = jnp.einsum("bhd,bhtd->bht", q, kq,
                                preferred_element_type=jnp.float32) \
                / np.sqrt(hd)
            tpos = jnp.arange(t)
            ok = tpos[None, None, :] <= pos[:, None, None]
            logits = jnp.where(ok, logits, -jnp.inf)
            logits = logits + m_.reshape(bsz, 1, -1).astype(jnp.float32)
            probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            out = jnp.einsum("bht,bhtd->bhd", probs, vq)
        return (out.reshape(bsz, nh * hd),
                jnp.stack([kc, vc], axis=0))

    # optional tensors travel positionally; a None stays a static leaf
    return apply_op("masked_multihead_attention", body,
                    (x, cache_kv, bias, src_mask, sequence_lengths,
                     rotary_tensor), {})


# ------------------------------------------------ block MHA (paged cache)
def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets, cum_offsets, cu_seqlens_q,
        cu_seqlens_k, block_tables, pre_key_cache=None,
        pre_value_cache=None, cache_k_quant_scales=None,
        cache_v_quant_scales=None, cache_k_dequant_scales=None,
        cache_v_dequant_scales=None, qkv_out_scale=None, qkv_bias=None,
        out_shift=None, out_smooth=None, max_enc_len_this_time=None,
        max_dec_len_this_time=None, rope_emb=None, mask=None,
        tgt_mask=None, max_seq_len=-1, block_size=64, use_neox_style=False,
        use_dynamic_cachekv_quant=False, quant_round_type=1,
        quant_max_bound=127.0, quant_min_bound=-127.0, out_scale=-1,
        compute_dtype="default", rope_theta=10000.0):
    """reference block_multihead_attention.py:34 (the PaddleNLP serving
    attention): packed variable-length tokens + paged block-table KV
    caches, one op for mixed prefill/decode batches.

    TPU formulation: tokens scatter to a padded [B, T, ...] layout via
    ``padding_offsets`` (static T = max_seq_len), this step's k/v
    scatter into the block pools through ``block_tables``, and every
    query attends its sequence's visible prefix gathered from the
    updated pools — all static shapes, jit-compatible.  The pool layout
    [max_block_num, kv_heads, block_size, head_dim] is exactly
    ops/pallas/paged_attention.py's; the pure-decode fast path in
    models/generation.py uses that kernel directly.

    Returns (out [token_num, nh*hd], qkv, key_cache, value_cache).
    """
    _reject(pre_key_cache=pre_key_cache, pre_value_cache=pre_value_cache,
            cache_k_quant_scales=cache_k_quant_scales,
            cache_v_quant_scales=cache_v_quant_scales,
            cache_k_dequant_scales=cache_k_dequant_scales,
            cache_v_dequant_scales=cache_v_dequant_scales,
            qkv_out_scale=qkv_out_scale, out_shift=out_shift,
            out_smooth=out_smooth, tgt_mask=tgt_mask)
    if out_scale != -1 or use_dynamic_cachekv_quant:
        raise NotImplementedError(
            "block_multihead_attention quantized output / dynamic cache-"
            "KV quant is not supported on the TPU backend")

    def body(qkv_, kc, vc, dec_lens, this_lens, pad_off, tables,
             b_=None, rope=None, m_=None):
        tok = qkv_.shape[0]
        nblocks, kvh, bs, hd = kc.shape
        nh = (qkv_.shape[1] - 2 * kvh * hd) // hd
        bsz = this_lens.shape[0]
        T = max_seq_len if max_seq_len > 0 else tok
        if b_ is not None:
            qkv_ = qkv_ + b_.reshape(1, -1)
        q = qkv_[:, :nh * hd].reshape(tok, nh, hd)
        k = qkv_[:, nh * hd:(nh + kvh) * hd].reshape(tok, kvh, hd)
        v = qkv_[:, (nh + kvh) * hd:].reshape(tok, kvh, hd)
        dec = dec_lens.reshape(-1).astype(jnp.int32)
        this = this_lens.reshape(-1).astype(jnp.int32)

        # packed -> padded (reference get_padding_offset protocol:
        # padded_index = token_index + padding_offsets[token_index])
        pidx = jnp.arange(tok) + pad_off.reshape(-1).astype(jnp.int32)

        def to_padded(a):
            buf = jnp.zeros((bsz * T,) + a.shape[1:], a.dtype)
            return buf.at[pidx].set(a, mode="drop") \
                .reshape(bsz, T, *a.shape[1:])

        qp, kp, vp = to_padded(q), to_padded(k), to_padded(v)
        p_in_seq = jnp.arange(T)[None, :]
        valid = p_in_seq < this[:, None]                   # [B, T]
        cache_pos = dec[:, None] + p_in_seq                # absolute pos

        if rope is not None:
            cos_t, sin_t = _rope_tables(rope, hd, use_neox_style)
            cp = jnp.clip(cache_pos, 0, cos_t.shape[0] - 1)
            cos = cos_t[cp][:, :, None, :]
            sin = sin_t[cp][:, :, None, :]
            qp = _apply_rope(qp, cos, sin, use_neox_style)
            kp = _apply_rope(kp, cos, sin, use_neox_style)

        # k/v scatter into the pools through the block tables
        blk = jnp.take_along_axis(
            tables.astype(jnp.int32),
            jnp.clip(cache_pos // bs, 0, tables.shape[1] - 1), axis=1)
        slot = (blk * bs + cache_pos % bs).reshape(-1)
        slot = jnp.where(valid.reshape(-1), slot, nblocks * bs)  # dropped

        def write(pool, new):
            flat = pool.transpose(0, 2, 1, 3).reshape(-1, kvh, hd)
            flat = flat.at[slot].set(new.reshape(-1, kvh, hd),
                                     mode="drop")
            return flat.reshape(nblocks, bs, kvh, hd).transpose(0, 2, 1, 3)

        kc2, vc2 = write(kc, kp), write(vc, vp)

        # every query attends its sequence's prefix from the pools
        maxp = tables.shape[1]
        kb = kc2[tables.astype(jnp.int32)] \
            .transpose(0, 2, 1, 3, 4).reshape(bsz, kvh, maxp * bs, hd)
        vb = vc2[tables.astype(jnp.int32)] \
            .transpose(0, 2, 1, 3, 4).reshape(bsz, kvh, maxp * bs, hd)
        rep = nh // kvh
        qg = qp.reshape(bsz, T, kvh, rep, hd)
        logits = jnp.einsum("btgrd,bgsd->btgrs", qg, kb,
                            preferred_element_type=jnp.float32) \
            / np.sqrt(hd)
        spos = jnp.arange(maxp * bs)[None, None, :]
        ok = spos <= cache_pos[:, :, None]                 # [B, T, S]
        ok = ok & valid[:, :, None]
        logits = jnp.where(ok[:, :, None, None, :], logits, -jnp.inf)
        if m_ is not None:
            logits = logits + m_.astype(jnp.float32).reshape(
                bsz, 1, 1, 1, -1)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(jnp.isfinite(probs), probs, 0.0)
        outp = jnp.einsum("btgrs,bgsd->btgrd", probs.astype(qp.dtype), vb)
        out = outp.reshape(bsz * T, nh * hd)[pidx]
        return out, qkv_, kc2, vc2

    args = (qkv, key_cache, value_cache, seq_lens_decoder,
            seq_lens_this_time, padding_offsets, block_tables,
            qkv_bias, rope_emb, mask)
    return apply_op("block_multihead_attention", body, args, {})


# ----------------------------------------------------------------- MoE
def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True):
    """reference fused_moe.py: x [B, S, D], gate scores [B, S, E],
    expert weights ffn1 [E, D, F*2] (gated: silu(u) * v halves when
    F*2 == 2 * ffn2-in, plain gelu otherwise), ffn2 [E, F, D].

    Delegates to the sort-based dropless dispatch engine
    (distributed/moe.py sort_dispatch_combine) — tokens route as pure
    gathers, no capacity loss (capacity = token count).
    """
    if quant_method not in ("None", None, "none"):
        raise NotImplementedError(
            f"fused_moe quant_method={quant_method!r} is not supported; "
            "use weight-only quant via models/generation.quantize_state")
    _reject(ffn1_scale=ffn1_scale, ffn2_scale=ffn2_scale)

    def body(x_, gates, w1, w2, b1=None, b2=None):
        from ...distributed.moe import sort_dispatch_combine

        lead = x_.shape[:-1]
        d = x_.shape[-1]
        e, _, f2 = w1.shape
        fin = w2.shape[1]
        xt = x_.reshape(-1, d)
        gl = gates.reshape(-1, e).astype(jnp.float32)
        s = xt.shape[0]
        gv, idx = jax.lax.top_k(jax.nn.softmax(gl, axis=-1), moe_topk)
        if norm_topk_prob:
            gv = gv / jnp.sum(gv, axis=-1, keepdims=True)

        gated = f2 == 2 * fin

        def ffn(expert_in):                    # [E, C, D] -> [E, C, D]
            h = jnp.einsum("ecd,edf->ecf", expert_in, w1)
            if b1 is not None:
                h = h + b1.reshape(e, 1, f2)
            if gated:
                u, g = jnp.split(h, 2, axis=-1)
                h = jax.nn.silu(u) * g
            else:
                h = jax.nn.gelu(h)
            out = jnp.einsum("ecf,efd->ecd", h, w2)
            if b2 is not None:
                out = out + b2.reshape(e, 1, d)
            return out

        y = sort_dispatch_combine(xt, idx.astype(jnp.int32),
                                  gv.astype(xt.dtype), e, s, ffn)
        return y.reshape(*lead, d)

    return apply_op("fused_moe", body,
                    (x, gate_weight, ffn1_weight, ffn2_weight,
                     ffn1_bias, ffn2_bias), {})


# -------------------------------------------------- fused_multi_transformer
def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-05, residual_alpha=1.0, cache_kvs=None,
        beam_offset=None, pre_caches=None, seq_lens=None, rotary_embs=None,
        time_step=None, attn_mask=None, dropout_rate=0.0,
        rotary_emb_dims=0, activation="gelu", training=False,
        mode="upscale_in_train", trans_qkvw=True, ring_id=-1,
        norm_type="layernorm", use_neox_rotary_style=False,
        gqa_group_size=-1, name=None):
    """reference fused_transformer.py:976: the stacked serving decoder —
    N pre-LN blocks, dense [2, B, kvh, T, hd] caches, one op.

    Prefill (time_step=None): causal self-attention over [B, S, D],
    caches filled for positions [0, S).  Decode (time_step given): one
    token per sequence appended at ``time_step`` and attended against
    the prefix.  Returns (out, cache_kvs) when caches are given, else
    out — matching the reference contract.
    """
    _reject(beam_offset=beam_offset, pre_caches=pre_caches)
    if not pre_layer_norm:
        raise NotImplementedError(
            "fused_multi_transformer: only pre_layer_norm=True (the "
            "reference serving configuration) is supported")
    if ring_id != -1:
        raise NotImplementedError(
            "fused_multi_transformer ring_id: wrap in shard_map / use "
            "distributed.fleet tensor parallel instead")

    n_layers = len(qkv_weights)
    decode = time_step is not None

    def norm(h, w, b):
        hf = h.astype(jnp.float32)
        if norm_type == "rmsnorm":
            hf = hf * jax.lax.rsqrt(
                jnp.mean(hf * hf, axis=-1, keepdims=True) + epsilon)
        else:
            mu = jnp.mean(hf, axis=-1, keepdims=True)
            var = jnp.var(hf, axis=-1, keepdims=True)
            hf = (hf - mu) * jax.lax.rsqrt(var + epsilon)
        out = hf.astype(h.dtype) * w
        return out + b if b is not None else out

    def act_fn(h):
        if activation in ("swiglu", "geglu"):
            u, g = jnp.split(h, 2, axis=-1)
            return (jax.nn.silu(u) if activation == "swiglu"
                    else jax.nn.gelu(u)) * g
        return getattr(jax.nn, activation)(h)

    def body(x_, *flat):
        it = iter(flat)

        def take(lst):
            return [next(it) if w is not None else None for w in lst]

        lns = take(ln_scales)
        lnb = take(ln_biases or [None] * n_layers)
        qkvw = take(qkv_weights)
        qkvb = take(qkv_biases or [None] * n_layers)
        outw = take(linear_weights)
        outb = take(linear_biases or [None] * n_layers)
        flns = take(ffn_ln_scales)
        flnb = take(ffn_ln_biases or [None] * n_layers)
        f1w = take(ffn1_weights)
        f1b = take(ffn1_biases or [None] * n_layers)
        f2w = take(ffn2_weights)
        f2b = take(ffn2_biases or [None] * n_layers)
        caches = take(cache_kvs) if cache_kvs is not None else None
        lens = next(it) if seq_lens is not None else None
        ts = next(it) if time_step is not None else None
        am = next(it) if attn_mask is not None else None
        rot = next(it) if rotary_embs is not None else None

        bsz, s, d = x_.shape
        new_caches = []
        h = x_
        for i in range(n_layers):
            resid = h
            hn = norm(h, lns[i], lnb[i])
            w = qkvw[i]
            # reference layout: [3, nh, hd, D] when trans_qkvw else
            # [D, 3, nh, hd] (fused_transformer.py qkv_weight docs)
            if w.ndim == 4:
                nh, hd = ((w.shape[1], w.shape[2]) if trans_qkvw
                          else (w.shape[2], w.shape[3]))
                w2d = (w.reshape(-1, d) if trans_qkvw
                       else w.reshape(d, -1).T)
            elif caches is not None:
                kvh0, hd = caches[i].shape[2], caches[i].shape[4]
                w2d = w.reshape(-1, d) if trans_qkvw else w.T
                nh = (w2d.shape[0] - 2 * kvh0 * hd) // hd
            else:
                raise ValueError(
                    "fused_multi_transformer: pass 4-D qkv weights "
                    "([3, nh, hd, D]) or caches so head shape is known")
            qkv_ = hn.reshape(-1, d) @ w2d.T
            if qkvb[i] is not None:
                qkv_ = qkv_ + qkvb[i].reshape(1, -1)
            width = w2d.shape[0]
            if caches is not None:
                kvh, hd = caches[i].shape[2], caches[i].shape[4]
                nh = (width - 2 * kvh * hd) // hd
            else:
                kvh = nh
            qkv3 = qkv_.reshape(bsz, s, width)
            q = qkv3[..., :nh * hd].reshape(bsz, s, nh, hd)
            k = qkv3[..., nh * hd:(nh + kvh) * hd] \
                .reshape(bsz, s, kvh, hd)
            v = qkv3[..., (nh + kvh) * hd:].reshape(bsz, s, kvh, hd)

            if decode:
                pos = (lens.reshape(-1).astype(jnp.int32)
                       if lens is not None
                       else jnp.full((bsz,), ts.reshape(()),
                                     dtype=jnp.int32))
            else:
                pos = None
            if rot is not None:
                cos_t, sin_t = _rope_tables(rot, hd,
                                             use_neox_rotary_style)
                if decode:
                    cos = cos_t[pos][:, None, None, :]
                    sin = sin_t[pos][:, None, None, :]
                else:
                    cos = cos_t[None, :s, None, :]
                    sin = sin_t[None, :s, None, :]
                q = _apply_rope(q, cos, sin, use_neox_rotary_style)
                k = _apply_rope(k, cos, sin, use_neox_rotary_style)

            rep = nh // kvh
            if decode:
                cache = caches[i]
                bi = jnp.arange(bsz)[:, None]
                hi = jnp.arange(kvh)[None, :]
                kc = cache[0].at[bi, hi, pos[:, None]].set(
                    k.reshape(bsz, kvh, hd))
                vc = cache[1].at[bi, hi, pos[:, None]].set(
                    v.reshape(bsz, kvh, hd))
                new_caches.append(jnp.stack([kc, vc], axis=0))
                from ...ops.pallas.decode_attention import decode_attention
                attn = decode_attention(
                    q.reshape(bsz, nh, hd), kc, vc, pos) \
                    .reshape(bsz, 1, nh * hd)
            else:
                if caches is not None:
                    cache = caches[i]
                    t = cache.shape[3]
                    kc = cache[0].at[:, :, :s].set(
                        k.transpose(0, 2, 1, 3))
                    vc = cache[1].at[:, :, :s].set(
                        v.transpose(0, 2, 1, 3))
                    new_caches.append(jnp.stack([kc, vc], axis=0))
                kq = jnp.repeat(k, rep, axis=2) if rep > 1 else k
                vq = jnp.repeat(v, rep, axis=2) if rep > 1 else v
                logits = jnp.einsum(
                    "bqhd,bkhd->bhqk", q, kq,
                    preferred_element_type=jnp.float32) / np.sqrt(hd)
                qpos = jnp.arange(s)[:, None]
                kpos = jnp.arange(s)[None, :]
                logits = jnp.where(kpos <= qpos, logits, -jnp.inf)
                if am is not None:
                    logits = logits + am.astype(jnp.float32)
                if lens is not None:
                    ok = jnp.arange(s)[None, None, None, :] \
                        < lens.reshape(-1, 1, 1, 1)
                    logits = jnp.where(ok, logits, -jnp.inf)
                probs = jax.nn.softmax(logits, axis=-1)
                probs = jnp.where(jnp.isfinite(probs), probs, 0.0)
                attn = jnp.einsum("bhqk,bkhd->bqhd",
                                  probs.astype(q.dtype), vq) \
                    .reshape(bsz, s, nh * hd)

            proj = attn.reshape(-1, nh * hd) @ outw[i].reshape(
                nh * hd, d)
            if outb[i] is not None:
                proj = proj + outb[i].reshape(1, -1)
            proj = proj.reshape(bsz, s, d)
            if training and dropout_rate > 0.0:
                from ...framework import random as _random
                keep = jax.random.bernoulli(
                    _random.split_key(), 1.0 - dropout_rate, proj.shape)
                proj = jnp.where(keep, proj / (1.0 - dropout_rate), 0.0) \
                    if mode == "upscale_in_train" \
                    else jnp.where(keep, proj, 0.0)
            h = resid * residual_alpha + proj

            resid = h
            hn = norm(h, flns[i], flnb[i])
            f1 = hn.reshape(-1, d) @ f1w[i].reshape(d, -1)
            if f1b[i] is not None:
                f1 = f1 + f1b[i].reshape(1, -1)
            f1 = act_fn(f1)
            f2 = f1 @ f2w[i].reshape(f1.shape[-1], d)
            if f2b[i] is not None:
                f2 = f2 + f2b[i].reshape(1, -1)
            h = resid * residual_alpha + f2.reshape(bsz, s, d)
        if caches is not None:
            return h, new_caches
        return h

    flat_args = [x]
    for lst in (ln_scales, ln_biases or [], qkv_weights, qkv_biases or [],
                linear_weights, linear_biases or [], ffn_ln_scales,
                ffn_ln_biases or [], ffn1_weights, ffn1_biases or [],
                ffn2_weights, ffn2_biases or []):
        flat_args += [w for w in lst if w is not None]
    if cache_kvs is not None:
        flat_args += [c for c in cache_kvs if c is not None]
    for extra in (seq_lens, time_step, attn_mask, rotary_embs):
        if extra is not None:
            flat_args.append(extra)
    return apply_op("fused_multi_transformer", body, tuple(flat_args), {})
