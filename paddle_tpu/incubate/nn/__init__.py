"""paddle.incubate.nn — fused layers + functional.

Reference: python/paddle/incubate/nn/ (FusedMultiHeadAttention,
FusedFeedForward layer classes over the fused_* functional ops)."""
from __future__ import annotations

from . import functional  # noqa: F401
from .layers import (FusedMultiHeadAttention, FusedFeedForward,
    FusedLinear, FusedDropoutAdd,
    FusedBiasDropoutResidualLayerNorm,
    FusedTransformerEncoderLayer, FusedMultiTransformer)  # noqa: F401

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedLinear", "FusedDropoutAdd",
           "FusedBiasDropoutResidualLayerNorm",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer"]
