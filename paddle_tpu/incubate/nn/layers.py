"""Fused layer classes (reference:
python/paddle/incubate/nn/layer/fused_transformer.py)."""
from __future__ import annotations

import numpy as np

from ...nn.layer import Layer
from . import functional as IF

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward", "FusedLinear",
           "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer"]


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        from ...nn.initializer import Constant
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn.initializer import Constant
        self._normalize_before = normalize_before
        self._activation = activation
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = act_dropout_rate if act_dropout_rate \
            is not None else dropout_rate
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr,
            default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter(
            [d_model], attr=ln1_bias_attr, is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr,
            default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter(
            [d_model], attr=ln2_bias_attr, is_bias=True)

    def forward(self, src, cache=None):
        return IF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate,
            activation=self._activation, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon,
            pre_layer_norm=self._normalize_before, training=self.training)


class FusedLinear(Layer):
    """(reference incubate/nn/layer/fused_linear.py): on TPU the fusion is
    XLA's — one matmul+bias kernel."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        import math
        from ...nn.initializer import Uniform
        bound = 1.0 / math.sqrt(in_features)
        shape = (out_features, in_features) if transpose_weight \
            else (in_features, out_features)
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=Uniform(-bound,
                                                                 bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)
        self.transpose_weight = transpose_weight

    def forward(self, x):
        w = self.weight.t() if self.transpose_weight else self.weight
        out = x.matmul(w)
        return out + self.bias if self.bias is not None else out


class FusedDropoutAdd(Layer):
    """(reference incubate/nn/layer/fused_dropout_add.py): dropout(x)+y
    in one fused op."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from ...nn import functional as F
        return F.dropout(x, p=self.p, training=self.training,
                         mode=self.mode) + y


class FusedBiasDropoutResidualLayerNorm(Layer):
    """(reference incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm): LN(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn.initializer import Constant
        self.linear_bias = self.create_parameter(
            (embed_dim,), is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.embed_dim = embed_dim

    def forward(self, x, residual):
        from ...nn import functional as F
        h = F.dropout(x + self.linear_bias, p=self.dropout_rate,
                      training=self.training)
        return F.layer_norm(residual + h, [self.embed_dim],
                            weight=self.ln_scale, bias=self.ln_bias,
                            epsilon=self.epsilon)


class FusedTransformerEncoderLayer(Layer):
    """(reference incubate/nn/layer/fused_transformer.py
    FusedTransformerEncoderLayer): attention + FFN with the fused building
    blocks; on TPU the standard encoder layer already compiles to the same
    fused program."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ...nn.transformer import TransformerEncoderLayer
        self._layer = TransformerEncoderLayer(
            d_model, nhead, dim_feedforward, dropout=dropout_rate,
            activation=activation,
            attn_dropout=attn_dropout_rate, act_dropout=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self._layer(src, src_mask=src_mask)


class FusedMultiTransformer(Layer):
    """(reference incubate/nn/layer/fused_transformer.py
    FusedMultiTransformer — the inference-serving stacked decoder): N
    pre-LN decoder blocks evaluated as one scanned program."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, num_layers=-1, nranks=1,
                 trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        if num_layers < 0:
            num_layers = len(ln_scale_attrs) if ln_scale_attrs else 1
        from ...nn.transformer import TransformerEncoderLayer
        self.layers = [TransformerEncoderLayer(
            embed_dim, num_heads, dim_feedforward, dropout=dropout_rate,
            activation=activation, normalize_before=normalize_before)
            for _ in range(num_layers)]
        for i, lyr in enumerate(self.layers):
            self.add_sublayer(f"layer_{i}", lyr)

    def forward(self, src, attn_mask=None, caches=None, **kw):
        out = src
        for lyr in self.layers:
            out = lyr(out, src_mask=attn_mask)
        return out
