"""paddle.incubate.nn.functional — fused ops.

Reference: python/paddle/incubate/nn/functional/ (fused_rms_norm.py,
fused_layer_norm.py, fused_dropout_add.py, fused_linear.py,
fused_rotary_position_embedding.py, fused_transformer.py; CUDA kernels
in paddle/phi/kernels/fusion/).

TPU formulation: the hot ones hit Pallas kernels (rms_norm, flash sdpa);
the rest are single jax expressions XLA fuses on its own — the API shape
is kept so incubate users port unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.registry import op
from ...ops.pallas.rms_norm import rms_norm as _pallas_rms_norm

__all__ = ["fused_rms_norm", "fused_layer_norm", "fused_dropout_add",
           "fused_linear", "fused_linear_activation",
           "fused_rotary_position_embedding", "fused_bias_act",
           "fused_multi_head_attention", "fused_feedforward"]


@op
def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **kwargs):
    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    out = _pallas_rms_norm(x, norm_weight, eps=epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    if residual is not None:
        return out, x          # reference returns (out, residual_out)
    return out


@op
def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None,
                     **kwargs):
    if residual is not None:
        x = x + residual
    if bias is not None:
        x = x + bias
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if norm_weight is not None:
        out = out * norm_weight
    if norm_bias is not None:
        out = out + norm_bias
    if residual is not None:
        return out, x
    return out


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """Reference: fused_dropout_add.py — dropout(x) + y in one pass."""
    from ...nn import functional as F
    return F.dropout(x, p=p, training=training, mode=mode) + y


@op
def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    w = weight.T if transpose_weight else weight
    out = x @ w
    if bias is not None:
        out = out + bias
    return out


@op
def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    a = x.T if trans_x else x
    b = y.T if trans_y else y
    out = a @ b + bias
    if activation == "gelu":
        return jax.nn.gelu(out)
    if activation == "relu":
        return jax.nn.relu(out)
    return out


@op
def fused_bias_act(x, bias=None, act_method="gelu", **kwargs):
    if bias is not None:
        x = x + bias
    return {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "silu": jax.nn.silu, "swiglu": _swiglu}[act_method](x)


def _swiglu(x):
    a, b = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(a) * b


@op
def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True):
    """Reference: fused_rotary_position_embedding.py; [B, S, H, D]."""
    s, d = q.shape[1], q.shape[-1]
    if sin is None or cos is None:
        inv = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, dtype=jnp.float32)
                                 / d))
        t = jnp.arange(s, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        cos = jnp.cos(emb)[None, :, None, :]
        sin = jnp.sin(emb)[None, :, None, :]
    if position_ids is not None:
        cos = jnp.squeeze(cos, (0, 2))[position_ids][:, :, None, :]
        sin = jnp.squeeze(sin, (0, 2))[position_ids][:, :, None, :]

    from .serving import _rot_half

    def apply(x):
        if x is None:
            return None
        return (x * cos
                + _rot_half(x, use_neox_rotary_style) * sin).astype(x.dtype)

    outs = tuple(apply(t) for t in (q, k, v))
    return outs


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.0, attn_dropout_rate=0.0,
                               ln_epsilon=1e-5, training=True, mode=None,
                               name=None):
    """Reference: fused_transformer.py fused_multi_head_attention —
    (optional pre-LN) + QKV proj + flash sdpa + out proj + residual + LN.
    qkv_weight: [3, num_heads, head_dim, embed_dim]."""
    from ...nn import functional as F
    from ...ops.pallas.flash_attention import sdpa as _sdpa
    from ...ops import manipulation as M
    from ...ops import math as Om
    from ...ops.linalg import matmul

    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], weight=pre_ln_scale,
                         bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    three, h, hd, e = qkv_weight.shape
    w = M.reshape(M.transpose(qkv_weight, [3, 0, 1, 2]), [e, 3 * h * hd])
    qkv = matmul(x, w)
    if qkv_bias is not None:
        qkv = qkv + M.reshape(qkv_bias, [-1])
    b, s = x.shape[0], x.shape[1]
    qkv = M.reshape(qkv, [b, s, 3, h, hd])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    o = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)
    o = M.reshape(o, [b, s, h * hd])
    out = matmul(o, linear_weight)
    if linear_bias is not None:
        out = out + linear_bias
    if dropout_rate:
        out = F.dropout(out, p=dropout_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], weight=ln_scale,
                           bias=ln_bias, epsilon=ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=None,
                      name=None):
    """Reference: fused_transformer.py fused_feedforward."""
    from ...nn import functional as F
    from ...ops.linalg import matmul

    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, [x.shape[-1]], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = matmul(x, linear1_weight)
    if linear1_bias is not None:
        h = h + linear1_bias
    h = getattr(F, activation)(h)
    if dropout1_rate:
        h = F.dropout(h, p=dropout1_rate, training=training)
    h = matmul(h, linear2_weight)
    if linear2_bias is not None:
        h = h + linear2_bias
    if dropout2_rate:
        h = F.dropout(h, p=dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], weight=ln2_scale,
                           bias=ln2_bias, epsilon=ln2_epsilon)
    return out


# ------------------------------------------------- serving fused-op surface
# (block_multihead_attention etc. — see serving.py for the engines)
from .serving import (swiglu, fused_matmul_bias, blha_get_max_len,  # noqa: E402,F401
                      variable_length_memory_efficient_attention,
                      masked_multihead_attention,
                      block_multihead_attention, fused_moe,
                      fused_multi_transformer)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-05, training=True,
        mode="upscale_in_train", name=None):
    """reference fused_transformer.py fused_bias_dropout_residual_layer_norm:
    layer_norm(residual + dropout(x + bias))."""
    import paddle_tpu.nn.functional as F
    h = x if bias is None else x + bias
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    out = residual + h
    w = ln_scale
    b = ln_bias
    return F.layer_norm(out, [int(out.shape[-1])], weight=w, bias=b,
                        epsilon=ln_epsilon)


__all__ += ["swiglu", "fused_matmul_bias", "blha_get_max_len",
            "variable_length_memory_efficient_attention",
            "masked_multihead_attention", "block_multihead_attention",
            "fused_moe", "fused_multi_transformer",
            "fused_bias_dropout_residual_layer_norm"]
