"""paddle.incubate.checkpoint — training auto-recovery.

Reference: python/paddle/incubate/checkpoint/auto_checkpoint.py (acp:
epoch-range contexts that snapshot program+optimizer state to durable
storage and resume after preemption).

TPU formulation: snapshots are paddle.save state dicts written every N
steps with an atomic rename; `auto_checkpoint` resumes from the newest
valid snapshot — the single-host analog of the elastic relaunch +
dist-checkpoint resume path.
"""
from __future__ import annotations

import os

__all__ = ["AutoCheckpoint", "train_epoch_range"]


class AutoCheckpoint:
    def __init__(self, save_dir, model=None, optimizer=None, interval=1):
        self.save_dir = save_dir
        self.model = model
        self.optimizer = optimizer
        self.interval = interval
        os.makedirs(save_dir, exist_ok=True)

    def _path(self, step):
        return os.path.join(self.save_dir, f"ckpt_{step}.pdparams")

    def save(self, step):
        if step % self.interval:
            return
        from .. import save as _save
        payload = {"step": step}
        if self.model is not None:
            payload["model"] = self.model.state_dict()
        if self.optimizer is not None:
            payload["opt"] = self.optimizer.state_dict()
        tmp = self._path(step) + ".tmp"
        _save(payload, tmp)
        os.replace(tmp, self._path(step))   # atomic: no torn snapshots

    def latest_step(self):
        steps = []
        for f in os.listdir(self.save_dir):
            if f.startswith("ckpt_") and f.endswith(".pdparams"):
                try:
                    steps.append(int(f[len("ckpt_"):-len(".pdparams")]))
                except ValueError:
                    pass
        return max(steps) if steps else None

    def restore(self):
        """Returns the restored step, or None if no snapshot exists."""
        step = self.latest_step()
        if step is None:
            return None
        from .. import load as _load
        payload = _load(self._path(step))
        if self.model is not None and "model" in payload:
            self.model.set_state_dict(payload["model"])
        if self.optimizer is not None and "opt" in payload:
            self.optimizer.set_state_dict(payload["opt"])
        return payload["step"]


def train_epoch_range(max_epoch, save_dir=None, model=None, optimizer=None,
                      interval=1):
    """Generator over epochs that resumes after the last snapshot
    (reference acp._run_save_0 epoch-range semantics)."""
    if save_dir is None:
        save_dir = os.environ.get("PADDLE_AUTO_CHECKPOINT_DIR")
    if save_dir is None:
        # a fresh temp dir could never be found again after preemption,
        # which is the entire point of auto-recovery
        raise ValueError(
            "train_epoch_range needs a stable save_dir (or "
            "PADDLE_AUTO_CHECKPOINT_DIR) to resume from after restart")
    acp = AutoCheckpoint(save_dir, model, optimizer, interval)
    start = acp.restore()
    first = 0 if start is None else start + 1
    for epoch in range(first, max_epoch):
        yield epoch
        acp.save(epoch)
