"""paddle.incubate.asp — automatic structured (2:4) sparsity.

Reference: python/paddle/incubate/asp/ (calculate_density, 1D/2D best
mask algorithms asp/utils.py, prune_model, decorate masking the
optimizer step).

TPU formulation: masks are plain arrays applied after each optimizer
step (the reference's OptimizerWithSparsityGuarantee does the same); the
MXU has no sparse-tensor-core analog, so 2:4 here preserves the
semantics/workflow (mask correctness, density accounting) rather than a
kernel speedup.
"""
from __future__ import annotations

import numpy as np

__all__ = ["calculate_density", "create_mask", "check_mask_2d",
           "check_mask_1d", "prune_model", "decorate", "reset_excluded_layers",
           "set_excluded_layers"]

_excluded: set = set()


def calculate_density(x):
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _best_nm_mask_1d(mat, n=2, m=4):
    """Keep the n largest |values| in every group of m along rows."""
    rows, cols = mat.shape
    pad = (-cols) % m
    if pad:
        mat = np.concatenate([mat, np.zeros((rows, pad), mat.dtype)], 1)
    g = np.abs(mat).reshape(rows, -1, m)
    idx = np.argsort(g, axis=-1)[..., ::-1][..., :n]
    mask = np.zeros_like(g, dtype=bool)
    np.put_along_axis(mask, idx, True, axis=-1)
    mask = mask.reshape(rows, -1)[:, :cols]
    return mask


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    arr = np.asarray(tensor.numpy() if hasattr(tensor, "numpy")
                     else tensor)
    shape = arr.shape
    mat = arr.reshape(shape[0], -1) if arr.ndim > 1 else arr.reshape(1, -1)
    mask = _best_nm_mask_1d(mat, n=n, m=m).reshape(shape)
    return mask


def check_mask_1d(mat, n=2, m=4):
    arr = np.asarray(mat.numpy() if hasattr(mat, "numpy") else mat)
    flat = arr.reshape(arr.shape[0], -1) if arr.ndim > 1 else \
        arr.reshape(1, -1)
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.concatenate(
            [flat, np.zeros((flat.shape[0], pad), flat.dtype)], 1)
    groups = flat.reshape(flat.shape[0], -1, m)
    return bool(np.all(np.count_nonzero(groups, axis=-1) <= n))


check_mask_2d = check_mask_1d


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _prunable(model):
    for layer in model.sublayers(include_self=True):
        w = getattr(layer, "weight", None)
        if w is None or w.ndim < 2 or w.name in _excluded:
            continue
        yield w


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to prunable weights; returns name->mask."""
    import jax.numpy as jnp
    masks = {}
    for w in _prunable(model):
        mask = create_mask(w, func_name=mask_algo, n=n, m=m)
        w._data = w._data * jnp.asarray(mask, w._data.dtype)
        masks[w.name] = mask
    return masks


class OptimizerWithSparsityGuarantee:
    """Reference: asp.py decorate — re-applies masks after each step."""

    def __init__(self, optimizer, masks=None):
        self._inner = optimizer
        self._masks = masks or {}

    def _attach(self, model, n=2, m=4):
        self._masks = prune_model(model, n=n, m=m)
        self._params = {w.name: w for w in _prunable(model)}
        return self

    def step(self):
        import jax.numpy as jnp
        self._inner.step()
        for name, mask in self._masks.items():
            p = self._params.get(name)
            if p is not None:
                p._data = p._data * jnp.asarray(mask, p._data.dtype)

    def __getattr__(self, item):
        return getattr(self._inner, item)


def decorate(optimizer, model=None, n=2, m=4):
    dec = OptimizerWithSparsityGuarantee(optimizer)
    if model is not None:
        dec._attach(model, n=n, m=m)
    return dec
