"""Autograd package (reference: python/paddle/autograd + fluid/eager)."""
from . import tape
from .tape import no_grad, enable_grad, is_grad_enabled, set_grad_enabled, \
    backward, grad
from .py_layer import PyLayer, PyLayerContext
from .functional import jacobian, hessian, saved_tensors_hooks

__all__ = ["no_grad", "enable_grad", "is_grad_enabled", "set_grad_enabled",
           "backward", "grad", "PyLayer", "PyLayerContext", "jacobian",
           "hessian", "saved_tensors_hooks"]
