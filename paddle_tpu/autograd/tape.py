"""Tape-based reverse-mode autograd over jax ops.

The reference implements dygraph autograd as a C++ GradNode DAG built by
generated ``<op>_ad_func`` wrappers and walked by ``egr::Backward``
(paddle/fluid/eager/backward.cc:105,439).  On TPU we get every op's VJP from
jax (`jax.vjp`), so the tape only needs to (a) record a node per op linking
input/output tensors, (b) run a reverse-topological sweep accumulating
cotangents.  The tape records plain functions of jax arrays, so it works both
eagerly and inside a `jax.jit` trace (backward() under trace yields traced
grads — this is how the compiled training step is built).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

__all__ = ["GradNode", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "backward", "grad"]


class _TapeState(threading.local):
    def __init__(self):
        self.enabled = True
        self.next_id = 0


_state = _TapeState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class _GradModeGuard:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with _GradModeGuard(self._mode):
                return fn(*a, **k)

        return wrapper


def no_grad(func=None):
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""
    g = _GradModeGuard(False)
    return g(func) if callable(func) else g


def enable_grad(func=None):
    g = _GradModeGuard(True)
    return g(func) if callable(func) else g


class GradNode:
    """One recorded op: maps output cotangents -> input cotangents.

    ``vjp_fn`` takes a tuple of output cotangents (one per output, zeros
    filled for unused outputs) and returns a tuple of input cotangents
    aligned with ``inputs``.

    Inputs are snapshotted as (tensor, producer_node, out_index) at record
    time: in-place APIs rebind tensor handles to new nodes, so the recorded
    graph must not chase the live ``_grad_node`` (it may point *forward*).
    """

    __slots__ = ("id", "name", "vjp_fn", "inputs", "out_avals")

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence[Any],
                 out_avals: Sequence[Any]):
        self.id = _state.next_id
        _state.next_id += 1
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = [(t, t._grad_node, t._out_index) for t in inputs]
        self.out_avals = list(out_avals)  # jax.ShapeDtypeStruct per output

    def __repr__(self):
        return f"<GradNode {self.name}#{self.id}>"


def _zeros_like_aval(aval):
    if aval.dtype == jax.dtypes.float0:
        import numpy as np
        return np.zeros(aval.shape, jax.dtypes.float0)
    return jnp.zeros(aval.shape, aval.dtype)


def backward(tensors, grad_tensors=None, retain_graph=False, _sink=None,
             _capture=frozenset()):
    """Reverse sweep from ``tensors`` accumulating into leaf ``.grad``.

    Mirrors ``egr::Backward`` semantics: seeds with ones for scalar outputs,
    walks nodes in reverse creation order (a valid reverse-topological order
    for a tape), accumulates into ``Tensor.grad`` on leaves
    (stop_gradient=False tensors with no grad node).

    When ``_sink`` (a dict) is given, leaf cotangents go into
    ``_sink[id(tensor)]`` instead of ``.grad`` — used by :func:`grad`.
    """
    from ..framework.tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor) or not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # node id -> list of output cotangents (lazily filled)
    pending: dict[int, list] = {}
    nodes: dict[int, GradNode] = {}

    def seed(t: Tensor, g):
        if t.stop_gradient:
            return
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones(t._data.shape, t._data.dtype)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        _accumulate(t, t._grad_node, t._out_index, g)

    def _accumulate(t: Tensor, node, out_index, g):
        if _sink is not None and (node is None or id(t) in _capture):
            prev = _sink.get(id(t))
            _sink[id(t)] = g if prev is None else prev + g
            if node is None:
                return
        elif node is None:
            # leaf: accumulate into .grad
            prev = t._grad
            t._grad = g if prev is None else prev + g
            return
        nodes[node.id] = node
        cots = pending.get(node.id)
        if cots is None:
            cots = [None] * len(node.out_avals)
            pending[node.id] = cots
        cots[out_index] = g if cots[out_index] is None \
            else cots[out_index] + g

    for t, g in zip(tensors, grad_tensors):
        seed(t, g)

    # Reverse creation order == reverse topological order on a tape.
    while nodes:
        nid = max(nodes)
        node = nodes.pop(nid)
        cots = pending.pop(nid)
        cots = tuple(
            c if c is not None else _zeros_like_aval(a)
            for c, a in zip(cots, node.out_avals))
        in_cots = node.vjp_fn(cots)
        for (t, prod_node, prod_idx), g in zip(node.inputs, in_cots):
            if t is None or g is None:
                continue
            if not t.stop_gradient:
                _accumulate(t, prod_node, prod_idx, g)
        if not retain_graph:
            node.vjp_fn = _used_vjp
            node.inputs = []


def _used_vjp(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time; "
        "pass retain_graph=True if you need to.")


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """paddle.grad: grads of outputs wrt inputs without touching .grad.

    Implemented as a tape sweep into a side accumulator (reference:
    general_grad.h selective subgraph).
    """
    from ..framework.tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    sink: dict[int, Any] = {}
    backward(outputs, grad_outputs, retain_graph=retain_graph, _sink=sink,
             _capture=frozenset(id(t) for t in inputs))
    results = []
    for t in inputs:
        g = sink.get(id(t))
        if g is None and not allow_unused:
            g = jnp.zeros(t._data.shape, t._data.dtype)
        results.append(Tensor(g, stop_gradient=True) if g is not None else None)
    return results


# ---------------------------------------------------- saved-tensor hooks
# (reference: python/paddle/autograd/saved_tensors_hooks.py — pack runs
# when an op saves residuals for backward, unpack when backward uses them.
# Here residuals live inside jax.vjp closures; the hooks are applied to
# the op's *input* tensors, which is the dominant residual class, by
# wrapping the recorded vjp.)

_saved_hooks_stack = []


def push_saved_tensors_hooks(pack_hook, unpack_hook):
    _saved_hooks_stack.append((pack_hook, unpack_hook))


def pop_saved_tensors_hooks():
    _saved_hooks_stack.pop()


def current_saved_tensors_hooks():
    return _saved_hooks_stack[-1] if _saved_hooks_stack else None
