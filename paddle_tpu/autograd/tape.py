"""Tape-based reverse-mode autograd over jax ops.

The reference implements dygraph autograd as a C++ GradNode DAG built by
generated ``<op>_ad_func`` wrappers and walked by ``egr::Backward``
(paddle/fluid/eager/backward.cc:105,439).  On TPU we get every op's VJP from
jax (`jax.vjp`), so the tape only needs to (a) record a node per op linking
input/output tensors, (b) run a reverse-topological sweep accumulating
cotangents.  The tape records plain functions of jax arrays, so it works both
eagerly and inside a `jax.jit` trace (backward() under trace yields traced
grads — this is how the compiled training step is built).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GradNode", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "backward", "grad"]


class _TapeState(threading.local):
    def __init__(self):
        self.enabled = True
        self.next_id = 0


_state = _TapeState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class _GradModeGuard:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with _GradModeGuard(self._mode):
                return fn(*a, **k)

        return wrapper


def no_grad(func=None):
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""
    g = _GradModeGuard(False)
    return g(func) if callable(func) else g


def enable_grad(func=None):
    g = _GradModeGuard(True)
    return g(func) if callable(func) else g


class GradNode:
    """One recorded op: maps output cotangents -> input cotangents.

    ``vjp_fn`` takes a tuple of output cotangents (one per output, zeros
    filled for unused outputs) and returns a tuple of input cotangents
    aligned with ``inputs``.

    Inputs are snapshotted as (tensor, producer_node, out_index) at record
    time: in-place APIs rebind tensor handles to new nodes, so the recorded
    graph must not chase the live ``_grad_node`` (it may point *forward*).
    """

    __slots__ = ("id", "name", "vjp_fn", "inputs", "out_avals",
                 "raw_vjp", "out_treedef", "fwd_closed")

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence[Any],
                 out_avals: Sequence[Any]):
        self.id = _state.next_id
        _state.next_id += 1
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = [(t, t._grad_node, t._out_index) for t in inputs]
        self.out_avals = list(out_avals)  # jax.ShapeDtypeStruct per output
        self.raw_vjp = None        # tree_util.Partial when fusable
        self.out_treedef = None
        self.fwd_closed = None     # re-runnable fwd for create_graph=True

    def __repr__(self):
        return f"<GradNode {self.name}#{self.id}>"


def _zeros_like_aval(aval):
    if aval.dtype == jax.dtypes.float0:
        import numpy as np
        return np.zeros(aval.shape, jax.dtypes.float0)
    return jnp.zeros(aval.shape, aval.dtype)


# ------------------------------------------------------- fused backward
# One dispatch per GradNode is the dygraph tax on a tunneled transport
# (~0.5 ms each).  For the common case — every node carries a cached-jit
# vjp Partial, no hooks, plain .grad accumulation — the WHOLE reverse
# sweep retraces into one jitted executable, cached by the tape's
# structural signature (the graph repeats every step in a training loop).
_FUSED_BW_CACHE: dict = {}
_FUSED_BW_MAX = 128
FUSED_BACKWARD = True


def _try_fused_backward(tensors, grad_tensors, retain_graph):
    """Returns True when the sweep ran fused; False -> caller runs the
    per-node path."""
    from jax.tree_util import tree_flatten, tree_unflatten

    # ---- plan: walk the graph symbolically (no vjp execution) --------
    plan_nodes = []            # GradNode, reverse-topo order
    nodes: dict[int, GradNode] = {}
    seeds = []                 # (node, out_index, seed_array)

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if isinstance(t._data, jax.core.Tracer):
            return False       # inside an outer trace: per-node path
        node = t._grad_node
        if node is None:
            return False       # direct-leaf seed: per-node path handles
        if g is None:
            if t.size != 1:
                return False   # error path: per-node code raises it
            g = jnp.ones(t._data.shape, t._data.dtype)
        else:
            g = g._data if hasattr(g, "_data") else jnp.asarray(g)
        seeds.append((node, t._out_index, g))
        nodes[node.id] = node

    if not seeds:
        return False
    order: list[int] = []
    walk = dict(nodes)
    while walk:
        nid = max(walk)
        node = walk.pop(nid)
        if node.raw_vjp is None or node.vjp_fn is _used_vjp:
            return False       # hooks / non-cached vjp / reused graph
        plan_nodes.append(node)
        order.append(nid)
        for (t, prod, _pi) in node.inputs:
            if t is not None and not t.stop_gradient and prod is not None:
                walk[prod.id] = prod

    # leaves in deterministic discovery order
    leaves = []                # Tensor objects
    leaf_slot: dict[int, int] = {}
    for node in plan_nodes:
        for (t, prod, _pi) in node.inputs:
            if t is not None and not t.stop_gradient and prod is None \
                    and id(t) not in leaf_slot:
                leaf_slot[id(t)] = len(leaves)
                leaves.append(t)

    id2pos = {nid: i for i, nid in enumerate(order)}

    # ---- signature + dynamic inputs ----------------------------------
    sig_parts = []
    res_leaves_all = []        # flat residual leaves, per node
    res_trees = []
    for node in plan_nodes:
        rl, rt = tree_flatten(node.raw_vjp)
        res_leaves_all.append(tuple(rl))
        res_trees.append(rt)
        links = tuple(
            ("x",) if t is None or t.stop_gradient else
            (("l", leaf_slot[id(t)]) if prod is None
             else ("n", id2pos[prod.id], pi))
            for (t, prod, pi) in node.inputs)
        sig_parts.append((
            node.name, rt, node.out_treedef,
            tuple((tuple(a.shape), str(a.dtype)) for a in node.out_avals),
            tuple((tuple(l.shape), str(l.dtype)) for l in rl),
            links))
    sig = (tuple(sig_parts),
           tuple((id2pos[n.id], oi, tuple(g.shape), str(g.dtype))
                 for n, oi, g in seeds),
           len(leaves))

    leaf_avals = tuple(
        (tuple(t._data.shape), str(t._data.dtype)) for t in leaves)
    sig = sig + (leaf_avals,)
    fn = _FUSED_BW_CACHE.get(sig)
    if fn is None:
        plan_meta = [(list(node.out_avals), tree, node.out_treedef,
                      links)
                     for node, tree, links in zip(
                         plan_nodes, res_trees,
                         [sp[-1] for sp in sig_parts])]
        seed_meta = [(id2pos[n.id], oi) for n, oi, _g in seeds]
        n_leaves = len(leaves)

        def fused(all_res, seed_vals):
            from ..ops.registry import _apply_cached_vjp

            pend = [[None] * len(m[0]) for m in plan_meta]
            leaf_out = [None] * n_leaves

            def add(slot, g):
                if g is None:
                    return
                kind = slot[0]
                if kind == "l":
                    i = slot[1]
                    leaf_out[i] = g if leaf_out[i] is None \
                        else leaf_out[i] + g
                elif kind == "n":
                    _, pos, oi = slot
                    pend[pos][oi] = g if pend[pos][oi] is None \
                        else pend[pos][oi] + g

            for (pos, oi), g in zip(seed_meta, seed_vals):
                pend[pos][oi] = g if pend[pos][oi] is None \
                    else pend[pos][oi] + g

            for pos, (avals, rtree, otree, links) in enumerate(plan_meta):
                cots = tuple(
                    c if c is not None else _zeros_like_aval(a)
                    for c, a in zip(pend[pos], avals))
                raw = tree_unflatten(rtree, list(all_res[pos]))
                in_cots = _apply_cached_vjp(
                    raw, tree_unflatten(otree, list(cots)))
                for slot, g in zip(links, in_cots):
                    if slot[0] != "x":
                        add(slot, g)
            return [g if g is not None else jnp.zeros(s, d)
                    for g, (s, d) in zip(leaf_out, leaf_avals)]

        fn = jax.jit(fused)
        if len(_FUSED_BW_CACHE) >= _FUSED_BW_MAX:
            _FUSED_BW_CACHE.pop(next(iter(_FUSED_BW_CACHE)))
        _FUSED_BW_CACHE[sig] = fn

    try:
        grads = fn(tuple(res_leaves_all), tuple(g for _n, _oi, g in seeds))
    except Exception:
        return False
    for t, g in zip(leaves, grads):
        t._grad = g if t._grad is None else t._grad + g
    if not retain_graph:
        for node in plan_nodes:
            node.vjp_fn = _used_vjp
            node.raw_vjp = None
            node.inputs = []
            node.fwd_closed = None
    return True


def backward(tensors, grad_tensors=None, retain_graph=False, _sink=None,
             _capture=frozenset()):
    """Reverse sweep from ``tensors`` accumulating into leaf ``.grad``.

    Mirrors ``egr::Backward`` semantics: seeds with ones for scalar outputs,
    walks nodes in reverse creation order (a valid reverse-topological order
    for a tape), accumulates into ``Tensor.grad`` on leaves
    (stop_gradient=False tensors with no grad node).

    When ``_sink`` (a dict) is given, leaf cotangents go into
    ``_sink[id(tensor)]`` instead of ``.grad`` — used by :func:`grad`.
    """
    from ..framework.tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor) or not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    if (FUSED_BACKWARD and _sink is None
            and _try_fused_backward(tensors, grad_tensors, retain_graph)):
        return

    # node id -> list of output cotangents (lazily filled)
    pending: dict[int, list] = {}
    nodes: dict[int, GradNode] = {}

    def seed(t: Tensor, g):
        if t.stop_gradient:
            return
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones(t._data.shape, t._data.dtype)
        else:
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        _accumulate(t, t._grad_node, t._out_index, g)

    def _accumulate(t: Tensor, node, out_index, g):
        if _sink is not None and (node is None or id(t) in _capture):
            prev = _sink.get(id(t))
            _sink[id(t)] = g if prev is None else prev + g
            if node is None:
                return
        elif node is None:
            # leaf: accumulate into .grad
            prev = t._grad
            t._grad = g if prev is None else prev + g
            return
        nodes[node.id] = node
        cots = pending.get(node.id)
        if cots is None:
            cots = [None] * len(node.out_avals)
            pending[node.id] = cots
        cots[out_index] = g if cots[out_index] is None \
            else cots[out_index] + g

    for t, g in zip(tensors, grad_tensors):
        seed(t, g)

    # Reverse creation order == reverse topological order on a tape.
    while nodes:
        nid = max(nodes)
        node = nodes.pop(nid)
        cots = pending.pop(nid)
        cots = tuple(
            c if c is not None else _zeros_like_aval(a)
            for c, a in zip(cots, node.out_avals))
        in_cots = node.vjp_fn(cots)
        for (t, prod_node, prod_idx), g in zip(node.inputs, in_cots):
            if t is None or g is None:
                continue
            if not t.stop_gradient:
                _accumulate(t, prod_node, prod_idx, g)
        if not retain_graph:
            node.vjp_fn = _used_vjp
            node.inputs = []
            node.fwd_closed = None


def _used_vjp(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time; "
        "pass retain_graph=True if you need to.")


# ------------------------------------------------- higher-order autograd
# The reference implements double/triple backward as dedicated
# *_double_grad / *_triple_grad ops (34 + 19 entries in
# paddle/phi/ops/yaml/backward.yaml:4) driven by grad(create_graph=True)
# (python/paddle/base/dygraph/base.py:656,690).  Here every registry op
# stores a re-runnable forward closure (registry._make_closed), so the
# create_graph sweep re-linearises each node with `jax.vjp` — the grad of
# the grad falls out of jax's own transpose rules, to arbitrary order
# (the replay node stores its OWN closure, so triple grad recurses).


def _replay_differentiable(node: GradNode, cot_ts: list):
    """Run one node's backward as a *recorded*, differentiable op.

    cot_ts: flat output-cotangent Tensors (one per out_aval).  Returns
    input-cotangent Tensors aligned with ``node.inputs``; when any diff
    input feeds them, they carry a new GradNode whose vjp comes from
    ``jax.vjp`` of the replay — so the result is differentiable w.r.t.
    both the op's original inputs (via residual recompute) and the
    incoming cotangents (the linear part).
    """
    from jax.tree_util import tree_flatten, tree_unflatten
    from ..framework.tensor import Tensor
    from ..ops.registry import _tangent_dtype

    if node.fwd_closed is None or node.out_treedef is None:
        raise NotImplementedError(
            f"grad(..., create_graph=True) through op '{node.name}' is not "
            "supported: the node has no re-differentiable forward closure "
            "(custom GradNodes — PyLayer / to_static / recompute / "
            "sparse-conv — and eager-RNG ops like dropout). Restructure the "
            "double-grad region to use framework ops, or compute it under "
            "jax.grad directly.")

    in_items = list(node.inputs)          # (tensor, producer, out_index)
    in_arrs0 = [t._data for (t, _p, _i) in in_items]
    # float0 cotangents (integer outputs) travel as raw numpy zeros, not
    # Tensors — they are never differentiable
    cot_arrs0 = [getattr(c, "_data", c) for c in cot_ts]
    fwd = node.fwd_closed
    otree = node.out_treedef

    def _inexact(a):
        return _tangent_dtype(a) != jax.dtypes.float0

    diff = [("i", k) for k, (t, _p, _ix) in enumerate(in_items)
            if not t.stop_gradient and _inexact(t._data)]
    diff += [("c", k) for k, c in enumerate(cot_ts)
             if isinstance(c, Tensor) and not c.stop_gradient
             and _inexact(c._data)]

    def gop(*darrs):
        ia, ca = list(in_arrs0), list(cot_arrs0)
        for (kind, k), a in zip(diff, darrs):
            (ia if kind == "i" else ca)[k] = a
        _out, vjp = jax.vjp(fwd, *ia)
        return tuple(vjp(tree_unflatten(otree, ca)))

    darrs = [(in_arrs0 if kind == "i" else cot_arrs0)[k]
             for (kind, k) in diff]
    if diff and is_grad_enabled():
        out, raw_vjp = jax.vjp(gop, *darrs)
    else:
        out, raw_vjp = gop(*darrs), None

    out_flat, out_tree2 = tree_flatten(out)
    nnode = None
    if raw_vjp is not None:
        out_avals = [jax.ShapeDtypeStruct(np.shape(a), _tangent_dtype(a))
                     for a in out_flat]

        def vjp_fn(flat_cots):
            return raw_vjp(tree_unflatten(out_tree2, list(flat_cots)))

        diff_ts = [in_items[k][0] if kind == "i" else cot_ts[k]
                   for (kind, k) in diff]
        nnode = GradNode(f"grad[{node.name}]", vjp_fn, diff_ts, out_avals)
        # the original inputs' producers were snapshotted at forward-record
        # time; the live _grad_node may have been rebound by in-place APIs
        # since — restore the snapshot
        for j, (kind, k) in enumerate(diff):
            if kind == "i":
                nnode.inputs[j] = in_items[k]
        nnode.fwd_closed = gop
        nnode.out_treedef = out_tree2

    res = []
    for i, a in enumerate(out_flat):
        diffable = nnode is not None and _tangent_dtype(a) != jax.dtypes.float0
        t = Tensor(a, stop_gradient=not diffable)
        if diffable:
            t._grad_node = nnode
            t._out_index = i
        res.append(t)
    return res


def _backward_create_graph(tensors, grad_tensors, _sink, _capture,
                           retain_graph):
    """The grad(create_graph=True) sweep: cotangents flow as *recorded*
    Tensors and every node replay is itself differentiable."""
    from ..framework.tensor import Tensor

    pending: dict[int, list] = {}
    nodes: dict[int, GradNode] = {}

    def _acc_pair(a, b):
        return b if a is None else a + b      # Tensor __add__: recorded

    def _accumulate(t, node, out_index, g):
        if node is None or id(t) in _capture:
            prev = _sink.get(id(t))
            _sink[id(t)] = _acc_pair(prev, g)
            if node is None:
                return
        nodes[node.id] = node
        cots = pending.get(node.id)
        if cots is None:
            cots = [None] * len(node.out_avals)
            pending[node.id] = cots
        cots[out_index] = _acc_pair(cots[out_index], g)

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = Tensor(jnp.ones(t._data.shape, t._data.dtype),
                       stop_gradient=True)
        elif not isinstance(g, Tensor):
            g = Tensor(jnp.asarray(g), stop_gradient=True)
        _accumulate(t, t._grad_node, t._out_index, g)

    while nodes:
        nid = max(nodes)
        node = nodes.pop(nid)
        cots = pending.pop(nid)
        def _zero_cot(a):
            z = _zeros_like_aval(a)
            return z if a.dtype == jax.dtypes.float0 \
                else Tensor(z, stop_gradient=True)

        cot_ts = [c if c is not None else _zero_cot(a)
                  for c, a in zip(cots, node.out_avals)]
        in_cots = _replay_differentiable(node, cot_ts)
        for (t, prod_node, prod_idx), g in zip(node.inputs, in_cots):
            if t is None or g is None:
                continue
            if not t.stop_gradient:
                _accumulate(t, prod_node, prod_idx, g)
        if not retain_graph:
            node.vjp_fn = _used_vjp
            node.inputs = []
            node.fwd_closed = None


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad: grads of outputs wrt inputs without touching .grad.

    Implemented as a tape sweep into a side accumulator (reference:
    general_grad.h selective subgraph; create_graph semantics from
    python/paddle/base/dygraph/base.py:656,690 — retain_graph defaults to
    the create_graph value, and with create_graph=True the returned grads
    are themselves recorded for higher-order differentiation).
    """
    from ..framework.tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if not only_inputs:
        raise NotImplementedError("only_inputs=False is not supported "
                                  "(matches the reference deprecation)")
    if retain_graph is None:
        retain_graph = create_graph
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor) or not isinstance(
            grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    ngv = []
    if no_grad_vars:
        if isinstance(no_grad_vars, Tensor):
            no_grad_vars = [no_grad_vars]
        for t in no_grad_vars:
            if not t.stop_gradient:
                ngv.append(t)
                t.stop_gradient = True
    sink: dict[int, Any] = {}
    try:
        if create_graph:
            with enable_grad():
                _backward_create_graph(
                    outputs, grad_outputs, sink,
                    frozenset(id(t) for t in inputs), retain_graph)
        else:
            backward(outputs, grad_outputs, retain_graph=retain_graph,
                     _sink=sink, _capture=frozenset(id(t) for t in inputs))
    finally:
        for t in ngv:
            t.stop_gradient = False
    results = []
    for t in inputs:
        g = sink.get(id(t))
        if g is None and not allow_unused:
            g = jnp.zeros(t._data.shape, t._data.dtype)
            g = Tensor(g, stop_gradient=True)
        elif g is not None and not isinstance(g, Tensor):
            g = Tensor(g, stop_gradient=True)
        results.append(g)
    return results


# ---------------------------------------------------- saved-tensor hooks
# (reference: python/paddle/autograd/saved_tensors_hooks.py — pack runs
# when an op saves residuals for backward, unpack when backward uses them.
# Here residuals live inside jax.vjp closures; the hooks are applied to
# the op's *input* tensors, which is the dominant residual class, by
# wrapping the recorded vjp.)

_saved_hooks_stack = []


def push_saved_tensors_hooks(pack_hook, unpack_hook):
    _saved_hooks_stack.append((pack_hook, unpack_hook))


def pop_saved_tensors_hooks():
    _saved_hooks_stack.pop()


def current_saved_tensors_hooks():
    return _saved_hooks_stack[-1] if _saved_hooks_stack else None
