"""Functional higher-order autodiff (reference: python/paddle/autograd/
autograd.py jacobian/hessian over the eager engine).  Here they lower to
jax.jacrev/jax.hessian directly — the reference builds these from repeated
VJP sweeps; XLA compiles the whole sweep."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["jacobian", "hessian", "saved_tensors_hooks"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def jacobian(ys, xs, batch_axis=None):
    """d(ys)/d(xs).  Two call forms (both in the reference):
      * jacobian(func, xs): differentiate a callable
      * jacobian(y_tensor, x_tensor): differentiate recorded tensors is NOT
        supported here — pass the function (jax traces functionally).
    """
    if not callable(ys):
        # recorded-tensor form (reference autograd.py's eager form):
        # one tape sweep per output element via grad(retain_graph=True).
        # O(y.size) sweeps — fine for the small outputs jacobians of
        # recorded graphs are used for; bounded loudly.
        from . import tape

        y = ys
        if not isinstance(y, Tensor) or y._grad_node is None:
            raise TypeError(
                "jacobian(ys, xs): ys must be a callable or a RECORDED "
                "Tensor (created under the tape from xs)")
        if y.size > 512:
            raise ValueError(
                f"jacobian over a recorded tensor runs one backward "
                f"sweep per output element; y.size={y.size} is too "
                "large — use the callable form (jax.jacrev compiles "
                "the whole sweep)")
        xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
        yf = y.flatten()
        rows = []
        for i in range(int(y.size)):
            gs = tape.grad([yf[i]], list(xs_list), retain_graph=True,
                           allow_unused=False)
            rows.append([g._data.reshape(-1) for g in gs])
        jacs = []
        for j in range(len(xs_list)):
            mat = jnp.stack([rows[i][j] for i in range(len(rows))])
            jacs.append(Tensor(
                mat.reshape(tuple(y.shape) + tuple(xs_list[j].shape)),
                stop_gradient=True))
        if isinstance(xs, (list, tuple)):
            return jacs
        return jacs[0]
    func = ys
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs_list]

    def wrapped(*arrs):
        args = [Tensor(a) for a in arrs]
        out = func(*args) if len(args) > 1 else func(args[0])
        return _unwrap(out)

    if batch_axis is None:
        jac = jax.jacrev(wrapped, argnums=tuple(range(len(arrays))))(*arrays)
    else:
        if batch_axis != 0:
            raise ValueError("batch_axis must be 0 or None")
        jac = jax.vmap(jax.jacrev(wrapped,
                                  argnums=tuple(range(len(arrays)))))(*arrays)
    if isinstance(xs, (list, tuple)):
        return [Tensor(j) for j in jac]
    return Tensor(jac[0])


def hessian(func, xs, batch_axis=None):
    """d2(func)/d(xs)2 for scalar-output func (reference autograd.py
    hessian)."""
    if not callable(func):
        raise TypeError("hessian needs a callable (see jacobian docstring)")
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs_list]

    def wrapped(*arrs):
        args = [Tensor(a) for a in arrs]
        out = func(*args) if len(args) > 1 else func(args[0])
        return _unwrap(out).sum()

    if batch_axis is None:
        h = jax.hessian(wrapped, argnums=tuple(range(len(arrays))))(*arrays)
    else:
        if batch_axis != 0:
            raise ValueError("batch_axis must be 0 or None")
        h = jax.vmap(jax.hessian(wrapped,
                                 argnums=tuple(range(len(arrays)))))(*arrays)
    if isinstance(xs, (list, tuple)):
        return [[Tensor(h[i][j]) for j in range(len(arrays))]
                for i in range(len(arrays))]
    return Tensor(h[0][0])


class saved_tensors_hooks:
    """Context manager transforming tensors saved for backward (reference
    python/paddle/autograd/saved_tensors_hooks.py; eager
    SavedTensorsHooks).  Registered with the tape: pack runs when an op
    records its VJP inputs, unpack when backward consumes them."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        from . import tape
        tape.push_saved_tensors_hooks(self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        from . import tape
        tape.pop_saved_tensors_hooks()
        return False
