"""PyLayer: user-defined autograd ops.

Reference: python/paddle/autograd/py_layer.py + paddle/fluid/eager/pylayer/.
The TPU equivalent of choice for *jit* code is `jax.custom_vjp`; this class
provides the dygraph-API shape on the tape: forward runs unrecorded, a single
GradNode is installed whose vjp calls the user's backward.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.tree_util import tree_flatten, tree_unflatten

from . import tape

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        self._non_diff = args

    def set_materialize_grads(self, value):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework.tensor import Tensor

        ctx = PyLayerContext()
        flat, _ = tree_flatten((args, kwargs),
                               is_leaf=lambda x: isinstance(x, Tensor))
        tensor_inputs = [x for x in flat if isinstance(x, Tensor)]
        record = tape.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)

        with tape.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)

        if not record:
            return outs

        out_flat, out_treedef = tree_flatten(
            outs, is_leaf=lambda x: isinstance(x, Tensor))
        out_tensors = [x for x in out_flat if isinstance(x, Tensor)]
        out_avals = [jax.ShapeDtypeStruct(t._data.shape, t._data.dtype)
                     for t in out_tensors]
        diff_inputs = [t for t in tensor_inputs if not t.stop_gradient]

        def vjp_fn(flat_cots):
            cot_tensors = [Tensor(c, stop_gradient=True) for c in flat_cots]
            with tape.no_grad():
                grads = cls.backward(
                    ctx, *(cot_tensors if len(cot_tensors) > 1
                           else [cot_tensors[0]]))
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            # Align returned grads with forward's tensor inputs, then filter
            # to the differentiable subset (paddle semantics: one grad per
            # tensor input, None allowed).
            g_by_input = list(grads) + [None] * (len(tensor_inputs) - len(grads))
            out = []
            for t, g in zip(tensor_inputs, g_by_input):
                if t.stop_gradient:
                    continue
                out.append(None if g is None else
                           (g._data if isinstance(g, Tensor) else g))
            return tuple(out)

        node = tape.GradNode(cls.__name__, vjp_fn, diff_inputs, out_avals)
        new_out_flat = []
        i = 0
        for x in out_flat:
            if isinstance(x, Tensor):
                nt = Tensor(x._data, stop_gradient=False)
                nt._grad_node = node
                nt._out_index = i
                i += 1
                new_out_flat.append(nt)
            else:
                new_out_flat.append(x)
        return tree_unflatten(out_treedef, new_out_flat)


once_differentiable = staticmethod  # compat alias used by some paddle code
