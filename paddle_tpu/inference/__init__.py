"""paddle.inference — Config / Predictor deployment API.

Reference: paddle/fluid/inference/api/analysis_predictor.cc (:1719 Run,
:2752 ZeroCopyRun) + python/paddle/inference/wrapper.py.  The reference
runs an analysis pass pipeline over a serialized program then executes
zero-copy through the StandaloneExecutor; here the saved static Program
(static.save_inference_model) is loaded and each Run is one cached
jax.jit executable — XLA's fusion pipeline plays the role of the 309
analysis/IR passes.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType", "Tensor"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"


class Config:
    """Reference: paddle_infer.Config (inference/api/paddle_analysis_config.h)."""

    def __init__(self, model_path=None, params_path=None):
        # static.save_inference_model writes <prefix>.pdmodel.pkl +
        # <prefix>.pdiparams.npz; accept the prefix (or the .pdmodel path)
        if model_path and model_path.endswith(".pdmodel"):
            model_path = model_path[: -len(".pdmodel")]
        self.model_prefix = model_path
        self.params_path = params_path
        self._precision = PrecisionType.Float32
        self._device = None
        self._enable_memory_optim = True
        self._cpu_math_threads = 1
        self._switch_ir_optim = True

    # common toggles kept for API parity; XLA makes most of them no-ops
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = ("gpu", device_id)

    def enable_xpu(self, *a, **k):
        self._device = ("xpu", 0)

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    def switch_ir_optim(self, flag=True):
        self._switch_ir_optim = flag

    def enable_tensorrt_engine(self, *a, precision_mode=None, **k):
        # TensorRT has no TPU analog; precision hint maps to dtype cast
        if precision_mode is not None:
            self._precision = precision_mode

    def set_model(self, model_path, params_path=None):
        if model_path.endswith(".pdmodel"):
            model_path = model_path[: -len(".pdmodel")]
        self.model_prefix = model_path
        self.params_path = params_path

    def model_dir(self):
        return self.model_prefix

    def summary(self):
        return (f"Config(model={self.model_prefix}, "
                f"precision={self._precision})")


class _IOTensor:
    """Zero-copy handle (reference: paddle_infer.Tensor over phi tensors)."""

    def __init__(self, name, store):
        self.name = name
        self._store = store

    def copy_from_cpu(self, arr):
        self._store[self.name] = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._store[self.name])

    def shape(self):
        return list(np.shape(self._store.get(self.name, ())))

    def reshape(self, shape):
        pass  # shapes derive from copy_from_cpu input


Tensor = _IOTensor


class Predictor:
    def __init__(self, config: Config):
        from .. import static

        self.config = config
        prog, feeds, fetches = static.load_inference_model(
            config.model_prefix)
        self._program = prog
        self._feed_names = feeds
        self._fetch_vars = fetches
        self._exe = static.Executor()
        self._inputs = {}
        self._outputs = {}

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [v.name for v in self._fetch_vars]

    def get_input_handle(self, name):
        return _IOTensor(name, self._inputs)

    def get_output_handle(self, name):
        return _IOTensor(name, self._outputs)

    def run(self, inputs=None):
        """Positional-list run (new API) or zero-copy handle run."""
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._inputs[name] = np.asarray(arr)
        feed = {n: self._inputs[n] for n in self._feed_names}
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_vars)
        for v, o in zip(self._fetch_vars, outs):
            self._outputs[v.name] = o
        return outs if inputs is not None else None

    def clone(self):
        """reference Predictor::Clone (goapi predictor.go Clone): a new
        predictor sharing the loaded weights and compiled executables —
        only the I/O buffers are private, so clones are safe to use
        from different request contexts."""
        p = object.__new__(Predictor)
        p.config = self.config
        p._program = self._program
        p._feed_names = list(self._feed_names)
        p._fetch_vars = self._fetch_vars
        p._exe = self._exe
        p._inputs = {}
        p._outputs = {}
        return p


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class DataType:
    """Tensor dtypes of the inference API (reference
    paddle_infer.DataType)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    FLOAT64 = 7
    BOOL = 8


class XpuConfig:
    """XPU device config placeholder (reference paddle_infer.XpuConfig);
    recorded, not acted on — there is no XPU here."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0
        self.conv_autotune_level = 0


class PredictorPool:
    """Pool of predictors over one config (reference
    paddle_infer.PredictorPool)."""

    def __init__(self, config, size=1):
        self._predictors = [create_predictor(config) for _ in range(size)]

    def retrieve(self, idx):
        return self._predictors[idx]


def get_version():
    from .. import __version__
    return __version__


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT on TPU


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.FLOAT64: 8, DataType.BOOL: 1}
    return sizes.get(dtype, 4)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Offline precision conversion (reference
    paddle.inference.convert_to_mixed_precision): rewrites a saved
    state dict to bf16/fp16."""
    import numpy as np
    import ml_dtypes
    from ..framework.io import load, save
    state = load(params_file)
    target = ml_dtypes.bfloat16 if mixed_precision in (None, "bfloat16", 6) \
        else np.float16
    out = {}
    for k, v in state.items():
        arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
        if np.issubdtype(np.asarray(arr).dtype, np.floating):
            arr = np.asarray(arr).astype(target)
        out[k] = arr
    save(out, mixed_params_file)
    import shutil
    if model_file != mixed_model_file:
        shutil.copy(model_file, mixed_model_file)


def _get_phi_kernel_name(op_name):
    """Reference maps fluid op names to phi kernel names; here ops are
    already registry names."""
    return op_name


__all__ += ["DataType", "XpuConfig", "PredictorPool", "get_version",
            "get_trt_compile_version", "get_trt_runtime_version",
            "get_num_bytes_of_data_type", "convert_to_mixed_precision",
            "_get_phi_kernel_name"]
