"""paddle.inference — Config / Predictor deployment API.

Reference: paddle/fluid/inference/api/analysis_predictor.cc (:1719 Run,
:2752 ZeroCopyRun) + python/paddle/inference/wrapper.py.  The reference
runs an analysis pass pipeline over a serialized program then executes
zero-copy through the StandaloneExecutor; here the saved static Program
(static.save_inference_model) is loaded and each Run is one cached
jax.jit executable — XLA's fusion pipeline plays the role of the 309
analysis/IR passes.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType", "Tensor"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"


class Config:
    """Reference: paddle_infer.Config (inference/api/paddle_analysis_config.h)."""

    def __init__(self, model_path=None, params_path=None):
        # static.save_inference_model writes <prefix>.pdmodel.pkl +
        # <prefix>.pdiparams.npz; accept the prefix (or the .pdmodel path)
        if model_path and model_path.endswith(".pdmodel"):
            model_path = model_path[: -len(".pdmodel")]
        self.model_prefix = model_path
        self.params_path = params_path
        self._precision = PrecisionType.Float32
        self._device = None
        self._enable_memory_optim = True
        self._cpu_math_threads = 1
        self._switch_ir_optim = True

    # common toggles kept for API parity; XLA makes most of them no-ops
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = ("gpu", device_id)

    def enable_xpu(self, *a, **k):
        self._device = ("xpu", 0)

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    def switch_ir_optim(self, flag=True):
        self._switch_ir_optim = flag

    def enable_tensorrt_engine(self, *a, precision_mode=None, **k):
        # TensorRT has no TPU analog; precision hint maps to dtype cast
        if precision_mode is not None:
            self._precision = precision_mode

    def set_model(self, model_path, params_path=None):
        if model_path.endswith(".pdmodel"):
            model_path = model_path[: -len(".pdmodel")]
        self.model_prefix = model_path
        self.params_path = params_path

    def model_dir(self):
        return self.model_prefix

    def summary(self):
        return (f"Config(model={self.model_prefix}, "
                f"precision={self._precision})")


class _IOTensor:
    """Zero-copy handle (reference: paddle_infer.Tensor over phi tensors)."""

    def __init__(self, name, store):
        self.name = name
        self._store = store

    def copy_from_cpu(self, arr):
        self._store[self.name] = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._store[self.name])

    def shape(self):
        return list(np.shape(self._store.get(self.name, ())))

    def reshape(self, shape):
        pass  # shapes derive from copy_from_cpu input


Tensor = _IOTensor


class Predictor:
    def __init__(self, config: Config):
        from .. import static

        self.config = config
        prog, feeds, fetches = static.load_inference_model(
            config.model_prefix)
        self._program = prog
        self._feed_names = feeds
        self._fetch_vars = fetches
        self._exe = static.Executor()
        self._inputs = {}
        self._outputs = {}

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return [v.name for v in self._fetch_vars]

    def get_input_handle(self, name):
        return _IOTensor(name, self._inputs)

    def get_output_handle(self, name):
        return _IOTensor(name, self._outputs)

    def run(self, inputs=None):
        """Positional-list run (new API) or zero-copy handle run."""
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._inputs[name] = np.asarray(arr)
        feed = {n: self._inputs[n] for n in self._feed_names}
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_vars)
        for v, o in zip(self._fetch_vars, outs):
            self._outputs[v.name] = o
        return outs if inputs is not None else None

    def clone(self):
        """reference Predictor::Clone (goapi predictor.go Clone): a new
        predictor sharing the loaded weights and compiled executables —
        only the I/O buffers are private, so clones are safe to use
        from different request contexts."""
        p = object.__new__(Predictor)
        p.config = self.config
        p._program = self._program
        p._feed_names = list(self._feed_names)
        p._fetch_vars = self._fetch_vars
        p._exe = self._exe
        p._inputs = {}
        p._outputs = {}
        return p


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def create_engine(model, **kwargs):
    """Predictor-style entry to the continuous-batching LLM serving
    engine (paddle_tpu/serving/): one engine serves many concurrent
    generation requests over a shared paged KV pool.  Key knobs:
    ``enable_prefix_cache=True`` reuses resident KV pages across
    requests with shared prompt prefixes (prefill runs only the uncached
    suffix); ``sync_interval=N`` lets the greedy decode loop run N
    device steps per host sync.  See
    :func:`paddle_tpu.serving.create_engine` for the full list."""
    from ..serving import create_engine as _create
    return _create(model, **kwargs)


class DataType:
    """Tensor dtypes of the inference API (reference
    paddle_infer.DataType)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    FLOAT64 = 7
    BOOL = 8


class XpuConfig:
    """XPU device config placeholder (reference paddle_infer.XpuConfig);
    recorded, not acted on — there is no XPU here."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0
        self.conv_autotune_level = 0


class PredictorPool:
    """Pool of predictors over one config (reference
    paddle_infer.PredictorPool).

    Pool members are clones of one base predictor: they share the loaded
    weights, program, and executor compile cache (one jit executable per
    feed signature for the WHOLE pool), with private I/O buffers — the
    reference Clone() contract.  Building N independent predictors would
    reload and recompile N times."""

    def __init__(self, config, size=1):
        if size < 1:
            raise ValueError(f"PredictorPool size must be >= 1, got {size}")
        base = create_predictor(config)
        self._predictors = [base] + [base.clone() for _ in range(size - 1)]

    def size(self):
        return len(self._predictors)

    def retrieve(self, idx):
        if not 0 <= idx < len(self._predictors):
            raise IndexError(
                f"PredictorPool.retrieve({idx}): pool holds "
                f"{len(self._predictors)} predictors (valid indices "
                f"0..{len(self._predictors) - 1})")
        return self._predictors[idx]


def get_version():
    from .. import __version__
    return __version__


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT on TPU


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.FLOAT64: 8, DataType.BOOL: 1}
    return sizes.get(dtype, 4)


def _walk_refs(obj, params, vars_):
    """Collect ("__param__", i) indices and ("__var__", name) references
    from a pickled node's stripped args/kwargs tree."""
    if isinstance(obj, tuple) and len(obj) == 2:
        if obj[0] == "__param__":
            params.add(obj[1])
            return
        if obj[0] == "__var__":
            vars_.add(obj[1])
            return
    if isinstance(obj, (list, tuple)):
        for x in obj:
            _walk_refs(x, params, vars_)
    elif isinstance(obj, dict):
        for x in obj.values():
            _walk_refs(x, params, vars_)


def _io_and_named_params(model_file):
    """From a saved .pdmodel.pkl: (io_param_indices, param_index ->
    names of the graph vars whose op consumes it).  io params are the
    ones the feed-consuming and fetch-producing ops read — keeping them
    fp32 keeps the model's I/O tensors fp32 (dtype promotion: an fp32
    operand makes the op compute/emit fp32)."""
    import pickle
    with open(model_file, "rb") as f:
        meta = pickle.load(f)
    feeds = set(meta.get("feeds", ()))
    node_params: dict[str, set] = {}
    node_vars: dict[str, set] = {}
    for name, nd in meta["nodes"].items():
        p, v = set(), set()
        if not nd.get("feed"):
            _walk_refs(nd.get("args"), p, v)
            _walk_refs(nd.get("kwargs"), p, v)
        node_params[name] = p
        node_vars[name] = v
    io = set()
    for name in meta.get("fetches", ()):
        io |= node_params.get(name, set())
    for name, v in node_vars.items():
        if v & feeds:
            io |= node_params[name]
    names: dict[int, set] = {}
    for name, p in node_params.items():
        for i in p:
            names.setdefault(i, set()).add(name)
    return io, names


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Offline precision conversion (reference
    paddle.inference.convert_to_mixed_precision): rewrites saved
    parameters to bf16/fp16.

    Handles both artifact formats: ``save_inference_model`` output
    (``.pdiparams.npz`` + ``.pdmodel.pkl``) and plain ``paddle.save``
    state-dict pickles.

    ``black_list``: parameter/tensor names kept at their original dtype.
    Entries match state-dict keys, npz keys (``p<i>``), or — for the
    inference-model format — the graph-var names of ops consuming the
    parameter (the reference's op-level blacklist).

    ``keep_io_types``: ``True`` keeps the parameters of feed-consuming
    and fetch-producing ops fp32, so model inputs/outputs stay fp32
    (requires the graph in ``model_file``; a plain state dict has no
    I/O notion and True is a no-op there).  A collection is treated as
    explicit tensor names to keep, same matching as ``black_list``."""
    import shutil

    import ml_dtypes
    import numpy as np

    target = ml_dtypes.bfloat16 if mixed_precision in (None, "bfloat16", 6) \
        else np.float16
    black = set(black_list or ())
    keep_names = set() if isinstance(keep_io_types, bool) \
        else set(keep_io_types)

    def convert(arr):
        arr = np.asarray(arr)
        if np.issubdtype(arr.dtype, np.floating) \
                and arr.dtype == np.float32:
            return arr.astype(target)
        return arr

    try:                                    # inference-model npz format?
        pz = np.load(params_file)
        is_npz = True
    except Exception:
        is_npz = False

    if is_npz:
        from .. import static as _static
        io_params, consumer_names = _io_and_named_params(model_file) \
            if keep_io_types is True or black or keep_names \
            else (set(), {})
        n = _static._npz_param_count(pz)
        out = {}
        for i in range(n):
            key = f"p{i}"
            arr = _static._npz_unpack(pz, key)
            matched = ({key} | consumer_names.get(i, set()))
            keep = bool(matched & black) or bool(matched & keep_names) \
                or (keep_io_types is True and i in io_params)
            out[key] = np.asarray(arr) if keep else convert(arr)
        # write through a handle: np.savez(path) appends '.npz' when the
        # name lacks that suffix, which would move the artifact
        with open(mixed_params_file, "wb") as f:
            np.savez(f, **_static._npz_pack(out))
    else:                                   # paddle.save state dict
        from ..framework.io import load, save
        state = load(params_file)
        out = {}
        for k, v in state.items():
            arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            if k in black or k in keep_names:
                out[k] = np.asarray(arr)
            else:
                out[k] = convert(arr)
        save(out, mixed_params_file)

    if model_file != mixed_model_file:
        shutil.copy(model_file, mixed_model_file)


def _get_phi_kernel_name(op_name):
    """Reference maps fluid op names to phi kernel names; here ops are
    already registry names."""
    return op_name


__all__ += ["DataType", "XpuConfig", "PredictorPool", "create_engine",
            "get_version",
            "get_trt_compile_version", "get_trt_runtime_version",
            "get_num_bytes_of_data_type", "convert_to_mixed_precision",
            "_get_phi_kernel_name"]
