// C inference API for paddle_tpu (reference: paddle/fluid/inference/capi_exp/
// pd_config.h / pd_predictor.h / pd_tensor.h — PD_ConfigCreate,
// PD_PredictorCreate, PD_PredictorGetInputHandle, PD_TensorCopyFromCpuFloat,
// PD_PredictorRun, PD_TensorCopyToCpuFloat ...).
//
// The reference's C API fronts its native AnalysisPredictor.  Here the
// predictor runtime IS the Python package (each Run = one cached XLA
// executable), so the C ABI embeds CPython and drives
// paddle_tpu.inference.{Config,Predictor}.  Deploy model files come from
// paddle.static.save_inference_model / jit.save, same as the reference.
//
// Build: make -f Makefile inference  (links -lpython3.12).
// Thread model: calls must come from one thread at a time (the reference
// predictor is also single-stream per handle); the embedded interpreter is
// initialized once on first PD_ConfigCreate.
//
// No Go wrapper is shipped: the reference's Go API is a cgo shim over this
// same C surface and there is no Go toolchain in this image.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "pd_inference_c.h"

struct PD_Config {
  std::string model_path;
  std::string params_path;
};

struct PD_Predictor {
  PyObject* pred;  // paddle_tpu.inference.Predictor
};

struct PD_Tensor {
  PyObject* pred;        // owned ref (handles outlive PD_PredictorDestroy)
  std::string name;
  bool is_input;
  std::vector<int32_t> dims;
};

static bool g_inited = false;
static PyThreadState* g_main_ts = nullptr;

namespace {

struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

void ensure_python() {
  if (g_inited) return;
  g_inited = true;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // we created the interpreter and hold its GIL: release it so Gil{}
    // works uniformly from any caller thread.  When the host process
    // already runs Python (ctypes / embedding), its GIL state is not
    // ours to touch — Gil{} alone suffices.
    g_main_ts = PyEval_SaveThread();
  }
}

// fetch attr chain like "paddle_tpu.inference" -> module object (new ref)
PyObject* import_mod(const char* name) {
  PyObject* m = PyImport_ImportModule(name);
  if (!m) PyErr_Print();
  return m;
}

}  // namespace

extern "C" {

// ----------------------------------------------------------------- config
PD_Config* PD_ConfigCreate() {
  ensure_python();
  return new PD_Config();
}

void PD_ConfigSetModel(PD_Config* c, const char* model_path,
                       const char* params_path) {
  c->model_path = model_path ? model_path : "";
  c->params_path = params_path ? params_path : "";
}

const char* PD_ConfigGetModelDir(PD_Config* c) {
  return c->model_path.c_str();
}

void PD_ConfigDestroy(PD_Config* c) { delete c; }

// -------------------------------------------------------------- predictor
PD_Predictor* PD_PredictorCreate(PD_Config* c) {
  // reference semantics: PD_PredictorCreate consumes the config — on
  // every exit path, success or failure
  ensure_python();
  Gil gil;
  PyObject* pred = nullptr;
  PyObject* mod = import_mod("paddle_tpu.inference");
  if (mod) {
    PyObject* cfg = PyObject_CallMethod(mod, "Config", "ss",
                                        c->model_path.c_str(),
                                        c->params_path.c_str());
    if (!cfg) {
      PyErr_Print();
    } else {
      pred = PyObject_CallMethod(mod, "create_predictor", "O", cfg);
      if (!pred) PyErr_Print();
      Py_DECREF(cfg);
    }
    Py_DECREF(mod);
  }
  PD_ConfigDestroy(c);
  if (!pred) return nullptr;
  PD_Predictor* p = new PD_Predictor();
  p->pred = pred;
  return p;
}

PD_Predictor* PD_PredictorClone(PD_Predictor* p) {
  // reference Predictor::Clone: share weights/executables, private IO
  Gil gil;
  PyObject* cl = PyObject_CallMethod(p->pred, "clone", nullptr);
  if (!cl) {
    PyErr_Print();
    return nullptr;
  }
  PD_Predictor* q = new PD_Predictor();
  q->pred = cl;
  return q;
}

static size_t name_list_size(PyObject* pred, const char* method) {
  PyObject* names = PyObject_CallMethod(pred, method, nullptr);
  if (!names) {
    PyErr_Print();
    return 0;
  }
  size_t n = PyList_Size(names);
  Py_DECREF(names);
  return n;
}

size_t PD_PredictorGetInputNum(PD_Predictor* p) {
  Gil gil;
  return name_list_size(p->pred, "get_input_names");
}

size_t PD_PredictorGetOutputNum(PD_Predictor* p) {
  Gil gil;
  return name_list_size(p->pred, "get_output_names");
}

// separate buffers so an input-name and an output-name pointer can be
// alive at once (e.g. both as printf arguments); each stays valid until
// the next call of the SAME function on this thread
static thread_local std::string g_in_name_buf;
static thread_local std::string g_out_name_buf;

static const char* name_at(PyObject* pred, const char* method, size_t i,
                           std::string* buf) {
  PyObject* names = PyObject_CallMethod(pred, method, nullptr);
  if (!names) {
    PyErr_Print();
    return nullptr;
  }
  PyObject* it = PyList_GetItem(names, (Py_ssize_t)i);  // borrowed
  if (!it) {
    PyErr_Clear();  // out-of-range index must not poison the next call
    Py_DECREF(names);
    return nullptr;
  }
  *buf = PyUnicode_AsUTF8(it);
  Py_DECREF(names);
  return buf->c_str();
}

const char* PD_PredictorGetInputName(PD_Predictor* p, size_t i) {
  Gil gil;
  return name_at(p->pred, "get_input_names", i, &g_in_name_buf);
}

const char* PD_PredictorGetOutputName(PD_Predictor* p, size_t i) {
  Gil gil;
  return name_at(p->pred, "get_output_names", i, &g_out_name_buf);
}

PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor* p, const char* name) {
  Gil gil;
  PD_Tensor* t = new PD_Tensor();
  Py_INCREF(p->pred);
  t->pred = p->pred;
  t->name = name;
  t->is_input = true;
  return t;
}

PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor* p, const char* name) {
  Gil gil;
  PD_Tensor* t = new PD_Tensor();
  Py_INCREF(p->pred);
  t->pred = p->pred;
  t->name = name;
  t->is_input = false;
  return t;
}

int PD_PredictorRun(PD_Predictor* p) {
  Gil gil;
  PyObject* r = PyObject_CallMethod(p->pred, "run", nullptr);
  if (!r) {
    PyErr_Print();
    return 0;
  }
  Py_DECREF(r);
  return 1;
}

void PD_PredictorDestroy(PD_Predictor* p) {
  {
    Gil gil;
    Py_XDECREF(p->pred);
  }
  delete p;
}

// ------------------------------------------------------------------ tensor
void PD_TensorReshape(PD_Tensor* t, size_t ndims, const int32_t* dims) {
  t->dims.assign(dims, dims + ndims);
}

static int copy_from_cpu(PD_Tensor* t, const void* data, const char* npdtype,
                         size_t itemsize) {
  Gil gil;
  size_t n = 1;
  for (int32_t d : t->dims) n *= (size_t)d;
  PyObject* np = import_mod("numpy");
  if (!np) return 0;
  PyObject* dims = PyTuple_New(t->dims.size());
  for (size_t i = 0; i < t->dims.size(); ++i)
    PyTuple_SetItem(dims, i, PyLong_FromLong(t->dims[i]));
  // numpy.frombuffer(bytes, dtype).reshape(dims).copy()
  PyObject* bytes =
      PyBytes_FromStringAndSize((const char*)data, (Py_ssize_t)(n * itemsize));
  PyObject* flat =
      PyObject_CallMethod(np, "frombuffer", "Os", bytes, npdtype);
  Py_DECREF(bytes);
  Py_DECREF(np);
  if (!flat) {
    PyErr_Print();
    Py_DECREF(dims);
    return 0;
  }
  PyObject* arr = PyObject_CallMethod(flat, "reshape", "O", dims);
  Py_DECREF(flat);
  Py_DECREF(dims);
  if (!arr) {
    PyErr_Print();
    return 0;
  }
  PyObject* handle =
      PyObject_CallMethod(t->pred, "get_input_handle", "s", t->name.c_str());
  if (!handle) {
    PyErr_Print();
    Py_DECREF(arr);
    return 0;
  }
  PyObject* r = PyObject_CallMethod(handle, "copy_from_cpu", "O", arr);
  Py_DECREF(arr);
  Py_DECREF(handle);
  if (!r) {
    PyErr_Print();
    return 0;
  }
  Py_DECREF(r);
  return 1;
}

int PD_TensorCopyFromCpuFloat(PD_Tensor* t, const float* data) {
  return copy_from_cpu(t, data, "float32", 4);
}

int PD_TensorCopyFromCpuInt64(PD_Tensor* t, const int64_t* data) {
  return copy_from_cpu(t, data, "int64", 8);
}

int PD_TensorCopyFromCpuInt32(PD_Tensor* t, const int32_t* data) {
  return copy_from_cpu(t, data, "int32", 4);
}

// output helpers: fetch np array (C-contiguous float32/int) for the fetch var
static PyObject* fetch_output(PD_Tensor* t, const char* npdtype) {
  PyObject* handle =
      PyObject_CallMethod(t->pred, "get_output_handle", "s", t->name.c_str());
  if (!handle) {
    PyErr_Print();
    return nullptr;
  }
  PyObject* arr = PyObject_CallMethod(handle, "copy_to_cpu", nullptr);
  Py_DECREF(handle);
  if (!arr) {
    PyErr_Print();
    return nullptr;
  }
  PyObject* np = import_mod("numpy");
  PyObject* cast = PyObject_CallMethod(
      np, "ascontiguousarray", "Os", arr, npdtype);
  Py_DECREF(np);
  Py_DECREF(arr);
  if (!cast) PyErr_Print();
  return cast;
}

static PyObject* tensor_shape_seq(PD_Tensor* t) {
  // handle.shape() reads the stored array's dims — no data copy/cast
  const char* getter =
      t->is_input ? "get_input_handle" : "get_output_handle";
  PyObject* handle =
      PyObject_CallMethod(t->pred, getter, "s", t->name.c_str());
  if (!handle) {
    PyErr_Print();
    return nullptr;
  }
  PyObject* shape = PyObject_CallMethod(handle, "shape", nullptr);
  Py_DECREF(handle);
  if (!shape) {
    PyErr_Print();
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(shape, "shape not a sequence");
  Py_DECREF(shape);
  if (!seq) PyErr_Print();
  return seq;
}

int PD_TensorGetRank(PD_Tensor* t, size_t* ndims) {
  Gil gil;
  PyObject* seq = tensor_shape_seq(t);
  if (!seq) return 0;
  *ndims = (size_t)PySequence_Fast_GET_SIZE(seq);
  Py_DECREF(seq);
  return 1;
}

int PD_TensorGetShape(PD_Tensor* t, size_t* ndims, int32_t* dims) {
  Gil gil;
  PyObject* seq = tensor_shape_seq(t);
  if (!seq) return 0;
  *ndims = (size_t)PySequence_Fast_GET_SIZE(seq);
  for (size_t i = 0; i < *ndims; ++i)
    dims[i] = (int32_t)PyLong_AsLong(
        PySequence_Fast_GET_ITEM(seq, (Py_ssize_t)i));
  Py_DECREF(seq);
  return 1;
}

static int copy_to_cpu(PD_Tensor* t, void* out, const char* npdtype,
                       size_t itemsize) {
  Gil gil;
  PyObject* arr = fetch_output(t, npdtype);
  if (!arr) return 0;
  PyObject* bytes = PyObject_CallMethod(arr, "tobytes", nullptr);
  Py_DECREF(arr);
  if (!bytes) {
    PyErr_Print();
    return 0;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(bytes, &buf, &len);
  std::memcpy(out, buf, (size_t)len);
  Py_DECREF(bytes);
  (void)itemsize;
  return 1;
}

int PD_TensorCopyToCpuFloat(PD_Tensor* t, float* out) {
  return copy_to_cpu(t, out, "float32", 4);
}

int PD_TensorCopyToCpuInt64(PD_Tensor* t, int64_t* out) {
  return copy_to_cpu(t, out, "int64", 8);
}

void PD_TensorDestroy(PD_Tensor* t) {
  {
    Gil gil;
    Py_XDECREF(t->pred);
  }
  delete t;
}

}  // extern "C"
