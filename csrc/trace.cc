// Host-side event tracer: low-overhead span recording + chrome-trace export.
// TPU-native analog of the reference profiler's HostTracer
// (paddle/phi/api/profiler/event_tracing.h, chrometracing_logger.cc):
// instrumented RecordEvent spans collected in C++, exported as a
// chrome://tracing JSON that can be merged with jax.profiler device traces.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {

namespace {

struct Event {
  std::string name;
  uint64_t tid;
  int64_t ts_ns;    // start, monotonic
  int64_t dur_ns;   // span duration; -1 => instant event
};

std::mutex g_mu;
std::vector<Event> g_events;
std::atomic<bool> g_enabled{false};

struct Open {
  std::string name;
  int64_t start_ns;
};
thread_local std::vector<Open> t_stack;

int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

uint64_t tid_hash() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id()) & 0xffffff;
}

}  // namespace

void pt_trace_enable(int on) { g_enabled.store(on != 0); }

int pt_trace_enabled() { return g_enabled.load() ? 1 : 0; }

void pt_trace_begin(const char* name) {
  if (!g_enabled.load()) return;
  t_stack.push_back(Open{name ? name : "", now_ns()});
}

void pt_trace_end() {
  if (t_stack.empty()) return;
  Open o = t_stack.back();
  t_stack.pop_back();
  if (!g_enabled.load()) return;
  int64_t end = now_ns();
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.push_back(Event{std::move(o.name), tid_hash(), o.start_ns,
                           end - o.start_ns});
}

void pt_trace_instant(const char* name) {
  if (!g_enabled.load()) return;
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.push_back(Event{name ? name : "", tid_hash(), now_ns(), -1});
}

void pt_trace_clear() {
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.clear();
}

int64_t pt_trace_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  return static_cast<int64_t>(g_events.size());
}

// Export events as chrome trace JSON ("traceEvents" array).  Returns 0 on
// success.  pid is taken from the caller so multi-process traces merge.
int pt_trace_export(const char* path, int64_t pid) {
  std::vector<Event> snap;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    snap = g_events;
  }
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fputs("{\"traceEvents\":[", f);
  bool first = true;
  for (const auto& e : snap) {
    if (!first) std::fputc(',', f);
    first = false;
    // escape name minimally (quotes + backslash)
    std::string n;
    n.reserve(e.name.size());
    for (char c : e.name) {
      if (c == '"' || c == '\\') n.push_back('\\');
      if (static_cast<unsigned char>(c) >= 0x20) n.push_back(c);
    }
    if (e.dur_ns >= 0) {
      std::fprintf(f,
                   "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%lld,\"tid\":%llu,"
                   "\"ts\":%.3f,\"dur\":%.3f}",
                   n.c_str(), static_cast<long long>(pid),
                   static_cast<unsigned long long>(e.tid), e.ts_ns / 1e3,
                   e.dur_ns / 1e3);
    } else {
      std::fprintf(f,
                   "{\"name\":\"%s\",\"ph\":\"i\",\"s\":\"t\",\"pid\":%lld,"
                   "\"tid\":%llu,\"ts\":%.3f}",
                   n.c_str(), static_cast<long long>(pid),
                   static_cast<unsigned long long>(e.tid), e.ts_ns / 1e3);
    }
  }
  std::fputs("]}", f);
  std::fclose(f);
  return 0;
}

// Fill out_ns[i] = {ts, dur} pairs for python-side statistics; returns number
// of events copied (<= cap).  Names are returned via a packed buffer of
// NUL-separated strings (out_names, cap bytes out_names_cap).
int64_t pt_trace_snapshot(int64_t* out_ns, int64_t cap_pairs, char* out_names,
                          int64_t out_names_cap) {
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t n = 0;
  int64_t off = 0;
  for (const auto& e : g_events) {
    if (n >= cap_pairs) break;
    int64_t need = static_cast<int64_t>(e.name.size()) + 1;
    if (off + need > out_names_cap) break;
    std::memcpy(out_names + off, e.name.c_str(), need);
    off += need;
    out_ns[2 * n] = e.ts_ns;
    out_ns[2 * n + 1] = e.dur_ns;
    ++n;
  }
  return n;
}

}  // extern "C"
