/* C inference API for paddle_tpu.
 *
 * Reference surface: paddle/fluid/inference/capi_exp/pd_inference_api.h
 * (PD_Config* / PD_Predictor* / PD_Tensor* families).  Link against
 * libpaddle_tpu_infer.so (build: `make -C csrc inference`); the library
 * embeds CPython and drives the paddle_tpu.inference predictor, whose
 * Run is one cached XLA executable.
 *
 * Calls must come from one thread at a time.  PD_PredictorCreate consumes
 * the config (reference semantics).
 */
#ifndef PD_INFERENCE_C_H
#define PD_INFERENCE_C_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;
typedef struct PD_Tensor PD_Tensor;

/* config */
PD_Config* PD_ConfigCreate(void);
void PD_ConfigSetModel(PD_Config*, const char* model_path,
                       const char* params_path);
const char* PD_ConfigGetModelDir(PD_Config*);
void PD_ConfigDestroy(PD_Config*);

/* predictor */
PD_Predictor* PD_PredictorCreate(PD_Config*);      /* consumes config */
size_t PD_PredictorGetInputNum(PD_Predictor*);
size_t PD_PredictorGetOutputNum(PD_Predictor*);
/* returned pointers stay valid until the next call of the same function
 * on the same thread */
const char* PD_PredictorGetInputName(PD_Predictor*, size_t i);
const char* PD_PredictorGetOutputName(PD_Predictor*, size_t i);
PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor*, const char* name);
PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor*, const char* name);
int PD_PredictorRun(PD_Predictor*);                /* 1 = ok */
/* weight-sharing clone (reference Predictor::Clone); NULL on failure */
PD_Predictor* PD_PredictorClone(PD_Predictor*);
void PD_PredictorDestroy(PD_Predictor*);

/* tensors */
void PD_TensorReshape(PD_Tensor*, size_t ndims, const int32_t* dims);
int PD_TensorCopyFromCpuFloat(PD_Tensor*, const float* data);
int PD_TensorCopyFromCpuInt64(PD_Tensor*, const int64_t* data);
int PD_TensorCopyFromCpuInt32(PD_Tensor*, const int32_t* data);
/* two-phase shape query (reference PD_OneDimArrayInt32 pattern):
   GetRank first, then GetShape with a dims buffer of that capacity */
int PD_TensorGetRank(PD_Tensor*, size_t* ndims);   /* 1 = ok */
int PD_TensorGetShape(PD_Tensor*, size_t* ndims, int32_t* dims);
int PD_TensorCopyToCpuFloat(PD_Tensor*, float* out);
int PD_TensorCopyToCpuInt64(PD_Tensor*, int64_t* out);
void PD_TensorDestroy(PD_Tensor*);

#ifdef __cplusplus
}
#endif
#endif /* PD_INFERENCE_C_H */
