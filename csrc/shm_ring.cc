// Process-shared ring buffer over POSIX shared memory.
// TPU-native analog of the reference DataLoader's shared-memory channel
// (paddle/phi/core/memory/allocation/mmap_allocator.cc + the mmap shm path of
// python/paddle/io/dataloader/dataloader_iter.py): worker processes push
// serialized batches into a shm ring; the trainer process pops them without a
// pipe copy.  Multi-producer/multi-consumer via process-shared pthread
// mutex + condvars stored in the shm header.
//
// Record layout inside the data region: u32 len | payload, with a wrap marker
// (len == 0xFFFFFFFF) when a record would straddle the end.
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <cstdio>

namespace {

constexpr uint64_t kMagic = 0x70745f72696e6701ULL;  // "pt_ring" v1
constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;

struct Header {
  uint64_t magic;
  uint64_t capacity;     // data region bytes
  uint64_t head;         // read offset  (consumer)
  uint64_t tail;         // write offset (producer)
  uint64_t used;         // bytes in use (records incl. headers)
  uint64_t n_items;
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint32_t closed;
  uint32_t _pad;
};

struct Ring {
  Header* hdr;
  char* data;
  uint64_t map_len;
  char name[256];
  bool owner;
};

void abs_deadline(struct timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += static_cast<long>(timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Create a named ring with `capacity` data bytes.  Returns handle or null.
void* pt_ring_create(const char* name, uint64_t capacity) {
  ::shm_unlink(name);  // stale segment from a crashed run
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t map_len = sizeof(Header) + capacity;
  if (::ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem =
      ::mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
  Header* h = static_cast<Header*>(mem);
  std::memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
#ifdef PTHREAD_MUTEX_ROBUST
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
#endif
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_empty, &ca);
  pthread_cond_init(&h->not_full, &ca);
  h->magic = kMagic;
  Ring* r = new Ring();
  r->hdr = h;
  r->data = static_cast<char*>(mem) + sizeof(Header);
  r->map_len = map_len;
  std::snprintf(r->name, sizeof(r->name), "%s", name);
  r->owner = true;
  return r;
}

void* pt_ring_attach(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) {
    ::munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = h;
  r->data = static_cast<char*>(mem) + sizeof(Header);
  r->map_len = static_cast<uint64_t>(st.st_size);
  std::snprintf(r->name, sizeof(r->name), "%s", name);
  r->owner = false;
  return r;
}

// Push one record.  Blocks while full (up to timeout_ms; <0 => forever).
// Returns 0 ok, -1 timeout, -2 closed, -3 record larger than capacity.
int pt_ring_push(void* hd, const char* buf, uint64_t len, int timeout_ms) {
  Ring* r = static_cast<Ring*>(hd);
  Header* h = r->hdr;
  uint64_t need = 4 + len;
  if (need + 4 > h->capacity) return -3;  // +4: room for a wrap marker
  struct timespec ts;
  if (timeout_ms >= 0) abs_deadline(&ts, timeout_ms);
  pthread_mutex_lock(&h->mu);
  while (!h->closed) {
    uint64_t tail_room = h->capacity - h->tail;
    uint64_t eff = need + (tail_room < need ? tail_room : 0);
    if (h->capacity - h->used >= eff) break;
    int rc = timeout_ms >= 0
                 ? pthread_cond_timedwait(&h->not_full, &h->mu, &ts)
                 : pthread_cond_wait(&h->not_full, &h->mu);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  uint64_t tail_room = h->capacity - h->tail;
  if (tail_room < need) {
    // wrap: mark the remainder dead and start at 0
    if (tail_room >= 4) {
      uint32_t m = kWrapMarker;
      std::memcpy(r->data + h->tail, &m, 4);
    }
    h->used += tail_room;
    h->tail = 0;
  }
  uint32_t len32 = static_cast<uint32_t>(len);
  std::memcpy(r->data + h->tail, &len32, 4);
  std::memcpy(r->data + h->tail + 4, buf, len);
  h->tail = (h->tail + need) % h->capacity;
  h->used += need;
  h->n_items += 1;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Pop one record into a malloc'd buffer (*out, caller frees via pt_free).
// Returns length >= 0, -1 timeout, -2 closed-and-empty.
int64_t pt_ring_pop(void* hd, char** out, int timeout_ms) {
  Ring* r = static_cast<Ring*>(hd);
  Header* h = r->hdr;
  struct timespec ts;
  if (timeout_ms >= 0) abs_deadline(&ts, timeout_ms);
  pthread_mutex_lock(&h->mu);
  while (h->n_items == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    int rc = timeout_ms >= 0
                 ? pthread_cond_timedwait(&h->not_empty, &h->mu, &ts)
                 : pthread_cond_wait(&h->not_empty, &h->mu);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  uint64_t head_room = h->capacity - h->head;
  uint32_t len32;
  if (head_room < 4) {
    h->used -= head_room;
    h->head = 0;
  } else {
    std::memcpy(&len32, r->data + h->head, 4);
    if (len32 == kWrapMarker) {
      h->used -= head_room;
      h->head = 0;
    }
  }
  std::memcpy(&len32, r->data + h->head, 4);
  *out = static_cast<char*>(std::malloc(len32 ? len32 : 1));
  std::memcpy(*out, r->data + h->head + 4, len32);
  uint64_t need = 4 + len32;
  h->head = (h->head + need) % h->capacity;
  h->used -= need;
  h->n_items -= 1;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len32);
}

uint64_t pt_ring_size(void* hd) {
  Ring* r = static_cast<Ring*>(hd);
  pthread_mutex_lock(&r->hdr->mu);
  uint64_t n = r->hdr->n_items;
  pthread_mutex_unlock(&r->hdr->mu);
  return n;
}

// Mark closed: producers stop, consumers drain then get -2.
void pt_ring_close(void* hd) {
  Ring* r = static_cast<Ring*>(hd);
  pthread_mutex_lock(&r->hdr->mu);
  r->hdr->closed = 1;
  pthread_cond_broadcast(&r->hdr->not_empty);
  pthread_cond_broadcast(&r->hdr->not_full);
  pthread_mutex_unlock(&r->hdr->mu);
}

void pt_ring_free(void* hd) {
  Ring* r = static_cast<Ring*>(hd);
  if (!r) return;
  bool owner = r->owner;
  char name[256];
  std::snprintf(name, sizeof(name), "%s", r->name);
  ::munmap(reinterpret_cast<void*>(r->hdr), r->map_len);
  if (owner) ::shm_unlink(name);
  delete r;
}

}  // extern "C"
