// TCPStore: rendezvous key-value store over raw TCP sockets.
// TPU-native analog of the reference bootstrap store
// (paddle/phi/core/distributed/store/tcp_store.h:121, tcp_utils.cc):
// a master rank runs the server; every rank connects as a client and uses
// set/get/add/wait to exchange small blobs (addresses, meshes, barrier
// counters) before jax.distributed / ICI collectives take over.
//
// Protocol (all little-endian):
//   request : u8 cmd | u32 klen | key | u32 vlen | value
//   response: u32 len | payload            (GET/ADD/WAIT)
// Commands: 0=SET 1=GET(blocking) 2=ADD(i64 delta -> i64 new) 3=WAIT
//           4=DELETE 5=NUM_KEYS 6=CHECK(non-blocking; 1/0)
// Server: accept-loop thread + thread per connection; kv guarded by a mutex,
// blocking GET/WAIT park on a condition variable.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Cmd : uint8_t {
  kSet = 0,
  kGet = 1,
  kAdd = 2,
  kWait = 3,
  kDelete = 4,
  kNumKeys = 5,
  kCheck = 6,
};

bool send_all(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool send_blob(int fd, const std::string& s) {
  uint32_t len = static_cast<uint32_t>(s.size());
  if (!send_all(fd, &len, 4)) return false;
  return s.empty() || send_all(fd, s.data(), s.size());
}

bool recv_blob(int fd, std::string* out) {
  uint32_t len = 0;
  if (!recv_all(fd, &len, 4)) return false;
  out->resize(len);
  return len == 0 || recv_all(fd, &(*out)[0], len);
}

struct Server {
  int listen_fd = -1;
  std::thread accept_thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv;
  std::unordered_map<std::string, std::string> kv;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;
  std::mutex conns_mu;

  // Serve one request; false => connection done (error, peer gone, or stop).
  bool serve_one(int fd) {
    uint8_t cmd;
    if (!recv_all(fd, &cmd, 1)) return false;
    std::string key, val;
    if (!recv_blob(fd, &key) || !recv_blob(fd, &val)) return false;
    switch (cmd) {
      case kSet: {
        {
          std::lock_guard<std::mutex> lk(mu);
          kv[key] = val;
        }
        cv.notify_all();
        uint32_t zero = 0;
        return send_all(fd, &zero, 4);
      }
      case kGet: {
        std::string out;
        {
          std::unique_lock<std::mutex> lk(mu);
          cv.wait(lk, [&] { return stop.load() || kv.count(key) != 0; });
          if (stop.load()) return false;
          out = kv[key];
        }
        return send_blob(fd, out);
      }
      case kAdd: {
        int64_t delta = 0;
        if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
        int64_t cur = 0;
        {
          std::lock_guard<std::mutex> lk(mu);
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string enc(8, '\0');
          std::memcpy(&enc[0], &cur, 8);
          kv[key] = enc;
        }
        cv.notify_all();
        std::string out(8, '\0');
        std::memcpy(&out[0], &cur, 8);
        return send_blob(fd, out);
      }
      case kWait: {
        {
          std::unique_lock<std::mutex> lk(mu);
          cv.wait(lk, [&] { return stop.load() || kv.count(key) != 0; });
          if (stop.load()) return false;
        }
        std::string ok("\x01", 1);
        return send_blob(fd, ok);
      }
      case kDelete: {
        uint32_t n;
        {
          std::lock_guard<std::mutex> lk(mu);
          n = static_cast<uint32_t>(kv.erase(key));
        }
        return send_all(fd, &n, 4);
      }
      case kNumKeys: {
        int64_t n;
        {
          std::lock_guard<std::mutex> lk(mu);
          n = static_cast<int64_t>(kv.size());
        }
        std::string out(8, '\0');
        std::memcpy(&out[0], &n, 8);
        return send_blob(fd, out);
      }
      case kCheck: {
        bool has;
        {
          std::lock_guard<std::mutex> lk(mu);
          has = kv.count(key) != 0;
        }
        std::string out(has ? "\x01" : "\x00", 1);
        return send_blob(fd, out);
      }
      default:
        return false;
    }
  }

  void handle(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    while (!stop.load() && serve_one(fd)) {
    }
    ::close(fd);
  }

  void accept_loop() {
    for (;;) {
      struct pollfd pfd = {listen_fd, POLLIN, 0};
      int r = ::poll(&pfd, 1, 200);
      if (stop.load()) return;
      if (r <= 0) continue;
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) continue;
      std::lock_guard<std::mutex> lk(conns_mu);
      conn_fds.push_back(fd);
      conns.emplace_back([this, fd] { handle(fd); });
    }
  }
};

struct Client {
  int fd = -1;
};

}  // namespace

extern "C" {

// Returns server handle, or null.  port==0 picks a free port; the bound port
// is written to *out_port.
void* pt_store_server_start(int port, int* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (out_port) *out_port = ntohs(addr.sin_port);
  Server* s = new Server();
  s->listen_fd = fd;
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

void pt_store_server_stop(void* h) {
  Server* s = static_cast<Server*>(h);
  if (!s) return;
  s->stop.store(true);
  s->cv.notify_all();
  if (s->accept_thread.joinable()) s->accept_thread.join();
  ::close(s->listen_fd);
  // Unblock handlers stuck in recv by shutting their sockets, then join them
  // all before freeing the Server (no use-after-free on mu/cv/kv).
  {
    std::lock_guard<std::mutex> lk(s->conns_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->conns)
    if (t.joinable()) t.join();
  delete s;
}

void* pt_store_client_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return nullptr;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      Client* c = new Client();
      c->fd = fd;
      return c;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void pt_store_client_close(void* h) {
  Client* c = static_cast<Client*>(h);
  if (!c) return;
  ::close(c->fd);
  delete c;
}

namespace {
bool send_req(Client* c, uint8_t cmd, const char* key, int klen,
              const char* val, int vlen) {
  if (!send_all(c->fd, &cmd, 1)) return false;
  uint32_t kl = static_cast<uint32_t>(klen), vl = static_cast<uint32_t>(vlen);
  if (!send_all(c->fd, &kl, 4)) return false;
  if (klen && !send_all(c->fd, key, klen)) return false;
  if (!send_all(c->fd, &vl, 4)) return false;
  if (vlen && !send_all(c->fd, val, vlen)) return false;
  return true;
}
}  // namespace

int pt_store_set(void* h, const char* key, int klen, const char* val,
                 int vlen) {
  Client* c = static_cast<Client*>(h);
  if (!send_req(c, kSet, key, klen, val, vlen)) return -1;
  uint32_t ack;
  return recv_all(c->fd, &ack, 4) ? 0 : -1;
}

// Blocking get; returns malloc'd buffer via *out (caller frees with pt_free),
// length as return value, -1 on error.
int64_t pt_store_get(void* h, const char* key, int klen, char** out) {
  Client* c = static_cast<Client*>(h);
  if (!send_req(c, kGet, key, klen, nullptr, 0)) return -1;
  std::string blob;
  if (!recv_blob(c->fd, &blob)) return -1;
  *out = static_cast<char*>(std::malloc(blob.size() ? blob.size() : 1));
  std::memcpy(*out, blob.data(), blob.size());
  return static_cast<int64_t>(blob.size());
}

int64_t pt_store_add(void* h, const char* key, int klen, int64_t delta) {
  Client* c = static_cast<Client*>(h);
  char enc[8];
  std::memcpy(enc, &delta, 8);
  if (!send_req(c, kAdd, key, klen, enc, 8)) return INT64_MIN;
  std::string blob;
  if (!recv_blob(c->fd, &blob) || blob.size() != 8) return INT64_MIN;
  int64_t v;
  std::memcpy(&v, blob.data(), 8);
  return v;
}

int pt_store_wait(void* h, const char* key, int klen) {
  Client* c = static_cast<Client*>(h);
  if (!send_req(c, kWait, key, klen, nullptr, 0)) return -1;
  std::string blob;
  return recv_blob(c->fd, &blob) ? 0 : -1;
}

int pt_store_check(void* h, const char* key, int klen) {
  Client* c = static_cast<Client*>(h);
  if (!send_req(c, kCheck, key, klen, nullptr, 0)) return -1;
  std::string blob;
  if (!recv_blob(c->fd, &blob) || blob.size() != 1) return -1;
  return blob[0] ? 1 : 0;
}

int pt_store_delete(void* h, const char* key, int klen) {
  Client* c = static_cast<Client*>(h);
  if (!send_req(c, kDelete, key, klen, nullptr, 0)) return -1;
  uint32_t n;
  return recv_all(c->fd, &n, 4) ? static_cast<int>(n) : -1;
}

int64_t pt_store_num_keys(void* h) {
  Client* c = static_cast<Client*>(h);
  if (!send_req(c, kNumKeys, nullptr, 0, nullptr, 0)) return -1;
  std::string blob;
  if (!recv_blob(c->fd, &blob) || blob.size() != 8) return -1;
  int64_t v;
  std::memcpy(&v, blob.data(), 8);
  return v;
}

void pt_free(void* p) { std::free(p); }

}  // extern "C"
