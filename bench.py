"""Benchmark ladder (BASELINE.md #1-#5) on the available chip(s).

Prints ONE JSON line per metric, flagship Llama first:
  llama_train_tokens_per_sec_per_chip   (ladder #4-lite, MFU vs 40% target)
  resnet50_train_images_per_sec_per_chip (ladder #2, conv/BN/AMP)
  bert_base_train_examples_per_sec_per_chip (ladder #3, encoder/AdamW)
  moe_train_tokens_per_sec_per_chip     (ladder #5, gating+dispatch)
  lenet_eager_steps_per_sec             (ladder #1, dygraph dispatch vs jit)

vs_baseline: the reference publishes no absolute numbers (BASELINE.md);
where MFU is defined the north star is >=40% MFU so vs_baseline =
measured_MFU / 0.40; for LeNet it is the eager/jit throughput ratio
(dygraph dispatch efficiency).
"""
from __future__ import annotations

import gc
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak TFLOP/s per chip by device kind (public figures)
PEAK_TFLOPS = {
    "TPU v5p": 459.0, "TPU v5 lite": 197.0, "TPU v5e": 197.0,
    "TPU v6 lite": 918.0, "TPU v6e": 918.0, "TPU v4": 275.0,
    "TPU v3": 123.0, "TPU v2": 45.0,
}


def _peak_flops(kind: str) -> float:
    for k, v in PEAK_TFLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v * 1e12
    return 197e12  # unknown chip: assume v5e-class


_BACKEND_READY = False


def _ensure_backend():
    """Resolve the backend ONCE, falling back to CPU when the preferred
    plugin is unavailable.  ``jax.devices()`` on an unreachable
    accelerator can block for minutes before raising UNAVAILABLE, and
    the per-rung retry loop used to re-trigger that init every attempt
    — a transport outage became an rc=124 timeout with zero numbers
    (BENCH_r05.json).  One bounded attempt; on failure pin
    ``JAX_PLATFORMS=cpu`` so every later ``jax.devices()`` is instant
    and the bench still emits its CPU smoke-mode lines."""
    global _BACKEND_READY
    if _BACKEND_READY:
        return
    try:
        jax.devices()
        _BACKEND_READY = True
        return
    except RuntimeError as e:
        print(json.dumps({"backend_fallback": "cpu",
                          "error": f"{type(e).__name__}: {e}"[:300]}),
              flush=True)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    # drop any half-initialized backend clients so the cpu retry below
    # starts clean (API moved across jax versions; best effort)
    for clear in ("extend.backend.clear_backends", "clear_backends"):
        try:
            obj = jax
            for part in clear.split("."):
                obj = getattr(obj, part)
            obj()
            break
        except Exception:
            continue
    jax.devices()                  # raises only if even CPU is broken
    _BACKEND_READY = True


def _env():
    _ensure_backend()
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    return dev, on_tpu, (len(jax.devices()) if on_tpu else 1)


_SUMMARY: list = []


def _emit(metric, value, unit, vs_baseline, detail):
    print(json.dumps({
        "metric": metric, "value": round(float(value), 2), "unit": unit,
        "vs_baseline": round(float(vs_baseline), 4), "detail": detail,
    }), flush=True)
    _SUMMARY.append((metric, round(float(value), 2), unit,
                     round(float(vs_baseline), 4)))


def _llama_throughput(cfg, mesh, batch, seq, steps, dtype, on_tpu, dev,
                      dp_shard=False, n_chips=1):
    """Shared llama-rung core: setup -> compile -> warmup -> timed steps.
    Returns (tokens/s, mfu, loss).  Timing notes: host fetch (not
    block_until_ready — the tunneled axon backend can report readiness
    early); warmup absorbs the slow first post-compile steps."""
    from paddle_tpu.models import llama_hybrid as H

    params, opt = H.setup(cfg, mesh, dtype=dtype)
    step = H.build_train_step(cfg, mesh, n_micro=1, remat=on_tpu, sp=False)
    ids_np = np.random.randint(0, cfg.vocab_size,
                               (batch, seq + 1)).astype(np.int64)
    if dp_shard:
        ids = jax.device_put(ids_np, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp", None)))
    else:
        ids = jnp.asarray(ids_np)
    loss, params, opt = step(params, opt, ids)
    float(loss)
    for _ in range(3):
        loss, params, opt = step(params, opt, ids)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params, opt = step(params, opt, ids)
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    tps = batch * seq * steps / dt
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    # tps is TOTAL tokens/s across the mesh; peak scales with chip count
    mfu = tps * (6 * n_params + attn_flops) / (
        n_chips * _peak_flops(dev.device_kind if on_tpu else "cpu"))
    return tps, (mfu if on_tpu else 0.0), loss_val, n_params


def bench_llama():
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_hybrid as H

    dev, on_tpu, n = _env()
    if on_tpu:
        # ~1B params saturates the MXU on one v5e chip (~16G HBM) with
        # bf16 params + fp32 AdamW state + flash attention + chunked CE
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype="bfloat16")
        batch, seq, steps = 8, 2048, 10
        dtype = jnp.bfloat16
    else:  # CPU smoke mode so the bench is runnable anywhere
        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=8, max_position_embeddings=512)
        batch, seq, steps = 4, 256, 3
        dtype = jnp.float32

    pp, dp, tp = (1, n, 1) if n > 1 else (1, 1, 1)
    mesh = H.build_mesh(n, pp=pp, dp=dp, tp=tp)
    tps, mfu, loss_val, n_params = _llama_throughput(
        cfg, mesh, batch, seq, steps, dtype, on_tpu, dev, dp_shard=n > 1,
        n_chips=n)
    _emit("llama_train_tokens_per_sec_per_chip", tps / n,
          "tokens/s/chip", mfu / 0.40 if on_tpu else 0.0,
          {"mfu": round(mfu, 4), "chips": n, "device": dev.device_kind,
           "params": int(n_params), "loss": loss_val})


def bench_resnet50():
    """Ladder #2: ResNet50 + AMP O1 (conv/BN/momentum on the MXU)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.vision.models import resnet50

    dev, on_tpu, _ = _env()
    n = 1  # runs on one device; per-chip numbers divide by what is used
    # batch 128 (measured r4 with the multi_step harness: 2570 img/s vs
    # 2377 at b512 — the earlier "b512 wins" came from a per-dispatch
    # harness whose launch overhead shrank with batch)
    batch, steps = (128, 2) if on_tpu else (4, 1)
    hw = 224 if on_tpu else 32

    model = resnet50(num_classes=1000)
    model.train()
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())

    def loss_fn(m, x, y):
        with paddle.amp.auto_cast(enable=on_tpu, level="O1"):
            out = m(x)
        return F.cross_entropy(out, y)

    # one dispatch per `chunk` steps: per-dispatch transport latency
    # (tens of ms on tunneled devices) must not masquerade as step time
    chunk = 25 if on_tpu else 2
    step = paddle.jit.train_step(model, o, loss_fn).multi_step(chunk)
    x = paddle.to_tensor(
        np.random.randn(batch, 3, hw, hw).astype(np.float32))
    y = paddle.to_tensor(
        np.random.randint(0, 1000, (batch,)).astype(np.int64))
    float(step(x, y))                      # compile (chunk steps)
    float(step(x, y))
    best_dt = float("inf")
    for _ in range(2):    # best-of-2: tunnel service windows swing ~10%
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        loss_val = float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)

    imgs_per_sec = batch * steps * chunk / best_dt
    # ResNet50 fwd ~4.1 GFLOPs/image at 224^2; train ~3x fwd
    flops_per_img = 3 * 4.1e9 * (hw / 224) ** 2
    mfu = imgs_per_sec * flops_per_img / (n * _peak_flops(dev.device_kind))
    if not on_tpu:
        mfu = 0.0
    _emit("resnet50_train_images_per_sec_per_chip", imgs_per_sec / n,
          "images/s/chip", mfu / 0.40 if on_tpu else 0.0,
          {"mfu": round(mfu, 4), "batch": batch, "amp": "O1" if on_tpu
           else "off", "device": dev.device_kind, "loss": loss_val})


def bench_bert():
    """Ladder #3: BERT-base fine-tune shape (encoder + AdamW)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models.bert import BertConfig, \
        BertForSequenceClassification

    dev, on_tpu, _ = _env()
    n = 1  # single-device bench
    if on_tpu:
        cfg = BertConfig()                         # base: 12L/768H
        batch, seq, steps = 32, 384, 3
    else:
        cfg = BertConfig(vocab_size=512, hidden_size=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=256)
        batch, seq, steps = 2, 64, 1

    model = BertForSequenceClassification(cfg)
    model.train()
    o = opt.AdamW(learning_rate=3e-5, parameters=model.parameters())

    def loss_fn(m, ids, y):
        with paddle.amp.auto_cast(enable=on_tpu, level="O1"):
            logits = m(ids)
        return F.cross_entropy(logits, y)

    chunk = 10 if on_tpu else 2
    step = paddle.jit.train_step(model, o, loss_fn).multi_step(chunk)
    ids = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    y = paddle.to_tensor(
        np.random.randint(0, cfg.num_labels, (batch,)).astype(np.int64))
    float(step(ids, y))
    float(step(ids, y))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, y)
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    ex_per_sec = batch * steps * chunk / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_ex = 6 * n_params * seq \
        + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq * seq
    mfu = ex_per_sec * flops_per_ex / (n * _peak_flops(dev.device_kind))
    if not on_tpu:
        mfu = 0.0
    _emit("bert_base_train_examples_per_sec_per_chip", ex_per_sec / n,
          "examples/s/chip", mfu / 0.40 if on_tpu else 0.0,
          {"mfu": round(mfu, 4), "seq": seq, "batch": batch,
           "params": int(n_params), "device": dev.device_kind,
           "loss": loss_val})


def bench_longctx():
    """Long-context rung: the SAME 0.95B llama trained at seq 8192 on one
    chip — runs on the grid-streamed flash kernels (VMEM-independent of
    sequence length), the single-chip face of the long-context story
    (ring/Ulysses attention covers the multi-chip face)."""
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_hybrid as H

    dev, on_tpu, n = _env()
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=8192,
            dtype="bfloat16")
        batch, seq, steps = 1, 8192, 8
        dtype = jnp.bfloat16
    else:
        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=8, max_position_embeddings=1024)
        batch, seq, steps = 1, 512, 2
        dtype = jnp.float32

    mesh = H.build_mesh(1, pp=1, dp=1, tp=1)
    tps, mfu, loss_val, _np_ = _llama_throughput(
        cfg, mesh, batch, seq, steps, dtype, on_tpu, dev)
    _emit("llama_longctx8k_tokens_per_sec_per_chip", tps,
          "tokens/s/chip", mfu / 0.40 if on_tpu else 0.0,
          {"mfu": round(mfu, 4), "seq": seq, "batch": batch,
           "device": dev.device_kind, "loss": loss_val,
           "note": "seq-8192 single-chip training on the streamed "
                   "flash kernels"})
    if on_tpu:
        bench_longctx_masked()


def bench_longctx_masked():
    """Masked long-seq attention (VERDICT r3 #2 gate): fwd+bwd of the
    STREAMED segment-masked kernel at seq 8192 vs the unmasked streamed
    kernel — packed-document pretraining must not lose the Pallas path.
    vs_baseline = masked/unmasked effective-MFU ratio (gate: >= 0.9)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    from op_bench import device_time
    from paddle_tpu.ops.pallas import flash_attention as FA
    from paddle_tpu.ops.pallas import flash_mask as FM

    dev, on_tpu, _ = _env()
    B, S, H, D = 1, 8192, 16, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, D) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, S, H, D) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, S, H, D) * 0.3, jnp.bfloat16)
    seg = np.zeros((B, S), np.int32)      # three packed documents
    seg[:, S // 3:2 * S // 3] = 1
    seg[:, 2 * S // 3:] = 2
    vecs = FM.segment_intervals(jnp.asarray(seg), causal=True)

    def grad_plain(q):
        return jax.grad(lambda q: jnp.sum(FA.sdpa(
            q, k, v, is_causal=True).astype(jnp.float32) ** 2))(q)

    def grad_masked(q):
        return jax.grad(lambda q: jnp.sum(FA.sdpa(
            q, k, v, flashmask=vecs, is_causal=True)
            .astype(jnp.float32) ** 2))(q)

    t_plain = device_time(grad_plain, q, reps=3)
    t_masked = device_time(grad_masked, q, reps=3)
    ratio = t_plain / max(t_masked, 1e-9)
    _emit("longctx8k_masked_attn_relative_mfu", ratio, "ratio",
          ratio / 0.9,
          {"unmasked_ms": round(t_plain * 1e3, 2),
           "masked_ms": round(t_masked * 1e3, 2),
           "seq": S, "device": dev.device_kind,
           "note": "streamed segment-masked flash fwd+bwd vs unmasked "
                   "streamed at seq 8192 (>= 0.9 required; masked may "
                   "exceed 1.0 — the mask skips work)"})


def bench_moe():
    """Ladder #5: MoE LM (gating + dense-dispatch einsums) on this chip."""
    from paddle_tpu.models import moe_llm as M

    dev, on_tpu, _ = _env()
    n = 1  # single-device bench (mesh is built with 1 device below)
    if on_tpu:
        # sort-based dispatch (no [tokens, E, capacity] one-hot) freed
        # the HBM that used to cap this rung at 4x512.  head_dim 128
        # (8 heads), matching DeepSeekMoE/Qwen2-MoE: D=64 halves the
        # MXU contraction in the flash kernel (measured r4: the D=64
        # attention cost 2.2x the D=128 one at identical flops)
        cfg = M.MoEConfig(vocab_size=32000, hidden_size=1024,
                          moe_intermediate_size=1408, num_hidden_layers=8,
                          num_attention_heads=8, num_key_value_heads=8,
                          num_experts=8, top_k=2, dtype="bfloat16")
        batch, seq, steps = 16, 512, 10
    else:
        cfg = M.moe_tiny()
        batch, seq, steps = 2, 64, 2

    mesh = M.build_mesh(1, dp=1, ep=1)
    params = M.setup(cfg, mesh)
    step = M.build_train_step(cfg, mesh)
    ids = jnp.asarray(
        np.random.randint(0, cfg.vocab_size, (batch, seq + 1)), jnp.int64)
    loss, params = step(params, ids)
    float(loss)
    for _ in range(2):
        loss, params = step(params, ids)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params = step(params, ids)
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    tok_per_sec = batch * seq * steps / dt
    # active params per token: top_k of num_experts expert FFNs
    leaves = jax.tree_util.tree_leaves(params)
    total = sum(x.size for x in leaves)
    expert = sum(x.size for x in leaves if x.ndim >= 3 and
                 x.shape[-3:-2] == (cfg.num_experts,))
    active = total - expert + expert * cfg.top_k // cfg.num_experts
    mfu = tok_per_sec * 6 * active / (n * _peak_flops(dev.device_kind))
    if not on_tpu:
        mfu = 0.0
    _emit("moe_train_tokens_per_sec_per_chip", tok_per_sec / n,
          "tokens/s/chip", mfu / 0.40 if on_tpu else 0.0,
          {"mfu_active": round(mfu, 4), "params_total": int(total),
           "params_active_per_tok": int(active),
           "experts": cfg.num_experts, "top_k": cfg.top_k,
           "device": dev.device_kind, "loss": loss_val})


def _decode_model():
    """Shared decode/paged rung model (built fresh per rung so one
    rung's failure cannot poison the other's state)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    dev, on_tpu, _ = _env()
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=4096,
            dtype="bfloat16")
        batch = 8
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=512)
        batch = 2

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg, batch, dev, on_tpu


def bench_decode():
    """Serving-path rung: KV-cache decode tokens/s (VERDICT r1 item 9;
    reference block_multi_head_attention_kernel.cu).  Emits the dense
    bf16 number plus the weight_quant="int8" number — the rung VERDICT
    r3 #1 gates on (quant decode must BEAT dense, not just match)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import generation as G

    model, cfg, batch, dev, on_tpu = _decode_model()
    prompt, new = (128, 128) if on_tpu else (8, 8)
    ids = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (batch, prompt)).astype(
            np.int64))

    def run(**kw):
        G._FN_CACHE.clear()
        out = G.generate(model, ids, max_new_tokens=new, **kw)
        float(np.asarray(out._data[0, -1]))       # compile + fetch
        best = 0.0
        for _ in range(2):   # best-of-2: tunnel service windows swing ~6%
            t0 = time.perf_counter()
            out = G.generate(model, ids, max_new_tokens=new, **kw)
            float(np.asarray(out._data[0, -1]))
            best = max(best, batch * new / (time.perf_counter() - t0))
        return best

    tps_dense = run()
    tps_int8 = run(weight_quant="int8")
    _emit("llama_decode_tokens_per_sec_per_chip", tps_dense,
          "tokens/s/chip", tps_int8 / max(tps_dense, 1e-9),
          {"int8_weight_quant_tokens_per_sec": round(tps_int8, 2),
           "batch": batch, "new_tokens": new, "device": dev.device_kind,
           "note": "vs_baseline = int8-weight-quant/dense decode ratio "
                   "(>1: the weight-only kernel wins)"})


def bench_paged():
    """Ragged serving: paged (block-table) cache vs dense cache — the
    scenario the reference's block_multi_head_attention exists for: one
    long context + short requests; dense pays batch*max_len everywhere,
    paged pays each sequence's own pages.  Split from bench_decode so a
    transport flake in one cannot take out the other (VERDICT r3 weak #1)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import generation as G

    if not _env()[1]:
        return  # the ragged-batch scenario only means anything on the chip
    model, cfg, batch, dev, on_tpu = _decode_model()
    prompt_r, new_r = 2048, 64
    lens = np.array([2048, 160, 96, 224, 128, 192, 96, 160],
                    np.int64)[:batch]
    ids_r = paddle.to_tensor(np.random.randint(
        0, cfg.vocab_size, (batch, prompt_r)).astype(np.int64))
    lens_t = paddle.to_tensor(lens)

    def run_ragged(**kw):
        G._FN_CACHE.clear()
        out = G.generate(model, ids_r, max_new_tokens=new_r,
                         lengths=lens_t, **kw)
        float(np.asarray(out._data[0, -1]))
        t0 = time.perf_counter()
        out = G.generate(model, ids_r, max_new_tokens=new_r,
                         lengths=lens_t, **kw)
        float(np.asarray(out._data[0, -1]))
        return batch * new_r / (time.perf_counter() - t0)

    tps_dense = run_ragged()
    tps_paged = run_ragged(cache="paged", page_size=128)
    _emit("llama_paged_ragged_tokens_per_sec_per_chip", tps_paged,
          "tokens/s/chip", tps_paged / max(tps_dense, 1e-9),
          {"dense_tokens_per_sec": round(tps_dense, 2),
           "batch": batch, "prompt": prompt_r, "new_tokens": new_r,
           "lengths": lens.tolist(), "device": dev.device_kind,
           "note": "vs_baseline = paged/dense on the ragged batch "
                   "(>1: block-table cache wins)"})


def bench_lenet():
    """Ladder #1: LeNet dygraph (eager tape) vs one-program jit steps/s —
    the per-op dispatch overhead number (reference hot-path goal,
    paddle/phi/README.md §1.2)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.vision.models import LeNet

    dev, on_tpu, _ = _env()
    batch = 64
    steps = 30 if on_tpu else 10
    x_np = np.random.randn(batch, 1, 28, 28).astype(np.float32)
    y_np = np.random.randint(0, 10, (batch,)).astype(np.int64)

    def make():
        paddle.seed(0)
        m = LeNet()
        m.train()
        return m, opt.SGD(learning_rate=0.01, parameters=m.parameters())

    # eager (dygraph) loop
    model, o = make()
    x, y = paddle.to_tensor(x_np), paddle.to_tensor(y_np)
    for _ in range(3):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
    float(loss)
    eager_sps = steps / (time.perf_counter() - t0)

    # compiled
    model, o = make()
    step = paddle.jit.train_step(
        model, o, lambda m, a, b: F.cross_entropy(m(a), b))
    float(step(x, y))
    for _ in range(3):
        loss = step(x, y)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    float(loss)
    jit_sps = steps / (time.perf_counter() - t0)

    _emit("lenet_eager_steps_per_sec", eager_sps, "steps/s",
          eager_sps / jit_sps,
          {"jit_steps_per_sec": round(jit_sps, 2), "batch": batch,
           "device": dev.device_kind,
           "note": "vs_baseline = eager/jit ratio (dispatch overhead)"})


def main():
    # the eager-dispatch rung goes FIRST: it measures per-op
    # Python+dispatch latency, which degrades (measured 29 -> 16
    # steps/s) once the other rungs' compiled executables and buffers
    # live in the process; a subprocess instead would contend with the
    # parent's device session on the tunneled transport
    for fn in (bench_lenet, bench_llama, bench_resnet50, bench_bert,
               bench_moe, bench_decode, bench_paged, bench_longctx):
        # one retry per rung: the tunneled transport flakes (~1/run in
        # round 3 it ate the whole decode+paged rung — VERDICT r3 weak
        # #1); a real failure reproduces, a transport hiccup does not
        for attempt in (0, 1):
            try:
                fn()
                break
            except Exception as e:
                if attempt == 0:
                    print(json.dumps(
                        {"retry": fn.__name__,
                         "error": f"{type(e).__name__}: {e}"[:300]}),
                        flush=True)
                    gc.collect()
                    time.sleep(5.0)
                    continue
                _emit(fn.__name__ + "_error", 0.0, "error", 0.0,
                      {"error": f"{type(e).__name__}: {e}"})
        gc.collect()

    # compact end-of-run recap: the driver records a BOUNDED TAIL of
    # this output (r4 lost the LeNet/Llama head lines from
    # BENCH_r04.json) — one short line per rung here guarantees every
    # rung survives the capture window
    print(json.dumps({"summary": [
        f"{m}={v}{u} (x{vs})" for m, v, u, vs in _SUMMARY]}), flush=True)


if __name__ == "__main__":
    main()
