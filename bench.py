"""Benchmark: flagship Llama pretraining step throughput + MFU on the
available chip(s).  Prints ONE JSON line.

vs_baseline: the reference publishes no absolute numbers (BASELINE.md);
the driver's north star is >=40% MFU, so vs_baseline = measured_MFU / 0.40.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak TFLOP/s per chip by device kind (public figures)
PEAK_TFLOPS = {
    "TPU v5p": 459.0, "TPU v5 lite": 197.0, "TPU v5e": 197.0,
    "TPU v6 lite": 918.0, "TPU v6e": 918.0, "TPU v4": 275.0,
    "TPU v3": 123.0, "TPU v2": 45.0,
}


def _peak_flops(kind: str) -> float:
    for k, v in PEAK_TFLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v * 1e12
    return 197e12  # unknown chip: assume v5e-class


def main():
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_hybrid as H

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    n = len(jax.devices()) if on_tpu else 1

    if on_tpu:
        # ~1B params saturates the MXU on one v5e chip (~16G HBM) with
        # bf16 params + fp32 AdamW state + flash attention + chunked CE
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype="bfloat16")
        batch, seq, steps = 8, 2048, 10
        dtype = jnp.bfloat16
    else:  # CPU smoke mode so the bench is runnable anywhere
        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=8,
            num_key_value_heads=8, max_position_embeddings=512)
        batch, seq, steps = 4, 256, 3
        dtype = jnp.float32

    pp, dp, tp = (1, n, 1) if n > 1 else (1, 1, 1)
    mesh = H.build_mesh(n, pp=pp, dp=dp, tp=tp)
    params, opt = H.setup(cfg, mesh, dtype=dtype)
    step = H.build_train_step(cfg, mesh, n_micro=1, remat=on_tpu, sp=False)

    ids = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (batch, seq + 1)).astype(
            np.int64),
        jax.sharding.NamedSharding(mesh,
                                   jax.sharding.PartitionSpec("dp", None)))

    loss, params, opt = step(params, opt, ids)  # compile
    float(loss)
    for _ in range(3):  # warmup: first post-compile steps run slow on
        loss, params, opt = step(params, opt, ids)  # the tunneled chip
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params, opt = step(params, opt, ids)
    # host fetch, not block_until_ready: the tunneled axon backend can
    # report readiness before the queued chain has actually executed
    loss_val = float(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    # 6*N_params FLOPs/token (fwd+bwd) + attention term
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    attn_flops = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops
    mfu = tokens_per_sec * flops_per_token / (n * _peak_flops(
        dev.device_kind if on_tpu else "cpu"))
    if not on_tpu:
        mfu = 0.0

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / n, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.40, 4) if on_tpu else 0.0,
        "detail": {"mfu": round(mfu, 4), "chips": n,
                   "device": dev.device_kind, "params": int(n_params),
                   "loss": loss_val},
    }))


if __name__ == "__main__":
    main()
