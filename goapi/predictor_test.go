package paddle

// End-to-end: save a tiny model with python, load+run it through the Go
// wrapper (reference goapi config_test.go pattern).  Requires
// libpaddle_tpu_infer.so (make -C ../csrc inference) — see README.md.

import (
	"os"
	"os/exec"
	"path/filepath"
	"testing"
)

func TestPredictorEndToEnd(t *testing.T) {
	dir := t.TempDir()
	model := filepath.Join(dir, "model")
	py := `
import sys
import paddle_tpu as paddle
from paddle_tpu import static
prefix = sys.argv[1]
paddle.enable_static()
main = static.Program()
with static.program_guard(main):
    x = static.data("x", [None, 4], "float32")
    out = static.nn.fc(x, 3)
exe = static.Executor()
static.save_inference_model(prefix, [x], [out], exe, program=main)
`
	cmd := exec.Command("python", "-c", py, model)
	cmd.Env = append(os.Environ(), "JAX_PLATFORMS=cpu",
		"PALLAS_AXON_POOL_IPS=")
	if out, err := cmd.CombinedOutput(); err != nil {
		t.Fatalf("model save failed: %v\n%s", err, out)
	}

	cfg := NewConfig()
	cfg.SetModel(model, "")
	if cfg.ModelDir() != model {
		t.Fatalf("ModelDir mismatch: %q", cfg.ModelDir())
	}
	pred, err := NewPredictor(cfg)
	if err != nil {
		t.Fatal(err)
	}
	defer pred.Destroy()

	if pred.GetInputNum() != 1 {
		t.Fatalf("want 1 input, got %d", pred.GetInputNum())
	}
	in := pred.GetInputHandle(pred.GetInputNames()[0])
	defer in.Destroy()
	in.Reshape([]int32{2, 4})
	if err := in.CopyFromCpu([]float32{1, 2, 3, 4, 5, 6, 7, 8}); err != nil {
		t.Fatal(err)
	}
	if err := pred.Run(); err != nil {
		t.Fatal(err)
	}
	out := pred.GetOutputHandle(pred.GetOutputNames()[0])
	defer out.Destroy()
	shape := out.Shape()
	if len(shape) != 2 || shape[0] != 2 || shape[1] != 3 {
		t.Fatalf("bad output shape %v", shape)
	}
	got := make([]float32, 6)
	if err := out.CopyToCpu(got); err != nil {
		t.Fatal(err)
	}
	for i, v := range got {
		if v != v { // NaN
			t.Fatalf("NaN at %d: %v", i, got)
		}
	}
}
