package paddle

// Reference: paddle/fluid/inference/goapi/tensor.go — PD_Tensor I/O.

// #include "pd_inference_c.h"
import "C"

import (
	"fmt"
	"unsafe"
)

// Tensor is a named input/output binding of a Predictor.
type Tensor struct {
	t *C.PD_Tensor
}

// Reshape sets the tensor's shape before CopyFromCpu.
func (t *Tensor) Reshape(shape []int32) {
	if len(shape) == 0 {
		return
	}
	C.PD_TensorReshape(t.t, C.size_t(len(shape)),
		(*C.int32_t)(unsafe.Pointer(&shape[0])))
}

// Shape returns the current tensor shape.  Two-phase query (rank
// first) so any rank is safe — the C side writes ndims entries
// unconditionally into the buffer we size here.
func (t *Tensor) Shape() []int32 {
	var n C.size_t
	if C.PD_TensorGetRank(t.t, &n) != 1 || n == 0 {
		return nil
	}
	dims := make([]int32, int(n))
	if C.PD_TensorGetShape(t.t, &n,
		(*C.int32_t)(unsafe.Pointer(&dims[0]))) != 1 {
		return nil
	}
	return dims[:int(n)]
}

func (t *Tensor) numel() int {
	n := 1
	for _, d := range t.Shape() {
		n *= int(d)
	}
	return n
}

// CopyFromCpu writes host data ([]float32, []int32 or []int64) into the
// tensor (reference tensor.go CopyFromCpu).
func (t *Tensor) CopyFromCpu(data interface{}) error {
	switch v := data.(type) {
	case []float32:
		if C.PD_TensorCopyFromCpuFloat(t.t,
			(*C.float)(unsafe.Pointer(&v[0]))) != 1 {
			return fmt.Errorf("paddle: CopyFromCpu(float32) failed")
		}
	case []int64:
		if C.PD_TensorCopyFromCpuInt64(t.t,
			(*C.int64_t)(unsafe.Pointer(&v[0]))) != 1 {
			return fmt.Errorf("paddle: CopyFromCpu(int64) failed")
		}
	case []int32:
		if C.PD_TensorCopyFromCpuInt32(t.t,
			(*C.int32_t)(unsafe.Pointer(&v[0]))) != 1 {
			return fmt.Errorf("paddle: CopyFromCpu(int32) failed")
		}
	default:
		return fmt.Errorf("paddle: unsupported CopyFromCpu type %T", data)
	}
	return nil
}

// CopyToCpu reads the tensor back into []float32 or []int64 sized by
// Shape().
func (t *Tensor) CopyToCpu(data interface{}) error {
	switch v := data.(type) {
	case []float32:
		if C.PD_TensorCopyToCpuFloat(t.t,
			(*C.float)(unsafe.Pointer(&v[0]))) != 1 {
			return fmt.Errorf("paddle: CopyToCpu(float32) failed")
		}
	case []int64:
		if C.PD_TensorCopyToCpuInt64(t.t,
			(*C.int64_t)(unsafe.Pointer(&v[0]))) != 1 {
			return fmt.Errorf("paddle: CopyToCpu(int64) failed")
		}
	default:
		return fmt.Errorf("paddle: unsupported CopyToCpu type %T", data)
	}
	return nil
}

// Destroy releases the tensor handle.
func (t *Tensor) Destroy() {
	if t.t != nil {
		C.PD_TensorDestroy(t.t)
		t.t = nil
	}
}
