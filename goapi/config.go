// Package paddle wraps the paddle_tpu C inference ABI for Go callers.
//
// Reference surface: paddle/fluid/inference/goapi/config.go — the cgo
// wrapper over the capi_exp PD_Config family.  Build requirements: a Go
// toolchain and libpaddle_tpu_infer.so (make -C ../csrc inference);
// point CGO_LDFLAGS at the build dir, e.g.
//
//	CGO_CFLAGS="-I../csrc" CGO_LDFLAGS="-L../csrc -lpaddle_tpu_infer" go test ./...
package paddle

// #cgo CFLAGS: -I${SRCDIR}/../csrc
// #cgo LDFLAGS: -L${SRCDIR}/../csrc -lpaddle_tpu_infer -Wl,-rpath,${SRCDIR}/../csrc
// #include <stdlib.h>
// #include "pd_inference_c.h"
import "C"

import (
	"fmt"
	"runtime"
	"unsafe"
)

// Config configures a Predictor (reference goapi Config).  A Config is
// consumed by NewPredictor — do not reuse it afterwards.
type Config struct {
	c *C.PD_Config

	// recorded generic knobs (no TPU-side action needed)
	progFile      string
	paramsFile    string
	optimCacheDir string
	mathThreads   int
	irOptim       bool
	memoryOptim   bool
	profile       bool
	glogOff       bool
}

// NewConfig creates an empty config.
func NewConfig() *Config {
	cfg := &Config{c: C.PD_ConfigCreate()}
	runtime.SetFinalizer(cfg, func(f *Config) {
		if f.c != nil {
			C.PD_ConfigDestroy(f.c)
			f.c = nil
		}
	})
	return cfg
}

// SetModel points the config at a jit.save'd model directory (the
// paramsPath may be empty — paddle_tpu bundles params with the model).
func (cfg *Config) SetModel(modelPath, paramsPath string) {
	cfg.progFile = modelPath
	cfg.paramsFile = paramsPath
	mp := C.CString(modelPath)
	pp := C.CString(paramsPath)
	defer C.free(unsafe.Pointer(mp))
	defer C.free(unsafe.Pointer(pp))
	C.PD_ConfigSetModel(cfg.c, mp, pp)
}

// ModelDir returns the configured model path.
func (cfg *Config) ModelDir() string {
	return C.GoString(C.PD_ConfigGetModelDir(cfg.c))
}

// ---- generic knobs (reference config.go surface; GPU/TRT/MKLDNN
// settings have no TPU analog and live off this wrapper — see README).
// These are recorded on the Go side: XLA already runs the optimization
// and memory planning the reference gates behind them.

// SetModelDir points at an uncombined model directory (params path
// kept: the setters compose — each updates only its own slot).
func (cfg *Config) SetModelDir(dir string) {
	cfg.SetModel(dir, cfg.paramsFile)
}

// SetProgFile sets the program (model) file path.
func (cfg *Config) SetProgFile(model string) {
	cfg.progFile = model
	cfg.SetModel(model, cfg.paramsFile)
}

// SetParamsFile sets the combined-params file path.
func (cfg *Config) SetParamsFile(params string) {
	cfg.paramsFile = params
	cfg.SetModel(cfg.progFile, params)
}

// ProgFile returns the configured program file.
func (cfg *Config) ProgFile() string { return cfg.progFile }

// ParamsFile returns the configured params file.
func (cfg *Config) ParamsFile() string { return cfg.paramsFile }

// SetOptimCacheDir records the optimization-cache directory (XLA's
// compilation cache is process-level here).
func (cfg *Config) SetOptimCacheDir(dir string) { cfg.optimCacheDir = dir }

// SetCpuMathLibraryNumThreads records the host math thread count.
func (cfg *Config) SetCpuMathLibraryNumThreads(n int) { cfg.mathThreads = n }

// CpuMathLibraryNumThreads returns the recorded thread count.
func (cfg *Config) CpuMathLibraryNumThreads() int32 {
	return int32(cfg.mathThreads)
}

// SwitchIrOptim toggles graph optimization (XLA always optimizes; the
// flag is recorded for API parity).
func (cfg *Config) SwitchIrOptim(x bool) { cfg.irOptim = x }

// IrOptim reports the recorded flag.
func (cfg *Config) IrOptim() bool { return cfg.irOptim }

// EnableMemoryOptim toggles memory reuse (XLA buffer donation governs
// this here).
func (cfg *Config) EnableMemoryOptim(x bool) { cfg.memoryOptim = x }

// MemoryOptimEnabled reports the recorded flag.
func (cfg *Config) MemoryOptimEnabled() bool { return cfg.memoryOptim }

// EnableProfile turns on runtime profiling (recorded).
func (cfg *Config) EnableProfile() { cfg.profile = true }

// ProfileEnabled reports the recorded flag.
func (cfg *Config) ProfileEnabled() bool { return cfg.profile }

// DisableGlogInfo silences info logging (recorded).
func (cfg *Config) DisableGlogInfo() { cfg.glogOff = true }

// GlogInfoDisabled reports the recorded flag.
func (cfg *Config) GlogInfoDisabled() bool { return cfg.glogOff }

// Summary renders the config state (reference Summary()).
func (cfg *Config) Summary() string {
	return fmt.Sprintf(
		"model: %s; params: %s; ir_optim: %v; memory_optim: %v; "+
			"math_threads: %d", cfg.ModelDir(), cfg.paramsFile,
		cfg.irOptim, cfg.memoryOptim, cfg.mathThreads)
}
