// Package paddle wraps the paddle_tpu C inference ABI for Go callers.
//
// Reference surface: paddle/fluid/inference/goapi/config.go — the cgo
// wrapper over the capi_exp PD_Config family.  Build requirements: a Go
// toolchain and libpaddle_tpu_infer.so (make -C ../csrc inference);
// point CGO_LDFLAGS at the build dir, e.g.
//
//	CGO_CFLAGS="-I../csrc" CGO_LDFLAGS="-L../csrc -lpaddle_tpu_infer" go test ./...
package paddle

// #cgo CFLAGS: -I${SRCDIR}/../csrc
// #cgo LDFLAGS: -L${SRCDIR}/../csrc -lpaddle_tpu_infer -Wl,-rpath,${SRCDIR}/../csrc
// #include <stdlib.h>
// #include "pd_inference_c.h"
import "C"

import (
	"runtime"
	"unsafe"
)

// Config configures a Predictor (reference goapi Config).  A Config is
// consumed by NewPredictor — do not reuse it afterwards.
type Config struct {
	c *C.PD_Config
}

// NewConfig creates an empty config.
func NewConfig() *Config {
	cfg := &Config{c: C.PD_ConfigCreate()}
	runtime.SetFinalizer(cfg, func(f *Config) {
		if f.c != nil {
			C.PD_ConfigDestroy(f.c)
			f.c = nil
		}
	})
	return cfg
}

// SetModel points the config at a jit.save'd model directory (the
// paramsPath may be empty — paddle_tpu bundles params with the model).
func (cfg *Config) SetModel(modelPath, paramsPath string) {
	mp := C.CString(modelPath)
	pp := C.CString(paramsPath)
	defer C.free(unsafe.Pointer(mp))
	defer C.free(unsafe.Pointer(pp))
	C.PD_ConfigSetModel(cfg.c, mp, pp)
}

// ModelDir returns the configured model path.
func (cfg *Config) ModelDir() string {
	return C.GoString(C.PD_ConfigGetModelDir(cfg.c))
}
