package paddle

// Reference: paddle/fluid/inference/goapi/predictor.go — the cgo
// wrapper over PD_Predictor.

// #include "pd_inference_c.h"
// #include <stdlib.h>
import "C"

import (
	"fmt"
	"unsafe"
)

// Predictor runs a saved paddle_tpu inference model; each Run is one
// cached XLA executable underneath.
type Predictor struct {
	p *C.PD_Predictor
}

// NewPredictor builds a predictor.  CONSUMES the config (reference
// semantics) — the config must not be touched afterwards.
func NewPredictor(cfg *Config) (*Predictor, error) {
	p := C.PD_PredictorCreate(cfg.c)
	cfg.c = nil // consumed
	if p == nil {
		return nil, fmt.Errorf("paddle: PD_PredictorCreate failed")
	}
	return &Predictor{p: p}, nil
}

// GetInputNum returns the number of model inputs.
func (pred *Predictor) GetInputNum() int {
	return int(C.PD_PredictorGetInputNum(pred.p))
}

// GetOutputNum returns the number of model outputs.
func (pred *Predictor) GetOutputNum() int {
	return int(C.PD_PredictorGetOutputNum(pred.p))
}

// GetInputNames lists input names in declaration order.
func (pred *Predictor) GetInputNames() []string {
	n := pred.GetInputNum()
	out := make([]string, n)
	for i := 0; i < n; i++ {
		out[i] = C.GoString(C.PD_PredictorGetInputName(pred.p, C.size_t(i)))
	}
	return out
}

// GetOutputNames lists output names.
func (pred *Predictor) GetOutputNames() []string {
	n := pred.GetOutputNum()
	out := make([]string, n)
	for i := 0; i < n; i++ {
		out[i] = C.GoString(C.PD_PredictorGetOutputName(pred.p, C.size_t(i)))
	}
	return out
}

// GetInputHandle returns the named input tensor handle.
func (pred *Predictor) GetInputHandle(name string) *Tensor {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	return &Tensor{t: C.PD_PredictorGetInputHandle(pred.p, cn)}
}

// GetOutputHandle returns the named output tensor handle.
func (pred *Predictor) GetOutputHandle(name string) *Tensor {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	return &Tensor{t: C.PD_PredictorGetOutputHandle(pred.p, cn)}
}

// Run executes the model on the bound inputs.
func (pred *Predictor) Run() error {
	if C.PD_PredictorRun(pred.p) != 1 {
		return fmt.Errorf("paddle: PD_PredictorRun failed")
	}
	return nil
}

// Clone creates a predictor sharing the loaded weights and compiled
// executables with this one; only the I/O buffers are private
// (reference goapi predictor.go Clone).
func (pred *Predictor) Clone() (*Predictor, error) {
	p := C.PD_PredictorClone(pred.p)
	if p == nil {
		return nil, fmt.Errorf("paddle: PD_PredictorClone failed")
	}
	return &Predictor{p: p}, nil
}

// Destroy releases the predictor (tensor handles stay valid).
func (pred *Predictor) Destroy() {
	if pred.p != nil {
		C.PD_PredictorDestroy(pred.p)
		pred.p = nil
	}
}
