#!/usr/bin/env python
"""tpu-lint: run the paddle_tpu static-analysis suite.

Usage:
    python tools/lint.py [paths...]          # default: paddle_tpu tools
    python tools/lint.py --json              # machine-readable output
    python tools/lint.py --update-baseline   # accept current findings
    python tools/lint.py --list-rules        # rule ids + descriptions
    python tools/lint.py --rules jit-host-sync,lock-order-cycle ...
    python tools/lint.py --changed           # only files != HEAD
    python tools/lint.py --changed main      # only files != main

Exit status is 0 when every finding is covered by the committed
baseline (tools/lint_baseline.json), 1 when there are NEW findings, and
2 on usage errors.  Suppress a single site inline with
``# tpu-lint: disable=RULE`` (same line, or a standalone comment line
directly above).
"""
from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from paddle_tpu.analysis import (ALL_RULES, load_baseline,  # noqa: E402
                                 load_baseline_entries, partition,
                                 render_json, render_text, run,
                                 save_baseline)

DEFAULT_PATHS = ["paddle_tpu", "tools"]
DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools",
                                "lint_baseline.json")


def _changed_files(ref: str, scope_paths) -> list[str]:
    """Repo-relative .py files differing from ``ref`` (plus untracked),
    restricted to the lint scope.  The full baseline still applies —
    unused entries are harmless."""
    import subprocess
    changed: set[str] = set()
    cmds = [["git", "-C", _REPO_ROOT, "diff", "--name-only", ref, "--"],
            ["git", "-C", _REPO_ROOT, "ls-files", "--others",
             "--exclude-standard"]]
    for cmd in cmds:
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 check=True).stdout
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise RuntimeError(
                f"--changed needs git ({detail.strip()})") from e
        changed.update(l.strip() for l in out.splitlines() if l.strip())
    scope = [p.rstrip("/").replace(os.sep, "/") for p in scope_paths]
    everything = any(s in (".", "") for s in scope)
    out_paths = []
    for rel in sorted(changed):
        if not rel.endswith(".py"):
            continue
        if not everything and not any(
                rel == s or rel.startswith(s + "/") for s in scope):
            continue
        if os.path.exists(os.path.join(_REPO_ROOT, rel)):
            out_paths.append(rel)   # deleted files have nothing to lint
    return out_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule-id subset to run")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/"
                         "lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current findings as the new "
                         "baseline and exit 0; with --rules, entries "
                         "for unlisted rules are kept (merge, not "
                         "clobber)")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the per-file result cache "
                         "(.lint_cache/)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only .py files that differ from the "
                         "given git ref (default HEAD), plus untracked "
                         "ones, restricted to the selected paths — "
                         "with the warm cache this is the sub-second "
                         "pre-commit loop")
    ap.add_argument("--list-rules", action="store_true",
                    help="list rule ids and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(r) for r in ALL_RULES)
        for rule in sorted(ALL_RULES):
            print(f"{rule:<{width}}  {ALL_RULES[rule]}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    paths = args.paths or DEFAULT_PATHS

    if args.changed is not None:
        try:
            paths = _changed_files(args.changed, paths)
        except RuntimeError as e:
            print(f"lint.py: {e}", file=sys.stderr)
            return 2
        if not paths:
            print(f"no .py files changed vs {args.changed}")
            return 0

    try:
        findings = run(paths, root=_REPO_ROOT, rules=rules,
                       cache=not args.no_cache)
    except ValueError as e:
        print(f"lint.py: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        old = load_baseline_entries(args.baseline)
        # with a rule filter active, this run only saw `rules` —
        # entries for every other rule must survive (merge semantics,
        # mirroring perf_gate.py's --update-baseline)
        kept = [e for e in old if rules is not None
                and e.get("rule") not in set(rules)]
        why = {e["fingerprint"]: e["why"] for e in old if e.get("why")}
        entries = kept + [f.to_dict() for f in findings]
        for e in entries:
            if e["fingerprint"] in why:
                e["why"] = why[e["fingerprint"]]
        save_baseline(args.baseline, entries)
        print(f"wrote {len(entries)} finding"
              f"{'' if len(entries) == 1 else 's'} to "
              f"{os.path.relpath(args.baseline, _REPO_ROOT)}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, baselined = partition(findings, baseline)

    if args.json:
        sys.stdout.write(render_json(new, baselined=len(baselined)))
    else:
        print(render_text(new, baselined=len(baselined)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
