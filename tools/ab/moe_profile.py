"""Where does the MoE rung's step time go?  Times the full step and
ablated variants on the chip (tunnel-honest: device-resident params
mutating per step, best-of-2 medians)."""
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import moe_llm as M
from paddle_tpu.distributed.moe import moe_dispatch_combine
from paddle_tpu.models.llama import _rope_tables, apply_rotary_pos_emb
from paddle_tpu.models.llama_hybrid import _rms, _chunked_ce_sum
from paddle_tpu.ops.pallas.flash_attention import sdpa

cfg = M.MoEConfig(vocab_size=32000, hidden_size=1024,
                  moe_intermediate_size=1408, num_hidden_layers=8,
                  num_attention_heads=8, num_key_value_heads=8,
                  num_experts=8, top_k=2, dtype="bfloat16")
batch, seq, steps = 16, 512, 10
mesh = M.build_mesh(1, dp=1, ep=1)
params = M.setup(cfg, mesh)
ids = jnp.asarray(np.random.randint(0, cfg.vocab_size,
                                    (batch, seq + 1)), jnp.int64)


def timed(fn, p0):
    p = jax.tree_util.tree_map(lambda a: a + 0, p0)   # private copy
    loss, p = fn(p, ids)
    float(loss)
    for _ in range(2):
        loss, p = fn(p, ids)
    float(loss)
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, p = fn(p, ids)
        float(loss)
        dt = (time.perf_counter() - t0) / steps
        best = dt if best is None else min(best, dt)
    return best


def make_step(loss_f):
    def step(p, ids):
        loss, grads = jax.value_and_grad(loss_f)(p, ids)
        p = jax.tree_util.tree_map(
            lambda a, g: (a.astype(jnp.float32)
                          - 3e-4 * g.astype(jnp.float32)).astype(a.dtype),
            p, grads)
        return loss, p
    return jax.jit(step, donate_argnums=(0,))


def loss_variant(mode):
    def loss_fn(p, ids):
        inp, lab = ids[:, :-1], ids[:, 1:]
        b, s = inp.shape
        x = jnp.take(p["embed"], inp, axis=0)
        cos, sin = _rope_tables(s, cfg.head_dim, cfg.rope_theta)
        nh = kvh = cfg.num_attention_heads
        hd = cfg.head_dim

        def body(carry, lp):
            h, aux = carry
            bsz, sq, hdim = h.shape
            r = h
            hh = _rms(h, lp["input_ln"], cfg.rms_norm_eps)
            if mode != "ffn_only":
                wqkv = jnp.concatenate([lp["q"], lp["k"], lp["v"]],
                                       axis=1)
                qkv = hh @ wqkv
                q = qkv[..., :nh * hd].reshape(bsz, sq, nh, hd)
                k = qkv[..., nh * hd:(nh + kvh) * hd] \
                    .reshape(bsz, sq, kvh, hd)
                v = qkv[..., (nh + kvh) * hd:].reshape(bsz, sq, kvh, hd)
                q, k = apply_rotary_pos_emb(q, k, cos, sin)
                a = sdpa(q, k, v, is_causal=True)
                h = r + (a.reshape(bsz, sq, nh * hd) @ lp["o"])
            r = h
            hh = _rms(h, lp["post_ln"], cfg.rms_norm_eps)
            flat = hh.reshape(bsz * sq, hdim)
            if mode == "attn_only":
                y = flat
                a2 = jnp.float32(0.0)
            elif mode == "dense_ffn":
                # same ACTIVE flops as top-2 of 8: two experts' worth
                w1 = lp["w1"][0]
                w2 = lp["w2"][0]
                y = jax.nn.silu(flat @ w1) @ w2
                w1b = lp["w1"][1]
                w2b = lp["w2"][1]
                y = y + jax.nn.silu(flat @ w1b) @ w2b
                a2 = jnp.float32(0.0)
            elif mode == "dense_dispatch":
                y, a2 = moe_dispatch_combine(
                    flat, lp["gate"], lp["w1"], lp["b1"], lp["w2"],
                    lp["b2"], top_k=cfg.top_k,
                    capacity_factor=cfg.capacity_factor,
                    activation=jax.nn.silu, mesh=mesh, ep_axis="ep",
                    dispatch_mode="dense")
            else:
                y, a2 = moe_dispatch_combine(
                    flat, lp["gate"], lp["w1"], lp["b1"], lp["w2"],
                    lp["b2"], top_k=cfg.top_k,
                    capacity_factor=cfg.capacity_factor,
                    activation=jax.nn.silu, mesh=mesh, ep_axis="ep")
            return (r + y.reshape(bsz, sq, hdim), aux + a2), None

        (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0.0)),
                                   p["layers"])
        h = _rms(x, p["norm"], cfg.rms_norm_eps)
        ce = _chunked_ce_sum(h, lab, p["head"]) / (b * s)
        return ce + cfg.aux_loss_weight * aux / cfg.num_hidden_layers
    return loss_fn


full = timed(make_step(loss_variant("full")), params)
print(f"full sort-dispatch step: {full*1e3:.1f} ms  "
      f"tok/s={batch*seq/full:,.0f}")
for mode in ("dense_ffn", "attn_only", "ffn_only"):
    dt = timed(make_step(loss_variant(mode)), params)
    print(f"{mode:>16}: {dt*1e3:.1f} ms  tok/s={batch*seq/dt:,.0f}")
