"""Same-session ResNet rung A/B: dispatch-chunk length 25/50/100 vs the
platform ceiling's with-BN raw-jax number (run in the same session)."""
import time

import jax
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.vision.models import resnet50

batch, hw = 128, 224


def rung(chunk, steps=2):
    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.train()
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())

    def loss_fn(m, x, y):
        with paddle.amp.auto_cast(enable=True, level="O1"):
            out = m(x)
        return F.cross_entropy(out, y)

    step = paddle.jit.train_step(model, o, loss_fn).multi_step(chunk)
    x = paddle.to_tensor(
        np.random.randn(batch, 3, hw, hw).astype(np.float32))
    y = paddle.to_tensor(
        np.random.randint(0, 1000, (batch,)).astype(np.int64))
    float(step(x, y))
    float(step(x, y))
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        float(loss)
        best = min(best, time.perf_counter() - t0)
    ips = batch * steps * chunk / best
    print(f"chunk={chunk}: {ips:,.0f} img/s", flush=True)
    return ips


if __name__ == "__main__":
    for chunk in (25, 50, 100):
        rung(chunk)
    # same-session ceiling
    import subprocess
    import sys
    print("running same-session ceiling (with BN)...", flush=True)
    import tools.platform_ceiling as PC
    PC.rawjax_resnet(with_bn=True)
