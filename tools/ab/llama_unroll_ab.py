"""Same-session A/B: llama flagship step with scanned vs unrolled
layer loop (remat kept identical)."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_hybrid as H

cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                  intermediate_size=5632, num_hidden_layers=16,
                  num_attention_heads=16, num_key_value_heads=16,
                  max_position_embeddings=2048, dtype="bfloat16")
batch, seq, steps = 8, 2048, 8
mesh = H.build_mesh(1, pp=1, dp=1, tp=1)
ids = jnp.asarray(np.random.randint(0, 32000, (batch, seq + 1)),
                  jnp.int64)


def run(tag):
    params, opt = H.setup(cfg, mesh, dtype=jnp.bfloat16)
    step = H.build_train_step(cfg, mesh, n_micro=1, remat=True, sp=False)
    loss, params, opt = step(params, opt, ids)
    float(loss)
    for _ in range(2):
        loss, params, opt = step(params, opt, ids)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params, opt = step(params, opt, ids)
    float(loss)
    dt = (time.perf_counter() - t0) / steps
    print(f"{tag}: {dt*1e3:.1f} ms  tok/s={batch*seq/dt:,.0f}",
          flush=True)


def unrolled_stage(stage_params, x, cos, sin, config, remat=True):
    body = functools.partial(H._decoder_layer, cos=cos, sin=sin,
                             config=config)
    if remat == "attn":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out"))
    elif remat:
        body = jax.checkpoint(body)
    lps = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    h = x
    for i in range(lps):
        lp = jax.tree_util.tree_map(lambda a: a[i], stage_params)
        h = body(lp, h)
    return h


orig = H._stage_fn
H._stage_fn = unrolled_stage
run("unroll")
H._stage_fn = orig
run("scan  ")
H._stage_fn = unrolled_stage
run("unroll2")
