#!/usr/bin/env python
"""Render per-request lifecycle waterfalls + tail-latency attribution.

Usage:
    python tools/request_report.py <dump-dir | exemplars.json | waterfall.json>
        [--request ID]

Input is any of:

  * an observability dump directory (``obs.dump()`` output) — reads
    its ``exemplars.json`` (written when ``FLAGS_serving_request_log``
    armed a RequestLog);
  * an ``exemplars.json`` file directly (a ``RequestLog.snapshot()``:
    attribution totals, conservation check, worst-K exemplars per SLO
    dimension);
  * a single waterfall JSON saved from ``GET /debug/requests/<id>``
    (replica response, or the router's fan-out response — the
    ``found`` entry is unwrapped automatically).

Default output is the attribution table by cause (the same rounded-6
seconds ``serve_bench --explain-tail`` prints), the conservation line,
and one line per kept exemplar (dimension, score, tenant/adapter,
trace id).  ``--request ID`` renders the full ASCII waterfall of that
request's timeline — from the exemplar store when given a snapshot, or
of the single-waterfall input itself.

Works standalone — no paddle_tpu / jax import, so it runs against
artifacts copied off a serving host.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_BAR_WIDTH = 40


def _load(path):
    """Resolve the input to a JSON document; dump dirs resolve to
    their exemplars.json."""
    if os.path.isdir(path):
        path = os.path.join(path, "exemplars.json")
        if not os.path.exists(path):
            sys.exit(f"request_report: no exemplars.json in the dump "
                     f"dir (run with FLAGS_serving_request_log=true, "
                     f"or pass a waterfall JSON): {path!r}")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        sys.exit(f"request_report: cannot read {path!r}: {e}")


def _fmt(v):
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e12:
            return str(int(v))
        return f"{v:.6g}"
    return str(v)


def _table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]

    def line(r):
        return "  ".join(str(c).ljust(w)
                         for c, w in zip(r, widths)).rstrip()

    return "\n".join([line(headers),
                      line(["-" * w for w in widths])]
                     + [line(r) for r in rows])


def attribution_lines(attribution, e2e_s=None, delta=None,
                      finished=None):
    """The per-cause table + conservation line — identical numbers to
    ``serve_bench --explain-tail`` (both render the rounded-6 seconds
    the RequestLog snapshots)."""
    causes = {c: float(v or 0) for c, v in (attribution or {}).items()}
    spent = sum(causes.values())
    lines = []
    if spent > 0:
        rows = [(c, f"{v:.6g}", f"{100.0 * v / spent:.1f}%")
                for c, v in sorted(causes.items(), key=lambda kv:
                                   (-kv[1], kv[0])) if v > 0]
        lines.append(_table(rows, ("cause", "seconds", "share")))
    else:
        lines.append("  no attributed seconds")
    if e2e_s is not None:
        # prefer the recorded delta (computed on unrounded seconds);
        # re-deriving from the rounded-6 buckets can drift by 1e-6
        d = (round(spent - float(e2e_s), 6) if delta is None
             else float(delta))
        lines.append(f"  conservation: sum(buckets)={spent:.6g}s vs "
                     f"e2e={float(e2e_s):.6g}s (delta {_fmt(d)}, "
                     f"must be 0)")
    elif delta is not None:
        line = (f"  conservation: max |sum(buckets) - e2e| = "
                f"{_fmt(delta)} (must be 0)")
        if finished is not None:
            line += f" over {_fmt(finished)} finished requests"
        lines.append(line)
    return lines


def waterfall_lines(doc):
    """ASCII waterfall of one request's timeline (the
    ``GET /debug/requests/<id>`` payload): one bar per charged event,
    offset from arrival, plus the attribution table."""
    events = doc.get("events") or []
    lines = [f"request {doc.get('request')} "
             f"trace={doc.get('trace_id') or '-'} "
             f"tenant={doc.get('tenant') or '-'} "
             f"adapter={doc.get('adapter') or '-'} "
             f"priority={doc.get('priority', 0)}"]
    status = ("finished" if doc.get("finished") else "in flight")
    lines.append(f"  {status}"
                 + (f" reason={doc.get('finish_reason')}"
                    if doc.get("finish_reason") else "")
                 + (f" e2e={float(doc['e2e_s']):.6g}s"
                    if doc.get("e2e_s") is not None else ""))
    span = max([float(e.get("t") or 0) for e in events] + [0.0])
    scale = _BAR_WIDTH / span if span > 0 else 0.0
    rows = []
    for ev in events:
        t = float(ev.get("t") or 0)
        dur = float(ev.get("dur") or 0)
        start = max(t - dur, 0.0)
        pad = int(start * scale)
        fill = max(1, int(dur * scale)) if dur > 0 else 0
        bar = " " * pad + ("#" * fill if fill else "|")
        attrs = ", ".join(f"{k}={v}" for k, v in sorted(ev.items())
                          if k not in ("event", "t", "dur", "bucket"))
        rows.append((ev.get("event", "?"),
                     ev.get("bucket") or "-",
                     f"{start:.6g}", f"{dur:.6g}",
                     bar[:_BAR_WIDTH + 1], attrs))
    if rows:
        lines.append(_table(rows, ("event", "bucket", "start_s",
                                   "dur_s", "waterfall", "attrs")))
    if doc.get("events_dropped"):
        lines.append(f"  ({_fmt(doc['events_dropped'])} events dropped "
                     f"by the bound — bucket seconds are complete)")
    lines += attribution_lines(doc.get("attribution"),
                               e2e_s=doc.get("e2e_s"),
                               delta=doc.get("conservation_delta"))
    return lines


def exemplar_lines(snapshot, request_id=None):
    """Render a RequestLog snapshot (``exemplars.json`` /
    ``GET /debug/exemplars``): attribution totals, conservation, and
    the kept exemplars; ``request_id`` expands one exemplar's
    snapshotted timeline into a full waterfall."""
    lines = ["Tail-latency attribution (all finished requests)"]
    lines += attribution_lines(
        snapshot.get("attribution_totals_s"),
        delta=snapshot.get("conservation_max_delta"),
        finished=snapshot.get("finished"))
    store = snapshot.get("exemplars") or snapshot
    by_dim = store.get("by_dimension") or {}
    records = [r for lst in by_dim.values() for r in (lst or [])
               if isinstance(r, dict)]
    if request_id is not None:
        hits = [r for r in records
                if r.get("request") == request_id
                and isinstance(r.get("timeline"), dict)]
        if not hits:
            sys.exit(f"request_report: request {request_id} is not in "
                     f"the exemplar store (only SLO-violating / "
                     f"errored requests are kept — fetch the live "
                     f"waterfall from /debug/requests/{request_id})")
        return lines + [""] + waterfall_lines(hits[0]["timeline"])
    rows = []
    for dim in sorted(by_dim):
        for rank, r in enumerate(x for x in (by_dim[dim] or [])
                                 if isinstance(x, dict)):
            rows.append((dim, rank,
                         f"{float(r.get('score_s') or 0):.6g}",
                         r.get("request"),
                         r.get("tenant") or "-",
                         r.get("adapter") or "-",
                         r.get("trace_id") or "-"))
    if rows:
        lines += ["", "Exemplars (worst-K per dimension; --request ID "
                      "renders the waterfall)",
                  _table(rows, ("dimension", "rank", "score_s",
                                "request", "tenant", "adapter",
                                "trace"))]
        lines.append(f"  {_fmt(store.get('kept', len(rows)))} kept of "
                     f"{_fmt(store.get('offered', 0))} violations "
                     f"offered (worst-{_fmt(store.get('k', 0))})")
    else:
        lines += ["", "no exemplars captured (no SLO violations or "
                      "errors this run)"]
    return lines


def report(doc, request_id=None):
    if isinstance(doc, dict) and isinstance(doc.get("found"), dict):
        doc = doc["found"]      # router fan-out response: unwrap
    if isinstance(doc, dict) and "events" in doc:
        return "\n".join(waterfall_lines(doc))
    if isinstance(doc, dict) and ("attribution_totals_s" in doc
                                  or "by_dimension" in doc
                                  or "exemplars" in doc):
        return "\n".join(exemplar_lines(doc, request_id))
    sys.exit("request_report: unrecognized input — expected a "
             "/debug/requests/<id> waterfall, an exemplars.json, or "
             "a dump directory")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path",
                    help="dump dir, exemplars.json, or waterfall JSON")
    ap.add_argument("--request", type=int, default=None, metavar="ID",
                    help="render this exemplar request's full "
                         "waterfall instead of the summary")
    args = ap.parse_args(argv)
    print(report(_load(args.path), args.request))
    return 0


if __name__ == "__main__":
    sys.exit(main())
