#!/usr/bin/env python
"""Render a sampling-profiler capture as readable tables.

Usage:
    python tools/profile_report.py <bench.folded | profile.json | dump-dir>
        [--top N] [--phase PHASE]

Accepts any of the three shapes the profiler produces:

* a ``.folded`` file (``serve_bench --profile out.folded`` or the
  text body of ``GET /debug/profile``) — semicolon-joined stacks, one
  per line, trailing sample count;
* a ``profile.json`` side-file from ``observability.dump()`` (the
  ``SamplingProfiler.snapshot()`` dict);
* a dump directory containing ``profile.json``.

Renders per-phase sample totals, the top-N leaf frames by self time
(where the engine actually spends its wall clock), and the heaviest
whole stacks.  ``--phase decode`` narrows every table to one phase.

Works standalone — no paddle_tpu / jax import, so it can run against a
capture copied off a serving host.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def parse_folded(text):
    """``phase;thread;f1;f2 count`` lines -> list of (stack, count).

    ``stack`` keeps the folded segments as a tuple, root-first, with
    stack[0] the phase and stack[1] the thread name.  Malformed lines
    (truncated writes, stray blank lines) are skipped, never fatal.
    """
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            n = int(count)
        except ValueError:
            continue
        out.append((tuple(stack.split(";")), n))
    return out


def folded_to_snapshot(stacks, top=50):
    """Lift folded (stack, count) pairs into the snapshot() dict shape
    so one rendering path serves both input formats."""
    by_phase = {}
    total = 0
    for stack, n in stacks:
        phase = stack[0] if stack else "other"
        by_phase[phase] = by_phase.get(phase, 0) + n
        total += n
    top_stacks = [{"phase": s[0] if s else "other",
                   "thread": s[1] if len(s) > 1 else "?",
                   "stack": list(s[2:]), "count": n}
                  for s, n in sorted(stacks, key=lambda kv: -kv[1])[:top]]
    return {"stats": {"observations": total,
                      "distinct_stacks": len(stacks)},
            "by_phase": by_phase, "top_stacks": top_stacks}


def leaf_self_time(snapshot, phase=None):
    """Aggregate sample counts by LEAF frame (self time): the frame on
    top of the stack owns the sample."""
    leaves = {}
    for ent in snapshot.get("top_stacks") or []:
        if phase and ent.get("phase") != phase:
            continue
        stack = ent.get("stack") or []
        leaf = stack[-1] if stack else "(no frames)"
        leaves[leaf] = leaves.get(leaf, 0) + int(ent.get("count") or 0)
    return sorted(leaves.items(), key=lambda kv: -kv[1])


def _bar(n, total, width=24):
    if total <= 0:
        return ""
    return "#" * max(1, int(round(width * n / total))) if n else ""


def render(snapshot, top=20, phase=None, out=sys.stdout):
    stats = snapshot.get("stats") or {}
    total = int(stats.get("observations") or 0)
    print("== profile ==", file=out)
    for k in ("interval_s", "samples", "observations", "distinct_stacks",
              "dropped"):
        if k in stats:
            print(f"  {k:<16} {stats[k]}", file=out)

    by_phase = snapshot.get("by_phase") or {}
    if by_phase:
        print("\n== samples by phase ==", file=out)
        for ph, n in sorted(by_phase.items(), key=lambda kv: -kv[1]):
            if phase and ph != phase:
                continue
            pct = 100.0 * n / total if total else 0.0
            print(f"  {ph:<14} {n:>8}  {pct:5.1f}%  {_bar(n, total)}",
                  file=out)

    leaves = leaf_self_time(snapshot, phase=phase)
    if leaves:
        print("\n== top frames by self time ==", file=out)
        for leaf, n in leaves[:top]:
            pct = 100.0 * n / total if total else 0.0
            print(f"  {n:>8}  {pct:5.1f}%  {leaf}", file=out)

    shown = 0
    print("\n== hottest stacks ==", file=out)
    for ent in snapshot.get("top_stacks") or []:
        if phase and ent.get("phase") != phase:
            continue
        if shown >= top:
            break
        shown += 1
        head = (f"  [{ent.get('count', 0)}] {ent.get('phase', '?')}"
                f" / {ent.get('thread', '?')}")
        print(head, file=out)
        for frame in ent.get("stack") or []:
            print(f"      {frame}", file=out)
    if not shown:
        print("  (no stacks captured)", file=out)


def load(path):
    """Path -> snapshot dict.  Accepts .folded, profile.json, or a
    dump directory holding profile.json."""
    if os.path.isdir(path):
        path = os.path.join(path, "profile.json")
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return json.loads(text)
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and "by_phase" in doc:
            return doc
    except ValueError:
        pass
    return folded_to_snapshot(parse_folded(text))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help=".folded file, profile.json, or "
                                 "observability dump directory")
    ap.add_argument("--top", type=int, default=20,
                    help="rows per table (default 20)")
    ap.add_argument("--phase", default="",
                    help="narrow every table to one phase "
                         "(prefill/decode/verify/host_sync/idle)")
    args = ap.parse_args(argv)
    try:
        snap = load(args.path)
    except (OSError, ValueError) as e:
        print(f"profile_report: cannot read {args.path}: {e}",
              file=sys.stderr)
        return 2
    render(snap, top=args.top, phase=args.phase or None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
