"""Per-op microbenchmark + regression gate.

Reference analog: tools/ci_op_benchmark.sh + check_op_benchmark_result.py
— the reference gates op-level perf in CI against stored baselines so a
kernel regression (like the r2 eager-dispatch cost) trips a wire instead
of surfacing as a mysterious end-to-end slowdown.

Usage:
    python tools/op_bench.py                 # run suite, print JSON lines
    python tools/op_bench.py --save          # write tools/op_baseline.json
    python tools/op_bench.py --check [tol]   # exit 1 on >tol regression

Timing methodology: each case runs inside one jitted lax.scan chain (a
data dependency threads iterations) and cost is the T(n2)-T(n1) delta —
host-fetch and dispatch latency cancel, which is essential on tunneled
TPU transports where a single fetch costs ~100ms (see BASELINE.md).
Run --check on an otherwise-idle host: heavy concurrent CPU load can
skew the calibration pass and produce a false 2-3x reading (observed
once against a full pytest run; re-run confirms).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(__file__), "op_baseline.json")


def device_time(f, *args, reps=7, target=0.15):
    """Auto-calibrated scan-delta: chain length scales until the timed
    span is ~`target` seconds, so sub-0.1ms ops stay above the tunnel's
    dispatch/fetch jitter."""
    args = tuple(jnp.asarray(a) for a in args)

    def chain(n):
        @jax.jit
        def run(args):
            def body(c, _):
                bump = (args[0].astype(jnp.float32)
                        + c * 1e-30).astype(args[0].dtype)
                out = f(bump, *args[1:])
                leaf = jax.tree_util.tree_leaves(out)[0]
                return c + leaf.reshape(-1)[0].astype(jnp.float32) * 1e-30, \
                    None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
            return c
        return run

    # rough calibration pass
    # every timed execution gets FRESH input values: the tunneled relay
    # memoizes repeated (executable, buffers) dispatches, which otherwise
    # yields petaflop-fast readings for some reps and garbage deltas
    def variant(i):
        # 1% steps: large enough to change the BITS in bfloat16 (a 1e-6
        # bump rounds away and the relay memoizes the identical buffers)
        return tuple(
            (a * (1 + (i + 1) * 0.01)).astype(a.dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a
            for a in args)

    variants = [variant(i) for i in range(2 * reps + 2)]
    jax.block_until_ready(variants)
    vi = iter(variants)

    probe = chain(64)
    float(probe(args))
    t0 = time.perf_counter(); float(probe(next(vi)))
    est = max((time.perf_counter() - t0) / 64, 1e-7)
    n2 = int(min(4000, max(60, target / est)))
    n1 = max(4, n2 // 6)
    r1, r2 = chain(n1), chain(n2)
    float(r1(args)); float(r2(args))
    deltas = []
    for _ in range(reps):
        a1, a2 = next(vi), next(vi)
        t0 = time.perf_counter(); float(r1(a1)); t1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(r2(a2)); t2 = time.perf_counter() - t0
        deltas.append((t2 - t1) / (n2 - n1))
    # median of positive deltas: transport jitter inflates AND (via
    # relay-side caching artifacts) deflates individual readings, so the
    # floor statistic latches onto impossible sub-physical values —
    # the median is the stable center
    pos = sorted(d for d in deltas if d > 0)
    if not pos:
        return 0.0
    return pos[len(pos) // 2]


def _cases():
    """The hot-op suite: matmul/conv/norm/attention/softmax/MoE-dispatch
    shapes the bench ladder leans on."""
    key = jax.random.PRNGKey(0)
    on_tpu = jax.devices()[0].platform != "cpu"
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    big = 2048 if on_tpu else 128
    cases = {}

    a = jax.random.normal(key, (big, big), dt)
    cases["matmul_2kx2k"] = (lambda a: a @ a, (a,))

    x4 = jax.random.normal(key, (32, 56, 56, 64), dt)
    w4 = jax.random.normal(key, (3, 3, 64, 64), dt) * 0.1

    def conv(x, w=w4):
        dn = jax.lax.conv_dimension_numbers(
            x.shape, w4.shape, ("NHWC", "HWIO", "NHWC"))
        return jax.lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                            dimension_numbers=dn)
    cases["conv3x3_56x56x64"] = (conv, (x4,))

    xb = jax.random.normal(key, (32, 56, 56, 64), dt)

    def bn(x):
        from paddle_tpu.nn.functional import batch_norm
        out, _, _ = batch_norm.__op_body__(
            x, jnp.zeros(64), jnp.ones(64), jnp.ones(64), jnp.zeros(64),
            training=True, data_format="NHWC")
        return out
    cases["batch_norm_train"] = (bn, (xb,))

    s = 512 if on_tpu else 128
    q = jax.random.normal(key, (4, s, 8, 64), dt)

    def flash(q):
        from paddle_tpu.ops.pallas.flash_attention import sdpa
        return sdpa(q, q, q, is_causal=True)
    cases["flash_causal_s512"] = (flash, (q,))

    xs = jax.random.normal(key, (4096, 1024) if on_tpu else (256, 64), dt)
    cases["softmax_wide"] = (lambda x: jax.nn.softmax(
        x.astype(jnp.float32), axis=-1), (xs,))

    tok = jax.random.normal(key, (4096 if on_tpu else 128, 512), dt)
    gw = jax.random.normal(key, (512, 8), jnp.float32) * 0.3

    def moe_disp(x, gw=gw):
        from paddle_tpu.distributed.moe import (sort_dispatch_combine,
                                                _topk_choices, _capacity)
        logits = x @ gw.astype(x.dtype)
        idx, gv, _aux = _topk_choices(logits, 2, False, None)
        cap = _capacity(x.shape[0], 2, 1.25, 8, None)
        return sort_dispatch_combine(x, idx, gv, 8, cap, lambda t: t)
    cases["moe_sort_dispatch"] = (moe_disp, (tok,))

    emb = jax.random.normal(key, (32000, 512) if on_tpu else (1000, 64),
                            jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, emb.shape[0], (64, 128)))
    cases["embedding_gather"] = (lambda e: jnp.take(e, ids, axis=0), (emb,))

    return cases


def run_suite():
    out = {}
    for name, (f, args) in _cases().items():
        try:
            dt = device_time(f, *args)
        except Exception as e:  # keep the rest of the suite running
            print(json.dumps({"op": name,
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)
            continue
        out[name] = dt
        print(json.dumps({"op": name, "ms": round(dt * 1e3, 4)}),
              flush=True)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", action="store_true",
                    help="store results as the regression baseline")
    ap.add_argument("--check", nargs="?", const=2.0, type=float,
                    default=None, metavar="TOL",
                    help="fail if any op is > TOL x its baseline "
                         "(default 2.0 — sized to the tunneled "
                         "transport's residual jitter)")
    ap.add_argument("--runs", type=int, default=None,
                    help="full-suite repetitions; per-op MEDIAN is the "
                         "result (default: 5 for --save, 3 for --check) "
                         "— single runs on the tunneled transport land "
                         "in fast/slow service windows and even produce "
                         "physically impossible deflated readings")
    args = ap.parse_args(argv)

    n_runs = args.runs or (5 if args.save else 3 if args.check else 1)
    runs = [run_suite() for _ in range(n_runs)]
    results = {}
    all_keys = sorted({k for r in runs for k in r})  # union: an op that
    for k in all_keys:       # errored in run 0 must not escape the gate
        vals = sorted(r[k] for r in runs if k in r)
        if vals:
            results[k] = vals[len(vals) // 2]
    if n_runs > 1:
        for k, v in results.items():
            print(json.dumps({"op": k, "median_ms": round(v * 1e3, 4),
                              "runs": n_runs}), flush=True)
    if args.save:
        meta = {"device": jax.devices()[0].device_kind,
                "ops": {k: v for k, v in results.items()}}
        with open(BASELINE, "w") as f:
            json.dump(meta, f, indent=1)
        print(f"baseline saved: {BASELINE}")
        return 0
    if args.check is not None:
        if not os.path.exists(BASELINE):
            print("no baseline stored; run with --save first")
            return 0
        with open(BASELINE) as f:
            base = json.load(f)
        if base.get("device") != jax.devices()[0].device_kind:
            print(f"baseline device {base.get('device')!r} != current "
                  f"{jax.devices()[0].device_kind!r}; skipping gate")
            return 0
        cases = _cases()
        # common-mode rejection: the tunnel's service rate swings 2-5x
        # between runs and moves EVERY op together; a regression is an op
        # that slowed relative to the rest.  Normalize by the median
        # per-op ratio before applying the tolerance.
        ratios = sorted(v / base["ops"][k] for k, v in results.items()
                        if base["ops"].get(k))
        mode = ratios[len(ratios) // 2] if ratios else 1.0
        # clamp: a uniformly faster run is not a shield, and a >5x
        # "uniform slowdown" is beyond any observed weather window —
        # past that the ops themselves must answer for it
        mode = min(max(mode, 1.0), 5.0)
        bad = []
        for k, v in results.items():
            b = base["ops"].get(k)
            if b:
                b = b * mode
            if not b or v <= b * args.check:
                continue
            # retry-to-confirm: the tunnel's run-to-run jitter exceeds
            # any single-shot tolerance; a REAL regression reproduces,
            # a transport spike does not
            best = v
            for _ in range(2):
                try:
                    f, a = cases[k]
                    best = min(best, device_time(f, *a))
                except Exception:
                    break
                if best <= b * args.check:
                    break
            if best > b * args.check:
                bad.append((k, b, best))
        for k, b, v in bad:
            print(f"REGRESSION {k}: {v*1e3:.3f} ms vs baseline "
                  f"{b*1e3:.3f} ms (> {args.check}x, confirmed x3)")
        return 1 if bad else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
