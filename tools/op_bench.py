"""Per-op microbenchmark + regression gate.

Reference analog: tools/ci_op_benchmark.sh + check_op_benchmark_result.py
— the reference gates op-level perf in CI against stored baselines so a
kernel regression (like the r2 eager-dispatch cost) trips a wire instead
of surfacing as a mysterious end-to-end slowdown.

Usage:
    python tools/op_bench.py                 # run suite, print JSON lines
    python tools/op_bench.py --save          # write tools/op_baseline.json
    python tools/op_bench.py --check [tol]   # exit 1 on >tol regression

Timing methodology: each case runs inside one jitted lax.scan chain (a
data dependency threads iterations) and cost is the T(n2)-T(n1) delta —
host-fetch and dispatch latency cancel, which is essential on tunneled
TPU transports where a single fetch costs ~100ms (see BASELINE.md).
Run --check on an otherwise-idle host: heavy concurrent CPU load can
skew the calibration pass and produce a false 2-3x reading (observed
once against a full pytest run; re-run confirms).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE = os.path.join(os.path.dirname(__file__), "op_baseline.json")


def device_time(f, *args, reps=7, target=0.15):
    """Auto-calibrated scan-delta: chain length scales until the timed
    span is ~`target` seconds, so sub-0.1ms ops stay above the tunnel's
    dispatch/fetch jitter."""
    args = tuple(jnp.asarray(a) for a in args)

    def chain(n):
        @jax.jit
        def run(args):
            def body(c, _):
                bump = (args[0].astype(jnp.float32)
                        + c * 1e-30).astype(args[0].dtype)
                out = f(bump, *args[1:])
                leaf = jax.tree_util.tree_leaves(out)[0]
                return c + leaf.reshape(-1)[0].astype(jnp.float32) * 1e-30, \
                    None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=n)
            return c
        return run

    # rough calibration pass
    # every timed execution gets FRESH input values: the tunneled relay
    # memoizes repeated (executable, buffers) dispatches, which otherwise
    # yields petaflop-fast readings for some reps and garbage deltas
    def variant(i):
        # 1% steps: large enough to change the BITS in bfloat16 (a 1e-6
        # bump rounds away and the relay memoizes the identical buffers)
        return tuple(
            (a * (1 + (i + 1) * 0.01)).astype(a.dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a
            for a in args)

    variants = [variant(i) for i in range(2 * reps + 2)]
    jax.block_until_ready(variants)
    vi = iter(variants)

    probe = chain(64)
    float(probe(args))
    t0 = time.perf_counter(); float(probe(next(vi)))
    est = max((time.perf_counter() - t0) / 64, 1e-7)
    n2 = int(min(4000, max(60, target / est)))
    n1 = max(4, n2 // 6)
    r1, r2 = chain(n1), chain(n2)
    float(r1(args)); float(r2(args))
    deltas = []
    for _ in range(reps):
        a1, a2 = next(vi), next(vi)
        t0 = time.perf_counter(); float(r1(a1)); t1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(r2(a2)); t2 = time.perf_counter() - t0
        deltas.append((t2 - t1) / (n2 - n1))
    # median of positive deltas: transport jitter inflates AND (via
    # relay-side caching artifacts) deflates individual readings, so the
    # floor statistic latches onto impossible sub-physical values —
    # the median is the stable center
    pos = sorted(d for d in deltas if d > 0)
    if not pos:
        return 0.0
    return pos[len(pos) // 2]


def _cases():
    """The hot-op suite: matmul/conv/norm/attention/softmax/MoE-dispatch
    shapes the bench ladder leans on."""
    key = jax.random.PRNGKey(0)
    on_tpu = jax.devices()[0].platform != "cpu"
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    big = 2048 if on_tpu else 128
    cases = {}

    a = jax.random.normal(key, (big, big), dt)
    cases["matmul_2kx2k"] = (lambda a: a @ a, (a,))

    x4 = jax.random.normal(key, (32, 56, 56, 64), dt)
    w4 = jax.random.normal(key, (3, 3, 64, 64), dt) * 0.1

    def conv(x, w=w4):
        dn = jax.lax.conv_dimension_numbers(
            x.shape, w4.shape, ("NHWC", "HWIO", "NHWC"))
        return jax.lax.conv_general_dilated(x, w, (1, 1), "SAME",
                                            dimension_numbers=dn)
    cases["conv3x3_56x56x64"] = (conv, (x4,))

    xb = jax.random.normal(key, (32, 56, 56, 64), dt)

    def bn(x):
        from paddle_tpu.nn.functional import batch_norm
        out, _, _ = batch_norm.__op_body__(
            x, jnp.zeros(64), jnp.ones(64), jnp.ones(64), jnp.zeros(64),
            training=True, data_format="NHWC")
        return out
    cases["batch_norm_train"] = (bn, (xb,))

    s = 512 if on_tpu else 128
    q = jax.random.normal(key, (4, s, 8, 64), dt)

    def flash(q):
        from paddle_tpu.ops.pallas.flash_attention import sdpa
        return sdpa(q, q, q, is_causal=True)
    cases["flash_causal_s512"] = (flash, (q,))

    xs = jax.random.normal(key, (4096, 1024) if on_tpu else (256, 64), dt)
    cases["softmax_wide"] = (lambda x: jax.nn.softmax(
        x.astype(jnp.float32), axis=-1), (xs,))

    tok = jax.random.normal(key, (4096 if on_tpu else 128, 512), dt)
    gw = jax.random.normal(key, (512, 8), jnp.float32) * 0.3

    def moe_disp(x, gw=gw):
        from paddle_tpu.distributed.moe import (sort_dispatch_combine,
                                                _topk_choices, _capacity)
        logits = x @ gw.astype(x.dtype)
        idx, gv, _aux = _topk_choices(logits, 2, False, None)
        cap = _capacity(x.shape[0], 2, 1.25, 8, None)
        return sort_dispatch_combine(x, idx, gv, 8, cap, lambda t: t)
    cases["moe_sort_dispatch"] = (moe_disp, (tok,))

    emb = jax.random.normal(key, (32000, 512) if on_tpu else (1000, 64),
                            jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, emb.shape[0], (64, 128)))
    cases["embedding_gather"] = (lambda e: jnp.take(e, ids, axis=0), (emb,))

    # ================= round-4 widening (VERDICT r3 #6): every op
    # family the bench ladder touches gets a gated shape ===============
    rs = np.random.RandomState(1)

    def _grad(f):
        return jax.grad(lambda *a: jnp.sum(f(*a).astype(jnp.float32)))

    # ---- matmul family: decode GEMV, lm_head, weight-only kernels ----
    hK, hN, vN = (2048, 5632, 32000) if on_tpu else (128, 256, 512)
    hvec = jnp.asarray(rs.randn(8, hK) * 0.3, dt)
    wKN = jnp.asarray(rs.randn(hK, hN) * 0.02, dt)
    wKV = jnp.asarray(rs.randn(hK, vN) * 0.02, dt)
    cases["matmul_gemv_decode"] = (lambda h: h @ wKN, (hvec,))
    cases["matmul_lmhead"] = (lambda h: h @ wKV, (hvec,))
    if on_tpu:
        from paddle_tpu.ops.pallas import quant_matmul as QM
        q8 = jnp.asarray(rs.randint(-127, 128, (hK, hN)), jnp.int8)
        sc = jnp.asarray(rs.rand(hN).astype(np.float32) * 0.01)
        wq8 = QM.QuantizedWeight(q8, sc, kind="int8")
        wq4 = QM.QuantizedWeight(QM.pack_int4(
            jnp.clip(q8, -8, 7)), sc, kind="int4", k=hK)
        cases["wo_int8_gemv"] = (
            lambda h: QM.weight_only_matmul(h, wq8), (hvec,))
        cases["wo_int4_gemv"] = (
            lambda h: QM.weight_only_matmul(h, wq4), (hvec,))

    # ---- norms fwd + bwd ---------------------------------------------
    xn = jax.random.normal(key, (4096, 2048) if on_tpu else (64, 64), dt)
    gn = jnp.ones((xn.shape[-1],), dt)

    def rms(x):
        from paddle_tpu.ops.pallas.rms_norm import rms_norm
        return rms_norm(x, gn, 1e-6)
    cases["rms_norm_fwd"] = (rms, (xn,))
    cases["rms_norm_bwd"] = (_grad(rms), (xn,))

    def ln(x):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(
            xf.var(-1, keepdims=True) + 1e-5)).astype(x.dtype) * gn
    cases["layer_norm_fwd"] = (ln, (xn,))
    cases["layer_norm_bwd"] = (_grad(ln), (xn,))
    cases["batch_norm_bwd"] = (_grad(lambda x: bn(x)), (xb,))

    # ---- attention variants ------------------------------------------
    from paddle_tpu.ops.pallas.flash_attention import sdpa as _sdpa
    cases["flash_causal_bwd_s512"] = (_grad(
        lambda q: _sdpa(q, q, q, is_causal=True)), (q,))
    qg = jax.random.normal(key, (4, s, 8, 64), dt)
    kg = jax.random.normal(key, (4, s, 2, 64), dt)
    cases["flash_gqa_fwd"] = (
        lambda qq: _sdpa(qq, kg, kg, is_causal=True), (qg,))
    if on_tpu:
        from paddle_tpu.ops.pallas import flash_mask as FM
        seg = np.zeros((4, s), np.int32)
        seg[:, s // 2:] = 1
        vecs = FM.segment_intervals(jnp.asarray(seg), causal=True)
        cases["flashmask_fwd"] = (
            lambda qq: _sdpa(qq, qq, qq, flashmask=vecs, is_causal=True),
            (q,))
        cases["flashmask_bwd"] = (_grad(
            lambda qq: _sdpa(qq, qq, qq, flashmask=vecs, is_causal=True)),
            (q,))
        sl = 8192
        ql = jax.random.normal(key, (1, sl, 4, 128), dt)
        cases["flash_streamed_8k_fwd"] = (
            lambda qq: _sdpa(qq, qq, qq, is_causal=True), (ql,))
        # decode + paged serving kernels
        from paddle_tpu.ops.pallas.decode_attention import decode_attention
        dq8 = jax.random.normal(key, (8, 16, 128), dt)
        kc = jax.random.normal(key, (8, 16, 2048, 128), dt)
        pos = jnp.full((8,), 1500, jnp.int32)
        cases["decode_attention_t2048"] = (
            lambda qq: decode_attention(qq, kc, kc, pos), (dq8,))

    # ---- activations / elementwise -----------------------------------
    cases["gelu_fwd"] = (jax.nn.gelu, (xn,))
    cases["silu_mul_ffn"] = (
        lambda x: jax.nn.silu(x) * x, (xn,))
    cases["softmax_bwd"] = (_grad(
        lambda x: jax.nn.softmax(x.astype(jnp.float32), axis=-1)), (xs,))
    cases["bf16_cast_roundtrip"] = (
        lambda x: x.astype(jnp.bfloat16).astype(jnp.float32), (xs,))

    # ---- loss / sampling ---------------------------------------------
    vlab = jnp.asarray(rs.randint(0, vN, (256,)))
    hl = jax.random.normal(key, (256, hK), dt)

    def ce(h):
        logits = (h @ wKV).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, vlab[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - tgt)
    cases["cross_entropy_32k"] = (ce, (hl,))
    cases["cross_entropy_32k_bwd"] = (jax.grad(ce), (hl,))
    cases["top_k_logits"] = (
        lambda h: jax.lax.top_k(h @ wKV, 50)[0], (hvec,))

    # ---- optimizer steps ---------------------------------------------
    pt = jax.random.normal(key, (4096, 2048) if on_tpu else (64, 64),
                           jnp.float32)

    def adamw(p):
        m = 0.9 * p + 0.1 * p
        v_ = 0.95 * jnp.square(p) + 0.05
        return p - 1e-3 * (m / (jnp.sqrt(v_) + 1e-8) + 0.01 * p)
    cases["adamw_update_8m"] = (adamw, (pt,))
    cases["momentum_update_8m"] = (
        lambda p: p - 0.1 * (0.9 * p + p), (pt,))

    # ---- data movement -----------------------------------------------
    cases["kv_cache_update"] = (
        lambda c: jax.lax.dynamic_update_slice_in_dim(
            c, c[:, :, :1] * 2, 100, axis=2),
        (jax.random.normal(key, (8, 16, 512, 128) if on_tpu else
                           (2, 4, 64, 32), dt),))
    cases["transpose_bshd_bhsd"] = (
        lambda x: jnp.swapaxes(x, 1, 2).copy(),
        (jax.random.normal(key, (8, 512, 16, 128) if on_tpu else
                           (2, 64, 4, 32), dt),))
    cases["argsort_32k"] = (
        lambda x: jnp.argsort(x, axis=-1),
        (jax.random.normal(key, (64, 32000) if on_tpu else (8, 512),
                           jnp.float32),))
    cases["scatter_add_rows"] = (
        lambda e: e.at[ids[0]].add(1.0), (emb,))

    # ---- rope ---------------------------------------------------------
    from paddle_tpu.models.llama import _rope_tables, _rotate_half
    cos_t, sin_t = _rope_tables(s, 64, 10000.0)

    def rope(qq):
        c = cos_t[None, :, None, :].astype(qq.dtype)
        si = sin_t[None, :, None, :].astype(qq.dtype)
        return qq * c + _rotate_half(qq) * si
    cases["rope_apply"] = (rope, (q,))

    # ---- conv bwd ------------------------------------------------------
    cases["conv3x3_bwd"] = (_grad(conv), (x4,))

    return cases


def run_suite():
    out = {}
    for name, (f, args) in _cases().items():
        try:
            dt = device_time(f, *args)
        except Exception as e:  # keep the rest of the suite running
            print(json.dumps({"op": name,
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)
            continue
        out[name] = dt
        print(json.dumps({"op": name, "ms": round(dt * 1e3, 4)}),
              flush=True)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", action="store_true",
                    help="store results as the regression baseline")
    ap.add_argument("--check", nargs="?", const=2.0, type=float,
                    default=None, metavar="TOL",
                    help="fail if any op is > TOL x its baseline "
                         "(default 2.0 — sized to the tunneled "
                         "transport's residual jitter)")
    ap.add_argument("--runs", type=int, default=None,
                    help="full-suite repetitions; per-op MEDIAN is the "
                         "result (default: 5 for --save, 3 for --check) "
                         "— single runs on the tunneled transport land "
                         "in fast/slow service windows and even produce "
                         "physically impossible deflated readings")
    args = ap.parse_args(argv)

    n_runs = args.runs or (5 if args.save else 3 if args.check else 1)
    runs = [run_suite() for _ in range(n_runs)]
    results = {}
    all_keys = sorted({k for r in runs for k in r})  # union: an op that
    for k in all_keys:       # errored in run 0 must not escape the gate
        vals = sorted(r[k] for r in runs if k in r)
        if vals:
            results[k] = vals[len(vals) // 2]
    if n_runs > 1:
        for k, v in results.items():
            print(json.dumps({"op": k, "median_ms": round(v * 1e3, 4),
                              "runs": n_runs}), flush=True)
    if args.save:
        meta = {"device": jax.devices()[0].device_kind,
                "ops": {k: v for k, v in results.items()}}
        with open(BASELINE, "w") as f:
            json.dump(meta, f, indent=1)
        print(f"baseline saved: {BASELINE}")
        return 0
    if args.check is not None:
        if not os.path.exists(BASELINE):
            print("no baseline stored; run with --save first")
            return 0
        with open(BASELINE) as f:
            base = json.load(f)
        if base.get("device") != jax.devices()[0].device_kind:
            print(f"baseline device {base.get('device')!r} != current "
                  f"{jax.devices()[0].device_kind!r}; skipping gate")
            return 0
        cases = _cases()
        # common-mode rejection: the tunnel's service rate swings 2-5x
        # between runs and moves EVERY op together; a regression is an op
        # that slowed relative to the rest.  Normalize by the median
        # per-op ratio before applying the tolerance.
        ratios = sorted(v / base["ops"][k] for k, v in results.items()
                        if base["ops"].get(k))
        mode = ratios[len(ratios) // 2] if ratios else 1.0
        # clamp: a uniformly faster run is not a shield, and a >5x
        # "uniform slowdown" is beyond any observed weather window —
        # past that the ops themselves must answer for it
        mode = min(max(mode, 1.0), 5.0)
        bad = []
        for k, v in results.items():
            b = base["ops"].get(k)
            if b:
                b = b * mode
            if not b or v <= b * args.check:
                continue
            # retry-to-confirm: the tunnel's run-to-run jitter exceeds
            # any single-shot tolerance; a REAL regression reproduces,
            # a transport spike does not
            best = v
            for _ in range(2):
                try:
                    f, a = cases[k]
                    best = min(best, device_time(f, *a))
                except Exception:
                    break
                if best <= b * args.check:
                    break
            if best > b * args.check:
                bad.append((k, b, best))
        for k, b, v in bad:
            print(f"REGRESSION {k}: {v*1e3:.3f} ms vs baseline "
                  f"{b*1e3:.3f} ms (> {args.check}x, confirmed x3)")
        return 1 if bad else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
