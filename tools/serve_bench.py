#!/usr/bin/env python
"""Synthetic serving benchmark for the continuous-batching engine.

Drives paddle_tpu.serving over a staggered-arrival workload (requests
arrive on an open-loop schedule, with mixed prompt and output lengths)
and reports throughput, TTFT, and per-output-token latency, plus an
observability dump for tools/metrics_report.py.

Usage:
    python tools/serve_bench.py [--requests 16] [--max-slots 4]
        [--page-size 16] [--arrival-gap-ms 5]
        [--arrival uniform|bursty|heavytail]
        [--prompt-len 8 24] [--new-tokens 4 24]
        [--shared-prefix-len 0] [--sync-interval 1] [--spec-k 0]
        [--prefix-cache | --no-prefix-cache]
        [--layers 2 --hidden 64 --vocab 128]
        [--metrics-dir /tmp/serve_metrics] [--seed 0]

``--arrival`` shapes the open-loop schedule while keeping the mean
inter-arrival at ``--arrival-gap-ms``: ``uniform`` is the constant-gap
default, ``bursty`` drops requests in back-to-back groups (queueing
spikes), ``heavytail`` draws Pareto inter-arrivals (rare long lulls,
dense clumps).  Tail latency (p99 TTFT/TPOT) is reported per run so the
three patterns can be compared at identical offered load.

``--spec-k K`` turns on speculative decoding (prompt-lookup drafting +
one K+1-position verify step); greedy outputs are identical, only the
step count changes.

``--priority-mix hi:0.2,lo:0.8`` assigns each request a priority class
drawn from the given weights (hi/high -> 1, normal -> 0, lo/low -> -1,
or any bare int), and the report adds per-class p50/p99 TTFT/TPOT
lines.  Combine with ``--prefill-chunk N`` (chunked admission prefill)
and ``--preempt`` (priority preempt-and-swap) to exercise the overload
path; ``--overload-baseline`` re-runs the identical workload on an
FCFS engine (no chunking, no preemption) in the same invocation and
prints a per-class tail-latency comparison.

``--tenants teamA:0.5,teamB:0.3,free:0.2`` draws a tenant label per
request from the given weights, wires a usage meter into the engine,
and prints the per-tenant cost table (computed/cached/decode tokens,
KV page-seconds by tier, queue seconds, preemptions, sheds) plus the
page-seconds conservation check.  Works in both the in-process and
``--http`` modes (the HTTP path carries the tenant in the request body
and merges the per-replica tables).

``--adapters sum:0.4,cls:0.3,none:0.3`` registers one random LoRA
adapter per named class (rank ``--lora-rank``) in an AdapterStore
wired into the engine and draws an adapter per request from the
weights (the reserved names ``none``/``-`` mean dense base-model
requests); the report adds a per-adapter p50/p99 TTFT/TPOT table —
the multi-tenant adapter-serving overhead view.

``--batch-file FILE`` drip-feeds an offline JSONL batch job (one
``{"prompt": [...]}`` record per line) through the engine at the
batch priority lane while the interactive workload runs, and reports
the interactive-vs-batch goodput split plus the preemptions the
interactive traffic inflicted on the lane (in-process mode only).

``--shared-prefix-len N`` prepends one common N-token prefix to every
prompt (the system-prompt / few-shot pattern prefix caching targets);
with ``--prefix-cache`` (default on) the report adds the prefix-cache
page hit rate, pages saved, and host-sync counts next to TTFT/TPOT.

``--http [--replicas N]`` drives the real serving stack instead of the
in-process engine loop: N HTTP replicas (each its own engine + worker
thread) behind a prefix-affinity Router, with streaming clients over
localhost.  TTFT/TPOT then include HTTP + SSE overhead, and the report
adds per-replica latency percentiles (grouped by which replica served
each stream), request counts, and the aggregate prefix hit rate.

``--trace out.json`` writes a chrome://tracing-loadable timeline of the
run: request/queue/prefill/decode spans and gauge counters, merged with
the native host profile when one is active (profiler.export_host_trace).

``--profile out.folded`` samples a phase-attributed host profile of the
run (stacks split by the engine's published phase: prefill /
prefill_chunk / decode / verify / host_sync / idle) and writes folded
stacks — flamegraph.pl / speedscope input, rendered by
``tools/profile_report.py``.

``--explain-tail`` wires a per-request lifecycle log
(observability.requestlog.RequestLog) into the engine and prints the
critical-path attribution of the p99-TTFT cohort ("p99 TTFT is 71%
queue, 18% chunk_gap, ...") plus the overall per-cause totals and the
conservation check — the numbers match what ``tools/request_report.py``
renders from the run's ``exemplars.json`` dump (in-process mode only).

``--record OUT.json`` writes a machine-readable bench artifact after
the run: tok/s, TTFT/TPOT p50/p95/p99, the scenario knobs, and (with
``--explain-tail``) the tail attribution — the input for regression
dashboards and A/B diffs.

The model is a randomly initialized tiny llama (this benchmarks the
ENGINE — scheduling, paging, dispatch — not the matmuls); sizes are
flags so the same harness scales up on real hardware.
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _percentile(vals, q):
    if not vals:
        return float("nan")
    vals = sorted(vals)
    idx = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
    return vals[idx]


# priority-mix class names <-> engine priority ints (mirrors the
# server's low/normal/high vocabulary; bare ints pass through)
_MIX_NAMES = {"hi": 1, "high": 1, "normal": 0, "mid": 0,
              "lo": -1, "low": -1}
_CLASS_NAMES = {1: "high", 0: "normal", -1: "low"}


def _parse_priority_mix(spec):
    """``"hi:0.2,lo:0.8"`` -> ``[(priority, weight), ...]`` with the
    weights normalised to sum to 1.  Empty spec -> None."""
    if not spec:
        return None
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        name = name.strip().lower()
        pri = _MIX_NAMES.get(name)
        if pri is None:
            pri = int(name)
        out.append((pri, float(w) if w else 1.0))
    if not out:
        return None
    total = sum(w for _, w in out)
    if total <= 0:
        raise ValueError(f"--priority-mix {spec!r}: weights must be > 0")
    return [(p, w / total) for p, w in out]


def _assign_priorities(mix, rng, n):
    """One priority per request, drawn from the mix weights with the
    bench rng (same seed -> same assignment).  No mix -> all zeros."""
    if not mix:
        return [0] * n
    out = []
    for _ in range(n):
        u = rng.random()
        acc = 0.0
        pri = mix[-1][0]
        for p, w in mix:
            acc += w
            if u < acc:
                pri = p
                break
        out.append(pri)
    return out


def _class_label(pri):
    return _CLASS_NAMES.get(pri, str(pri))


def _parse_tenant_mix(spec):
    """``"teamA:0.5,teamB:0.5"`` -> ``[(name, weight), ...]`` with the
    weights normalised to sum to 1.  Empty spec -> None."""
    if not spec:
        return None
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        name = name.strip()
        if not name:
            continue
        out.append((name, float(w) if w else 1.0))
    if not out:
        return None
    total = sum(w for _, w in out)
    if total <= 0:
        raise ValueError(f"--tenants {spec!r}: weights must be > 0")
    return [(n, w / total) for n, w in out]


def _assign_tenants(mix, rng, n):
    """One tenant label per request, drawn from the mix weights with
    the bench rng (same seed -> same assignment).  No mix -> None."""
    if not mix:
        return [None] * n
    out = []
    for _ in range(n):
        u = rng.random()
        acc = 0.0
        name = mix[-1][0]
        for t, w in mix:
            acc += w
            if u < acc:
                name = t
                break
        out.append(name)
    return out


def _print_tenant_table(usage):
    """Per-tenant cost table from a UsageMeter snapshot (or a
    merge_usage result — conservation is then absent and skipped)."""
    tenants = usage.get("tenants") or {}
    if not tenants:
        return
    print("  tenant cost table (page-seconds ledger):")
    print(f"    {'tenant':<12} {'reqs':>5} {'good':>5} {'computed':>9} "
          f"{'cached':>7} {'decode':>7} {'page-s':>9} {'host-s':>8} "
          f"{'queue-s':>8} {'preempt':>7} {'shed':>5}")
    for name in sorted(tenants):
        row = tenants[name]
        print(f"    {name:<12} {row['requests']:>5} "
              f"{row['goodput_requests']:>5} "
              f"{row['prefill_computed_tokens']:>9} "
              f"{row['prefill_cached_tokens']:>7} "
              f"{row['decode_tokens']:>7} "
              f"{row['page_seconds']:>9.4f} "
              f"{row['host_page_seconds']:>8.4f} "
              f"{row['queue_seconds']:>8.4f} "
              f"{row['preemptions']:>7} {row['shed']:>5}")
    cons = usage.get("conservation")
    if cons:
        print(f"    conservation         device_delta="
              f"{cons['device_delta']} host_delta={cons['host_delta']} "
              f"(both must be 0)")


def _per_class_latency(samples):
    """``samples``: iterable of (priority, ttft_or_None, tpot_or_None)
    -> ``{label: {"ttft_s": [...], "tpot_s": [...], "requests": n}}``."""
    out = {}
    for pri, ttft, tpot in samples:
        d = out.setdefault(_class_label(pri),
                           {"ttft_s": [], "tpot_s": [], "requests": 0})
        d["requests"] += 1
        if ttft is not None:
            d["ttft_s"].append(ttft)
        if tpot is not None:
            d["tpot_s"].append(tpot)
    return out


def _print_per_class(per_class, kind="class"):
    for label in sorted(per_class):
        d = per_class[label]
        line = f"  {kind} {label:<8} n={d['requests']}"
        if d["ttft_s"]:
            line += (f"  TTFT p50/p99 "
                     f"{_percentile(d['ttft_s'], 0.5) * 1e3:.2f}/"
                     f"{_percentile(d['ttft_s'], 0.99) * 1e3:.2f} ms")
        if d["tpot_s"]:
            line += (f"  TPOT p50/p99 "
                     f"{_percentile(d['tpot_s'], 0.5) * 1e3:.2f}/"
                     f"{_percentile(d['tpot_s'], 0.99) * 1e3:.2f} ms")
        print(line)


def _per_replica_latency(results):
    """Group --http results by the replica that served each stream:
    ``{replica_name: (ttfts, tpots, n_requests)}``."""
    out: dict = {}
    for r in results:
        if not r or r[4] is None:
            continue
        sent, first, last, n_toks, replica = r
        ttfts, tpots, n = out.setdefault(replica, ([], [], 0))
        out[replica] = (ttfts, tpots, n + 1)
        if first is not None:
            ttfts.append(first - sent)
        if n_toks > 1:
            tpots.append((last - first) / (n_toks - 1))
    return out


def run_bench(args):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import GenerationConfig, create_engine

    rng = np.random.default_rng(args.seed)
    paddle.seed(args.seed)
    cfg = llama_tiny(num_hidden_layers=args.layers, hidden_size=args.hidden,
                     intermediate_size=2 * args.hidden,
                     vocab_size=args.vocab,
                     num_attention_heads=args.heads,
                     num_key_value_heads=args.kv_heads,
                     max_position_embeddings=args.max_model_len)
    model = LlamaForCausalLM(cfg)
    model.eval()

    tenant_mix = _parse_tenant_mix(getattr(args, "tenants", ""))
    usage_meter = None
    if tenant_mix:
        from paddle_tpu.observability.usage import UsageMeter
        usage_meter = UsageMeter()

    # --adapters sum:0.4,none:0.6: random rank-r adapters registered in
    # an AdapterStore; the reserved names none/- mean dense requests
    adapter_mix = _parse_tenant_mix(getattr(args, "adapters", ""))
    lora_store = None
    if adapter_mix:
        from paddle_tpu.serving.lora import AdapterStore, random_adapter
        names = [n for n, _ in adapter_mix if n not in ("none", "-")]
        lora_store = AdapterStore(cfg, capacity=max(1, len(names)),
                                  rank=args.lora_rank)
        for j, nm in enumerate(names):
            lora_store.register(
                nm, random_adapter(cfg, args.lora_rank,
                                   seed=args.seed + j))

    # --explain-tail: per-request lifecycle timelines + critical-path
    # attribution (requestlog=None keeps the zero-overhead-off default)
    requestlog = None
    if getattr(args, "explain_tail", False):
        from paddle_tpu.observability.requestlog import RequestLog
        requestlog = RequestLog(max_requests=max(512, args.requests))

    engine = create_engine(model, max_slots=args.max_slots,
                           page_size=args.page_size,
                           num_pages=args.num_pages,
                           max_model_len=args.max_model_len,
                           enable_prefix_cache=args.prefix_cache,
                           sync_interval=args.sync_interval,
                           mesh=args.mesh, spec_k=args.spec_k,
                           prefill_chunk=getattr(args, "prefill_chunk",
                                                 None),
                           preempt=getattr(args, "preempt", None),
                           usage=usage_meter, lora=lora_store,
                           quant=(None if getattr(args, "quant", "none")
                                  == "none" else args.quant),
                           kv_quant=getattr(args, "kv_quant", None),
                           requestlog=requestlog)

    # --batch-file FILE: an offline JSONL job rides the batch priority
    # lane, drip-fed between interactive admissions
    batch_job = None
    if getattr(args, "batch_file", ""):
        from paddle_tpu.serving.lora import BatchJob
        batch_job = BatchJob.from_jsonl(args.batch_file)

    # --chaos SEED: seed a probabilistic fault plan (poisoned steps,
    # synthetic OOM, slow steps) and drive through the self-healing
    # supervisor — the run then reports availability alongside latency
    chaos = getattr(args, "chaos", None)
    supervisor = None
    if chaos is not None:
        from paddle_tpu.serving import EngineSupervisor, FaultPlan
        plan = FaultPlan(seed=int(chaos))
        plan.add("step_raise", p=0.01)
        plan.add("page_alloc", p=0.01)
        plan.add("slow_step", p=0.02, seconds=0.002)
        plan.add("spill_fail", p=0.05)
        engine.faults = plan
        engine.blocks.faults = plan
        supervisor = EngineSupervisor(engine)
    step = engine.step if supervisor is None else supervisor.step

    # --profile out.folded: continuous phase-attributed sampling of
    # the bench (this driver thread runs the engine, so its stacks
    # split by engine.current_phase); folded stacks land at the path
    profiler = None
    if getattr(args, "profile", None):
        bench_ident = threading.get_ident()
        profiler = obs.SamplingProfiler(
            0.005, phases=lambda: {bench_ident: engine.current_phase})
        profiler.start_sampling()

    workload = _build_workload(args, rng, np)
    mix = _parse_priority_mix(getattr(args, "priority_mix", ""))
    priorities = _assign_priorities(mix, rng, len(workload))
    tenants = _assign_tenants(tenant_mix, rng, len(workload))
    adapters = [None if a in (None, "none", "-") else a
                for a in _assign_tenants(adapter_mix, rng,
                                         len(workload))]

    t0 = time.monotonic()
    pending = list(enumerate(workload))
    reqs = []
    # open-loop driver: submit what has "arrived", run one iteration,
    # repeat — admissions interleave with decode exactly as in a server
    while (pending or engine.scheduler.has_work()
           or (batch_job is not None and not batch_job.done)):
        if batch_job is not None and not batch_job.done:
            batch_job.pump(engine.submit)
        now = time.monotonic() - t0
        while pending and pending[0][1][0] <= now:
            i, (_, prompt, n_new) = pending.pop(0)
            reqs.append(engine.submit(
                prompt, GenerationConfig(max_new_tokens=n_new),
                priority=priorities[i], tenant=tenants[i],
                adapter=adapters[i]))
        if not step() and pending:
            time.sleep(min(1e-3, max(0.0, pending[0][1][0] - now)))
    wall = time.monotonic() - t0

    toks = sum(r.num_generated for r in reqs)
    ttfts = [r.first_token_at - r.arrival_time for r in reqs
             if r.first_token_at is not None]
    tpots = []
    for r in reqs:
        if r.num_generated > 1:
            tpots.append((r.last_token_at - r.first_token_at)
                         / (r.num_generated - 1))
    stats = engine.stats()

    print(f"serve_bench: {len(reqs)} requests, {toks} tokens, "
          f"{wall:.3f}s wall ({args.arrival} arrivals)")
    print(f"  throughput      {toks / wall:10.1f} tok/s")
    print(f"  TTFT   mean/p50/p95/p99  {np.mean(ttfts) * 1e3:8.2f} / "
          f"{_percentile(ttfts, 0.5) * 1e3:.2f} / "
          f"{_percentile(ttfts, 0.95) * 1e3:.2f} / "
          f"{_percentile(ttfts, 0.99) * 1e3:.2f} ms")
    if tpots:
        print(f"  TPOT   mean/p50/p95/p99  {np.mean(tpots) * 1e3:8.2f} / "
              f"{_percentile(tpots, 0.5) * 1e3:.2f} / "
              f"{_percentile(tpots, 0.95) * 1e3:.2f} / "
              f"{_percentile(tpots, 0.99) * 1e3:.2f} ms")
    print(f"  decode-step traces   {stats['decode_traces']} "
          f"(continuous batching wants exactly 1)")
    print(f"  prefill buckets      {stats['prefill_buckets']}"
          + (f" cached={stats['cached_prefill_buckets']}"
             if stats['cached_prefill_buckets'] else ""))
    lookups = stats["prefix_hits"] + stats["prefix_misses"]
    hit_rate = stats["prefix_hits"] / lookups if lookups else 0.0
    if args.prefix_cache:
        print(f"  prefix cache         hit rate {hit_rate * 100:.1f}% "
              f"({stats['prefix_hits']}/{lookups} page lookups), "
              f"{stats['prefix_hits']} pages saved, "
              f"{stats['cached_tokens']} prompt tokens skipped, "
              f"{stats['cow_copies']} CoW copies, "
              f"{stats['prefix_evictions']} evictions")
    print(f"  host syncs           {stats['host_syncs']} ring "
          f"(~1/{args.sync_interval} per token) + "
          f"{stats['logit_fetches']} logits fetches")
    if args.spec_k:
        steps = stats["decode_steps"]
        print(f"  spec decode          k={args.spec_k}: "
              f"{stats['spec_accepted']}/{stats['spec_proposed']} drafts "
              f"accepted ({stats['spec_acceptance_rate'] * 100:.1f}%), "
              f"{stats['spec_verify_steps']} verify steps, "
              f"{toks / steps if steps else 0.0:.2f} tokens/decode-step")

    def _req_samples():
        for r in reqs:
            ttft = (r.first_token_at - r.arrival_time
                    if r.first_token_at is not None else None)
            tpot = ((r.last_token_at - r.first_token_at)
                    / (r.num_generated - 1)
                    if r.num_generated > 1 else None)
            yield getattr(r, "priority", 0), ttft, tpot

    per_class = _per_class_latency(_req_samples())
    if mix:
        _print_per_class(per_class)
    if (stats.get("prefill_chunk") or stats.get("preemptions")
            or stats.get("spill_aborts")):
        print(f"  scheduling           chunk={stats['prefill_chunk']}: "
              f"{stats['prefill_chunks']} prefill chunks "
              f"(max decode gap {stats['max_prefill_gap']} tok), "
              f"{stats['preemptions']} preemptions "
              f"({stats['spill_aborts']} aborted), "
              f"{stats['spilled_pages']}/{stats['restored_pages']} pages "
              f"spilled/restored ({stats['spill_bytes']} bytes)")

    per_adapter = {}
    if adapter_mix:
        per_adapter = _per_class_latency(
            (getattr(r, "adapter", None) or "(dense)", ttft, tpot)
            for (_, ttft, tpot), r in zip(_req_samples(), reqs))
        _print_per_class(per_adapter, kind="adapter")
        print(f"  adapter bank         "
              f"{engine.lora_snapshot()['bank_bytes_device']} device "
              f"bytes, {lora_store.loads} loads, "
              f"{lora_store.evictions} evictions")

    batch_out = {}
    if batch_job is not None:
        prog = batch_job.progress()
        print(f"  batch lane           job {prog['id']}: "
              f"{prog['completed']}/{prog['total']} rows "
              f"({prog['failed']} failed), {prog['output_tokens']} "
              f"tokens -> {prog['output_path']}")
        print(f"  goodput split        interactive {toks} tok "
              f"({toks / wall:.1f} tok/s) vs batch "
              f"{prog['output_tokens']} tok "
              f"({prog['output_tokens'] / wall:.1f} tok/s), "
              f"{stats['preemptions']} preemptions")
        batch_out = {"batch": prog}

    usage_out = {}
    if usage_meter is not None:
        snap = usage_meter.snapshot()
        _print_tenant_table(snap)
        usage_out = {"usage": snap}

    tail_out = {}
    if requestlog is not None:
        tail_out = {"tail": _explain_tail(requestlog, reqs, ttfts)}

    chaos_out = {}
    if supervisor is not None:
        ok = sum(1 for r in reqs if r.finish_reason in ("length", "eos"))
        availability = ok / len(reqs) if reqs else 1.0
        leak = engine.blocks.pool_accounting()["leak"]
        print(f"  chaos (seed {chaos})  availability "
              f"{availability * 100:.1f}% ({ok}/{len(reqs)}), "
              f"{engine.recoveries} recoveries, "
              f"{engine.quarantines} quarantines, "
              f"faults {dict(engine.faults.injected)}, leak {leak}")
        print(f"  p99 under faults     TTFT "
              f"{_percentile(ttfts, 0.99) * 1e3:.2f} ms, TPOT "
              f"{_percentile(tpots, 0.99) * 1e3:.2f} ms")
        chaos_out = {"chaos_seed": int(chaos),
                     "availability": availability,
                     "recoveries": engine.recoveries,
                     "quarantines": engine.quarantines,
                     "faults_injected": dict(engine.faults.injected),
                     "leaked_pages": leak,
                     "spill_aborts": engine.spill_aborts}

    profile_out = {}
    if profiler is not None:
        profiler.stop()
        with open(args.profile, "w") as f:
            f.write(profiler.folded() + "\n")
        by_phase = profiler.by_phase()
        top = ", ".join(f"{k}={v}" for k, v in
                        list(by_phase.items())[:4])
        print(f"  profile              {profiler.samples} samples -> "
              f"{args.profile} (render: python tools/profile_report.py "
              f"{args.profile}; phases: {top})")
        profile_out = {"profile_path": args.profile,
                       "profile_samples": profiler.samples,
                       "profile_by_phase": by_phase}

    if args.metrics_dir:
        out = obs.dump(args.metrics_dir)
        print(f"  metrics dump         {out} "
              f"(render: python tools/metrics_report.py {out})")
    _export_trace(args)
    return {**profile_out,
            "requests": len(reqs), "tokens": toks, "wall_s": wall,
            "arrival": args.arrival, "spec_k": args.spec_k,
            "throughput": toks / wall, "ttft_s": ttfts, "tpot_s": tpots,
            "decode_traces": stats["decode_traces"],
            "prefix_hit_rate": hit_rate,
            "pages_saved": stats["prefix_hits"],
            "host_syncs": stats["host_syncs"],
            "logit_fetches": stats["logit_fetches"],
            "per_class": per_class, "per_adapter": per_adapter,
            "prefill_chunks": stats["prefill_chunks"],
            "max_prefill_gap": stats["max_prefill_gap"],
            "preemptions": stats["preemptions"],
            "spill_aborts": stats["spill_aborts"],
            "spilled_pages": stats["spilled_pages"],
            "restored_pages": stats["restored_pages"],
            **batch_out, **usage_out, **tail_out, **chaos_out}


def _explain_tail(requestlog, reqs, ttfts):
    """--explain-tail report: critical-path attribution of the
    p99-TTFT cohort (every request whose TTFT reached the p99
    estimate) plus the run-wide per-cause totals and the conservation
    check.  Seconds are rounded to 6 decimals — identical to what the
    run's exemplars.json dump carries, so tools/request_report.py
    renders the same numbers."""
    snap = requestlog.snapshot()
    totals = snap["attribution_totals_s"]

    thresh = _percentile(ttfts, 0.99) if ttfts else float("inf")
    cohort = []
    for r in reqs:
        if r.first_token_at is None:
            continue
        if r.first_token_at - r.arrival_time >= thresh:
            tl = requestlog.get(r.id)
            if tl is not None:
                cohort.append(tl)
    cohort_s: dict = {}
    for tl in cohort:
        for cause, v in tl.attribution().items():
            cohort_s[cause] = cohort_s.get(cause, 0.0) + v
    cohort_s = {c: round(v, 6) for c, v in cohort_s.items()}

    def shares(by_cause):
        spent = sum(by_cause.values())
        if spent <= 0:
            return "no attributed seconds"
        top = sorted(by_cause.items(), key=lambda kv: -kv[1])
        return ", ".join(f"{100.0 * v / spent:.0f}% {c}"
                         for c, v in top if v > 0)

    if cohort_s:
        print(f"  tail attribution     p99 TTFT cohort "
              f"({len(cohort)} req): {shares(cohort_s)}")
    print(f"  latency attribution  {shares(totals)} "
          f"over {snap['finished']} finished requests")
    print(f"  conservation         max |sum(buckets) - e2e| = "
          f"{snap['conservation_max_delta']} (must be 0)")
    return {"attribution_totals_s": totals,
            "p99_ttft_cohort": {"requests": len(cohort),
                                "attribution_s": cohort_s},
            "finished": snap["finished"],
            "conservation_max_delta": snap["conservation_max_delta"],
            "exemplars": snap["exemplars"]}


# scenario knobs --record captures alongside the results — enough to
# reproduce the run (with --seed) and to group artifacts in dashboards
_RECORD_KNOBS = (
    "requests", "max_slots", "page_size", "num_pages", "arrival_gap_ms",
    "arrival", "prompt_len", "new_tokens", "shared_prefix_len",
    "sync_interval", "spec_k", "prefix_cache", "prefill_chunk",
    "preempt", "priority_mix", "tenants", "adapters", "lora_rank",
    "quant", "kv_quant", "http", "replicas", "layers", "hidden",
    "vocab", "heads", "kv_heads", "max_model_len", "seed")


def _write_record(args, res):
    """--record OUT.json: machine-readable bench artifact (throughput,
    latency percentiles, scenario knobs, and — with --explain-tail —
    the p99-cohort attribution)."""
    import json

    def pcts(vals):
        if not vals:
            return None
        return {"p50": _percentile(vals, 0.5),
                "p95": _percentile(vals, 0.95),
                "p99": _percentile(vals, 0.99),
                "mean": sum(vals) / len(vals), "n": len(vals)}

    doc = {"tool": "serve_bench",
           "scenario": {k: (list(v) if isinstance(v, tuple) else v)
                        for k in _RECORD_KNOBS
                        for v in [getattr(args, k, None)]},
           "requests": res.get("requests"),
           "tokens": res.get("tokens"),
           "wall_s": res.get("wall_s"),
           "tokens_per_s": res.get("throughput"),
           "ttft_s": pcts(res.get("ttft_s") or []),
           "tpot_s": pcts(res.get("tpot_s") or []),
           "tail": res.get("tail")}
    with open(args.record, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"  record               {args.record}")


def run_overload_compare(args):
    """--overload-baseline: run the configured engine, then the same
    seeded workload (identical arrivals, prompts, priorities) on an
    FCFS engine with chunking and preemption off, and print the
    per-class tail-latency comparison.  Returns (configured, fcfs)."""
    import copy

    res = run_bench(args)
    base_args = copy.copy(args)
    base_args.prefill_chunk = 0
    base_args.preempt = False
    base_args.profile = ""      # the configured run owns the profile
    print("\n--- FCFS baseline: same workload, prefill-chunk 0, "
          "no preemption ---")
    ref = run_bench(base_args)

    print("\noverload comparison (configured vs FCFS baseline):")
    labels = sorted(set(res.get("per_class", {}))
                    | set(ref.get("per_class", {})))
    rows = [(f"class {lab}",
             res["per_class"].get(lab, {}),
             ref["per_class"].get(lab, {})) for lab in labels]
    rows.append(("overall",
                 {"ttft_s": res["ttft_s"], "tpot_s": res["tpot_s"]},
                 {"ttft_s": ref["ttft_s"], "tpot_s": ref["tpot_s"]}))
    for name, a, b in rows:
        for metric in ("ttft_s", "tpot_s"):
            va, vb = a.get(metric, []), b.get(metric, [])
            if not va or not vb:
                continue
            pa = _percentile(va, 0.99) * 1e3
            pb = _percentile(vb, 0.99) * 1e3
            tag = metric[:4].upper()
            print(f"  {name:<14} p99 {tag} {pa:8.2f} ms vs "
                  f"{pb:8.2f} ms FCFS "
                  f"({'-' if pa <= pb else '+'}"
                  f"{abs(pa - pb) / pb * 100 if pb else 0.0:.1f}%)")
    return res, ref


def _export_trace(args):
    if not getattr(args, "trace", None):
        return
    from paddle_tpu import profiler
    if profiler.export_host_trace(args.trace):
        print(f"  chrome trace         {args.trace} "
              f"(load in chrome://tracing or https://ui.perfetto.dev)")
    else:
        print(f"  chrome trace         FAILED to write {args.trace}")


def _arrival_times(args, rng):
    """Arrival offsets (seconds) for each request.  Every pattern keeps
    the mean inter-arrival at ``--arrival-gap-ms`` so runs differ only
    in burstiness, not offered load."""
    gap = args.arrival_gap_ms / 1e3
    n = args.requests
    if args.arrival == "uniform":
        return [i * gap for i in range(n)]
    if args.arrival == "bursty":
        # back-to-back groups of 4, bursts spaced to preserve the rate
        burst = 4
        return [(i // burst) * burst * gap for i in range(n)]
    # heavytail: Pareto (alpha=1.5) inter-arrivals scaled to mean gap —
    # E[pareto+1] = alpha/(alpha-1), so multiply by (alpha-1)/alpha
    alpha = 1.5
    gaps = (rng.pareto(alpha, n) + 1.0) * gap * (alpha - 1.0) / alpha
    t, out = 0.0, []
    for g in gaps:
        out.append(t)
        t += float(g)
    return out


def _build_workload(args, rng, np):
    plo, phi = args.prompt_len
    nlo, nhi = args.new_tokens
    shared = rng.integers(0, args.vocab,
                          args.shared_prefix_len).astype(np.int32)
    arrivals = _arrival_times(args, rng)
    workload = []
    for i in range(args.requests):
        suffix = rng.integers(0, args.vocab,
                              int(rng.integers(plo, phi + 1))).astype(
                                  np.int32)
        workload.append((
            arrivals[i],
            np.concatenate([shared, suffix]) if shared.size else suffix,
            int(rng.integers(nlo, nhi + 1))))
    return workload


def run_http_bench(args):
    """End-to-end benchmark over the HTTP serving stack: N replica
    servers behind a Router, streaming SSE clients over localhost."""
    import threading

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    from paddle_tpu.serving import Router, serve

    rng = np.random.default_rng(args.seed)
    paddle.seed(args.seed)
    cfg = llama_tiny(num_hidden_layers=args.layers, hidden_size=args.hidden,
                     intermediate_size=2 * args.hidden,
                     vocab_size=args.vocab,
                     num_attention_heads=args.heads,
                     num_key_value_heads=args.kv_heads,
                     max_position_embeddings=args.max_model_len)
    model = LlamaForCausalLM(cfg)
    model.eval()

    tenant_mix = _parse_tenant_mix(getattr(args, "tenants", ""))

    def _replica_kw():
        if not tenant_mix:
            return {}
        from paddle_tpu.observability.usage import UsageMeter
        return {"usage": UsageMeter()}      # one meter per replica

    # each replica announces itself via the SSE "model" field, so the
    # client side can attribute every stream to the replica that ran it
    servers = [serve(model, max_slots=args.max_slots,
                     page_size=args.page_size,
                     num_pages=args.num_pages,
                     max_model_len=args.max_model_len,
                     enable_prefix_cache=args.prefix_cache,
                     sync_interval=args.sync_interval,
                     spec_k=args.spec_k,
                     quant=(None if args.quant == "none"
                            else args.quant),
                     kv_quant=args.kv_quant,
                     model_name=f"replica-{i}", **_replica_kw())
               for i in range(args.replicas)]
    router = Router([s.address for s in servers],
                    page_size=args.page_size)
    workload = _build_workload(args, rng, np)
    mix = _parse_priority_mix(getattr(args, "priority_mix", ""))
    priorities = _assign_priorities(mix, rng, len(workload))
    tenants = _assign_tenants(tenant_mix, rng, len(workload))

    results = [None] * len(workload)
    rejected = [False] * len(workload)
    t0 = time.monotonic()

    def drive(i, at, prompt, n_new):
        time.sleep(max(0.0, at - (time.monotonic() - t0)))
        sent = time.monotonic()
        first = last = None
        n_toks = 0
        replica = None
        try:
            for ev in router.completion([int(t) for t in prompt],
                                        max_tokens=n_new, stream=True,
                                        priority=priorities[i],
                                        tenant=tenants[i]):
                replica = ev.get("model", replica)
                got = ev["choices"][0]["token_ids"]
                if got:
                    n_toks += len(got)
                    last = time.monotonic()
                    if first is None:
                        first = last
        except Exception:
            # shed (429) or replica failure — counted, not fatal
            rejected[i] = True
            return
        results[i] = (sent, first, last, n_toks, replica)

    threads = [threading.Thread(target=drive, args=(i, at, p, n),
                                daemon=True)
               for i, (at, p, n) in enumerate(workload)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    toks = sum(r[3] for r in results if r)
    ttfts = [r[1] - r[0] for r in results if r and r[1] is not None]
    tpots = [(r[2] - r[1]) / (r[3] - 1) for r in results
             if r and r[3] > 1]

    rstats = router.stats()
    hits = misses = 0
    for srv in servers:
        st = srv.worker.stats()
        hits += st["prefix_hits"]
        misses += st["prefix_misses"]
    lookups = hits + misses
    hit_rate = hits / lookups if lookups else 0.0

    print(f"serve_bench --http: {len(results)} requests over "
          f"{args.replicas} replica(s), {toks} tokens, {wall:.3f}s wall "
          f"({args.arrival} arrivals)")
    print(f"  throughput      {toks / wall:10.1f} tok/s")
    if ttfts:
        print(f"  TTFT   mean/p50/p95/p99  {np.mean(ttfts) * 1e3:8.2f} / "
              f"{_percentile(ttfts, 0.5) * 1e3:.2f} / "
              f"{_percentile(ttfts, 0.95) * 1e3:.2f} / "
              f"{_percentile(ttfts, 0.99) * 1e3:.2f} ms")
    if tpots:
        print(f"  TPOT   mean/p50/p95/p99  {np.mean(tpots) * 1e3:8.2f} / "
              f"{_percentile(tpots, 0.5) * 1e3:.2f} / "
              f"{_percentile(tpots, 0.95) * 1e3:.2f} / "
              f"{_percentile(tpots, 0.99) * 1e3:.2f} ms")
    per_class = _per_class_latency(
        (priorities[i],
         r[1] - r[0] if r[1] is not None else None,
         (r[2] - r[1]) / (r[3] - 1) if r[3] > 1 else None)
        for i, r in enumerate(results) if r)
    if mix:
        _print_per_class(per_class)
    n_rejected = sum(rejected)
    if n_rejected:
        print(f"  rejected             {n_rejected} requests "
              f"(shed or replica failure)")
    per_replica = _per_replica_latency(results)
    for name in sorted(per_replica):
        r_ttft, r_tpot, n = per_replica[name]

        def pcts(vals):
            return (f"{_percentile(vals, 0.5) * 1e3:.2f}/"
                    f"{_percentile(vals, 0.95) * 1e3:.2f}/"
                    f"{_percentile(vals, 0.99) * 1e3:.2f}")

        line = f"  {name:<12} n={n}"
        if r_ttft:
            line += f"  TTFT p50/p95/p99 {pcts(r_ttft)} ms"
        if r_tpot:
            line += f"  TPOT p50/p95/p99 {pcts(r_tpot)} ms"
        print(line)
    for rep in rstats["replicas"]:
        print(f"  replica {rep['address']}  up={rep['up']} "
              f"fails={rep['fails']} inflight={rep['inflight']}")
    if args.prefix_cache:
        print(f"  prefix cache         hit rate {hit_rate * 100:.1f}% "
              f"({hits}/{lookups} page lookups across replicas)")

    usage_out = {}
    if tenant_mix:
        from paddle_tpu.observability.usage import merge_usage
        merged = merge_usage(srv.worker.engine.usage.snapshot()
                             for srv in servers)
        _print_tenant_table(merged)
        usage_out = {"usage": merged}

    router.stop()
    for srv in servers:
        srv.stop(drain_timeout=5.0)
    if args.metrics_dir:
        out = obs.dump(args.metrics_dir)
        print(f"  metrics dump         {out} "
              f"(render: python tools/metrics_report.py {out})")
    _export_trace(args)
    return {"requests": len(results), "tokens": toks, "wall_s": wall,
            "arrival": args.arrival, "spec_k": args.spec_k,
            "throughput": toks / wall, "ttft_s": ttfts, "tpot_s": tpots,
            "prefix_hit_rate": hit_rate, "router": rstats,
            "per_class": per_class, "rejected": n_rejected,
            "per_replica": {k: {"ttft_s": v[0], "tpot_s": v[1],
                                "requests": v[2]}
                            for k, v in per_replica.items()},
            **usage_out}


def _build_parser() -> argparse.ArgumentParser:
    """THE bench argument parser — the single source of defaults.
    ``bench_args()`` derives embedder/test Namespaces from it, so a
    newly added flag can never be missing from a hand-built one."""
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="pool size (default: full residency)")
    ap.add_argument("--arrival-gap-ms", type=float, default=5.0)
    ap.add_argument("--arrival", default="uniform",
                    choices=("uniform", "bursty", "heavytail"),
                    help="arrival pattern at the same mean rate: "
                         "constant gap, back-to-back groups of 4, or "
                         "Pareto inter-arrivals")
    ap.add_argument("--prompt-len", type=int, nargs=2, default=(8, 24),
                    metavar=("LO", "HI"))
    ap.add_argument("--new-tokens", type=int, nargs=2, default=(4, 24),
                    metavar=("LO", "HI"))
    ap.add_argument("--shared-prefix-len", type=int, default=0,
                    help="common prompt prefix prepended to every "
                         "request (exercises the prefix cache)")
    ap.add_argument("--sync-interval", type=int, default=1,
                    help="greedy decode steps per host sync")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative decoding draft length (0 = off); "
                         "greedy outputs are identical either way")
    ap.add_argument("--prefix-cache",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="automatic prefix caching over the KV pool")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--max-model-len", type=int, default=128)
    ap.add_argument("--http", action="store_true",
                    help="drive the real HTTP stack (replica servers + "
                         "router + SSE clients) instead of the "
                         "in-process engine loop")
    ap.add_argument("--replicas", type=int, default=1,
                    help="replica server count for --http")
    ap.add_argument("--metrics-dir", default="")
    ap.add_argument("--trace", default="",
                    help="write a chrome://tracing JSON of the run's "
                         "request/prefill/decode spans to this path")
    ap.add_argument("--mesh", default=None,
                    help="tensor-parallel mesh size for the in-process "
                         "engine (e.g. 4 or tp=4; default FLAGS_serving_"
                         "mesh_tp).  CPU: export XLA_FLAGS=--xla_force_"
                         "host_platform_device_count=N first.  tp>1 "
                         "needs head counts divisible by tp — pass "
                         "--heads/--kv-heads accordingly")
    ap.add_argument("--heads", type=int, default=4,
                    help="attention heads of the bench model")
    ap.add_argument("--kv-heads", type=int, default=2,
                    help="KV heads of the bench model")
    ap.add_argument("--priority-mix", default="", metavar="SPEC",
                    help="per-request priority classes drawn from "
                         "weighted spec, e.g. hi:0.2,lo:0.8 "
                         "(hi/high=1, normal=0, lo/low=-1, or bare "
                         "ints); adds per-class p50/p99 TTFT/TPOT")
    ap.add_argument("--tenants", default="", metavar="SPEC",
                    help="per-request tenant labels drawn from a "
                         "weighted spec, e.g. teamA:0.5,teamB:0.3,"
                         "free:0.2; wires a usage meter into the "
                         "engine and prints the per-tenant cost table "
                         "(page-seconds ledger) with the conservation "
                         "check")
    ap.add_argument("--adapters", default="", metavar="SPEC",
                    help="per-request LoRA adapters drawn from a "
                         "weighted spec, e.g. sum:0.4,cls:0.3,none:0.3 "
                         "(none/- = dense); registers one random "
                         "rank=--lora-rank adapter per name and adds a "
                         "per-adapter p50/p99 TTFT/TPOT table "
                         "(in-process mode only)")
    ap.add_argument("--lora-rank", type=int, default=4,
                    help="rank of the random adapters --adapters "
                         "registers")
    ap.add_argument("--batch-file", default="", metavar="FILE",
                    help="drip-feed this JSONL file (one "
                         "{'prompt': [...]} record per line) as an "
                         "offline batch job on the lowest-priority "
                         "lane while the interactive workload runs; "
                         "reports the interactive-vs-batch goodput "
                         "split (in-process mode only)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="split admission prefill into chunks of this "
                         "many tokens, interleaved with decode steps "
                         "(0 = single-shot; default FLAGS_serving_"
                         "prefill_chunk)")
    ap.add_argument("--preempt",
                    action=argparse.BooleanOptionalAction, default=None,
                    help="priority preempt-and-swap: spill a lower-"
                         "priority resident's KV to host RAM to admit "
                         "a higher class (default FLAGS_serving_"
                         "preempt)")
    ap.add_argument("--quant", choices=("none", "int8", "int4"),
                    default="none",
                    help="weight-only quantized serving: convert the "
                         "checkpoint to int8 or int4 QuantizedWeight "
                         "shards at engine construction (embeddings/"
                         "norms/lm_head stay dense; default "
                         "FLAGS_serving_quant)")
    ap.add_argument("--kv-quant",
                    action=argparse.BooleanOptionalAction, default=None,
                    help="int8 KV pages: pools store int8 with per-"
                         "(page-row, head) f32 scales — quantize on "
                         "write, dequant fused into the attention "
                         "gather, spill/restore move the quantized "
                         "bytes (default FLAGS_serving_kv_quant)")
    ap.add_argument("--overload-baseline", action="store_true",
                    help="after the configured run, re-run the "
                         "identical workload on an FCFS engine "
                         "(prefill-chunk 0, no preemption) and print "
                         "a per-class tail-latency comparison "
                         "(in-process mode only)")
    ap.add_argument("--explain-tail", action="store_true",
                    help="wire a per-request lifecycle log into the "
                         "engine and print the critical-path "
                         "attribution of the p99-TTFT cohort plus the "
                         "run-wide per-cause totals and conservation "
                         "check (in-process mode only)")
    ap.add_argument("--record", default="", metavar="OUT.json",
                    help="write a machine-readable bench artifact "
                         "(tok/s, TTFT/TPOT p50/p95/p99, scenario "
                         "knobs, tail attribution) to this path")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chaos", type=int, default=None, metavar="SEED",
                    help="inject a seeded probabilistic fault plan "
                         "(poisoned steps, synthetic OOM, slow steps) "
                         "and drive through the self-healing "
                         "supervisor; reports availability and p99 "
                         "TTFT/TPOT under faults (in-process mode only)")
    ap.add_argument("--profile", default="", metavar="OUT.folded",
                    help="sample a phase-attributed host profile of "
                         "the run (observability.SamplingProfiler) and "
                         "write folded stacks to this path — feed to "
                         "flamegraph.pl / speedscope or "
                         "tools/profile_report.py (in-process mode "
                         "only)")
    return ap


def bench_args(**overrides) -> argparse.Namespace:
    """Default bench Namespace built from the REAL parser
    (``parse_args([])``), with keyword overrides by attribute name
    (``prefill_chunk=8``, not ``--prefill-chunk``).  Tests and
    embedders use this instead of hand-building a Namespace, so a
    newly added bench flag can never silently be missing (the PR 10 /
    PR 13 breakage class).  Unknown names raise."""
    args = _build_parser().parse_args([])
    for k, v in overrides.items():
        if not hasattr(args, k):
            raise TypeError(f"bench_args(): unknown bench arg {k!r}")
        setattr(args, k, v)
    return args


def main(argv=None):
    args = _build_parser().parse_args(argv)
    if args.http:
        res = run_http_bench(args)
    elif args.overload_baseline:
        res, _ = run_overload_compare(args)
    else:
        res = run_bench(args)
    if args.record:
        _write_record(args, res)
    return 0


if __name__ == "__main__":
    sys.exit(main())
