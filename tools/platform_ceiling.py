"""Platform-ceiling measurements — the re-runnable evidence behind
BASELINE.md's "ResNet/MoE are platform-shape-bound" claim (VERDICT r3
weak #2/#3: the claim must be driver-verifiable, not builder lore).

Measures with SELF-FEEDING timed chains (x_{t+1} = f(x_t)): plain
scan-delta chains whose iterations are bit-identical in bf16 read
impossible TF/s on this tunnel (verified: a@a chains at 2.7 PF/s), so
every probe feeds its output back into its input:

  * big/medium square matmuls — the chip's practical matmul ceiling;
  * the three conv shapes ResNet50 spends its time in;
  * raw-jax ResNet50 train step (BN on and off) — the framework-free
    ceiling the vision rung is judged against;
  * the MoE expert-FFN matmul at the bench rung's shapes.

Usage: python tools/platform_ceiling.py   # prints one JSON line each
"""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

def _emit(name, tfs, detail=None):
    print(json.dumps({"probe": name, "tflops": round(tfs, 2),
                      **(detail or {})}), flush=True)
    return tfs


def _chain_time(step, x0, iters=None, reps=3, target=0.6):
    """Self-feeding timed chain: x_{t+1} = step(x_t), so every
    iteration's INPUT BITS differ and neither XLA nor the tunnel relay
    can collapse repeats — the failure mode that makes plain scan-delta
    chains report impossible TF/s for big matmuls (the op_bench
    methodology note; verified on this tunnel: a@a chains read 2.7
    PF/s).  Returns seconds per step via a two-length delta so dispatch
    and fetch latency cancel."""
    import time

    def chain(n):
        @jax.jit
        def run(x):
            def body(x, _):
                return step(x), None
            x, _ = jax.lax.scan(body, x, None, length=n)
            # reduce over EVERY leaf: depending on one leaf lets XLA
            # dead-code the whole chain when that leaf happens to be a
            # fixed point (observed: summing an unused-BN param turned
            # the resnet probe into a no-op reading 115 PF/s)
            return sum(jnp.sum(l.astype(jnp.float32))
                       for l in jax.tree_util.tree_leaves(x))
        return run

    # every timed call gets FRESH input values: the relay memoizes
    # repeated (executable, buffers) dispatches (op_bench methodology
    # note) — 1% steps so the bf16 bits actually change
    def variant(i):
        return jax.tree_util.tree_map(
            lambda a: (a * (1 + (i + 1) * 0.01)).astype(a.dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, x0)

    variants = [variant(i) for i in range(2 * reps + 2)]
    jax.block_until_ready(variants)
    vi = iter(variants)

    probe = chain(8)
    float(probe(x0))
    t0 = time.perf_counter()
    float(probe(next(vi)))
    est = max((time.perf_counter() - t0) / 8, 1e-7)
    n2 = int(min(4000, max(24, target / est)))
    n1 = max(4, n2 // 4)
    r1, r2 = chain(n1), chain(n2)
    float(r1(x0))
    float(r2(x0))
    deltas = []
    for _ in range(reps):
        a1, a2 = next(vi), next(vi)
        t0 = time.perf_counter()
        float(r1(a1))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(r2(a2))
        t2 = time.perf_counter() - t0
        deltas.append((t2 - t1) / (n2 - n1))
    pos = sorted(d for d in deltas if d > 0)
    return pos[len(pos) // 2] if pos else float("inf")


def _renorm(y):
    """Keep a self-feeding chain's values ~unit-scale (and the bits
    changing) without meaningful cost next to the op under test."""
    yf = y.astype(jnp.float32)
    return (yf * jax.lax.rsqrt(jnp.mean(jnp.square(yf)) + 1e-6)).astype(
        y.dtype)


def matmul_ceilings():
    rs = np.random.RandomState(0)
    for n in (8192, 4096, 2048):
        a = jnp.asarray(rs.randn(n, n) * 0.1, jnp.bfloat16)
        dt = _chain_time(lambda x: _renorm(x @ x), a)
        _emit(f"matmul_{n}", 2 * n ** 3 / dt / 1e12)
    # the skinny-N shape decode lives in
    a = jnp.asarray(rs.randn(8, 4096) * 0.1, jnp.bfloat16)
    b = jnp.asarray(rs.randn(4096, 256) * 0.1, jnp.bfloat16)

    def skinny(x):
        y = x @ b                      # [8, 256]
        # fold the result back so the next input's bits change
        return _renorm(x + jnp.pad(y, ((0, 0), (0, 4096 - 256))))
    dt = _chain_time(skinny, a)
    _emit("matmul_skinny_8x4096x256", 2 * 8 * 4096 * 256 / dt / 1e12)


def conv_ceilings():
    rs = np.random.RandomState(1)
    shapes = [  # (N, H, W, C, k) — resnet50's hot trio (stride 1)
        (128, 56, 56, 64, 3),
        (128, 28, 28, 128, 3),
        (128, 14, 14, 256, 3),
    ]
    for (n, h, w, c, k) in shapes:
        x = jnp.asarray(rs.randn(n, h, w, c) * 0.1, jnp.bfloat16)
        kw = jnp.asarray(rs.randn(k, k, c, c) * 0.1, jnp.bfloat16)

        def f(x, kw=kw):
            dn = jax.lax.conv_dimension_numbers(
                x.shape, kw.shape, ("NHWC", "HWIO", "NHWC"))
            return _renorm(jax.lax.conv_general_dilated(
                x, kw, (1, 1), "SAME", dimension_numbers=dn))
        dt = _chain_time(f, x)
        flops = 2 * n * h * w * c * c * k * k
        _emit(f"conv{k}x{k}_{h}x{w}x{c}", flops / dt / 1e12)


# --------------------------- raw-jax resnet50 (framework-free ceiling)
_BLOCKS = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]


def _rn_params(key):
    p = {}
    ks = iter(jax.random.split(key, 256))

    def conv_w(ci, co, k):
        return jax.random.normal(next(ks), (k, k, ci, co)) \
            * (1.0 / np.sqrt(ci * k * k))

    p["stem"] = conv_w(3, 64, 7)
    p["stem_bn"] = (jnp.ones(64), jnp.zeros(64))
    cin = 64
    for bi, (cmid, n, stride) in enumerate(_BLOCKS):
        cout = cmid * 4
        for j in range(n):
            blk = {"w1": conv_w(cin, cmid, 1),
                   "bn1": (jnp.ones(cmid), jnp.zeros(cmid)),
                   "w2": conv_w(cmid, cmid, 3),
                   "bn2": (jnp.ones(cmid), jnp.zeros(cmid)),
                   "w3": conv_w(cmid, cout, 1),
                   "bn3": (jnp.ones(cout), jnp.zeros(cout))}
            if j == 0:
                blk["wd"] = conv_w(cin, cout, 1)
                blk["bnd"] = (jnp.ones(cout), jnp.zeros(cout))
            p[f"b{bi}_{j}"] = blk
            cin = cout
    p["fc"] = jax.random.normal(next(ks), (cin, 1000)) * 0.01
    return p


def _conv(x, w, s):
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
    k = w.shape[0]
    return jax.lax.conv_general_dilated(
        x.astype(jnp.bfloat16), w.astype(jnp.bfloat16), (s, s),
        [(k // 2, k // 2)] * 2, dimension_numbers=dn)


def _bn_relu(x, gb, with_bn):
    if not with_bn:
        return jax.nn.relu(x)
    g, b = gb
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=(0, 1, 2))
    v = jnp.maximum(jnp.mean(jnp.square(xf), axis=(0, 1, 2))
                    - m * m, 0.0)
    out = (xf - m) * jax.lax.rsqrt(v + 1e-5) * g + b
    return jax.nn.relu(out).astype(x.dtype)


def _rn_fwd(p, x, with_bn):
    x = _conv(x, p["stem"], 2)
    x = _bn_relu(x, p["stem_bn"], with_bn)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    cin = 64
    for bi, (cmid, n, stride) in enumerate(_BLOCKS):
        for j in range(n):
            s = stride if j == 0 else 1
            blk = p[f"b{bi}_{j}"]
            r = x
            y = _bn_relu(_conv(x, blk["w1"], s), blk["bn1"], with_bn)
            y = _bn_relu(_conv(y, blk["w2"], 1), blk["bn2"], with_bn)
            y = _conv(y, blk["w3"], 1)
            if j == 0:
                r = _conv(x, blk["wd"], s)
                if with_bn:
                    r = _bn_relu(r, blk["bnd"], True)
            x = jax.nn.relu(y + r)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return x @ p["fc"].astype(jnp.float32)


# ResNet50 fwd ~4.1 GFLOP/image at 224: train step ~3x
_RN_FLOPS_IMG = 4.1e9 * 3


def rawjax_resnet(with_bn):
    batch = 128
    p = _rn_params(jax.random.key(0))
    y = jnp.asarray(np.random.RandomState(0).randint(0, 1000, (batch,)))

    def loss(p, x):
        logits = _rn_fwd(p, x, with_bn)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - tgt)

    x = jnp.asarray(np.random.RandomState(1).rand(batch, 224, 224, 3),
                    jnp.bfloat16)

    # params MUTATE along the chain (real SGD), so iterations are never
    # bit-identical — the honest self-feeding form
    def step(p):
        g = jax.grad(loss)(p, x)
        return jax.tree_util.tree_map(lambda a, b: a - 1e-4 * b, p, g)

    dt = _chain_time(step, p, target=2.0)
    img_s = batch / dt
    peak = 197e12 if jax.devices()[0].platform == "tpu" else 1e12
    mfu = img_s * _RN_FLOPS_IMG / peak
    _emit(f"rawjax_resnet50_{'bn' if with_bn else 'nobn'}",
          img_s * _RN_FLOPS_IMG / 1e12,
          {"images_per_sec": round(img_s, 1), "mfu": round(mfu, 4),
           "batch": batch})


def moe_ffn_ceiling():
    """The grouped expert-FFN matmul at the MoE rung's shapes:
    [E, cap, H] x [E, H, I] einsum."""
    rs = np.random.RandomState(2)
    e, cap, h, i = 8, 2048, 1024, 1408
    x = jnp.asarray(rs.randn(e, cap, h) * 0.1, jnp.bfloat16)
    w1 = jnp.asarray(rs.randn(e, h, i) * 0.05, jnp.bfloat16)
    w2 = jnp.asarray(rs.randn(e, i, h) * 0.05, jnp.bfloat16)

    def f(x):
        u = jnp.einsum("ech,ehi->eci", x, w1)
        return _renorm(jnp.einsum("eci,eih->ech", jax.nn.silu(u), w2))
    dt = _chain_time(f, x)
    flops = 2 * e * cap * h * i * 2
    _emit("moe_expert_ffn", flops / dt / 1e12,
          {"experts": e, "capacity": cap})


def rawjax_moe_step():
    """End-to-end raw-jax MoE train-step ceiling at the bench rung's
    exact config (models/moe_llm.py IS raw jax; this probe additionally
    measures the NO-ROUTING bound — identical model with the top-2
    expert FFN applied densely — so the rung can be judged against both
    a same-program ceiling and the perfect-dispatch bound)."""
    import time

    from paddle_tpu.models import moe_llm as M

    cfg = M.MoEConfig(vocab_size=32000, hidden_size=1024,
                      moe_intermediate_size=1408, num_hidden_layers=8,
                      num_attention_heads=8, num_key_value_heads=8,
                      num_experts=8, top_k=2, dtype="bfloat16")
    batch, seq, steps = 16, 512, 10
    mesh = M.build_mesh(1, dp=1, ep=1)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq + 1)),
                      jnp.int64)

    def timed_step(step_fn):
        p = M.setup(cfg, mesh)
        loss, p = step_fn(p, ids)
        float(loss)
        for _ in range(2):
            loss, p = step_fn(p, ids)
        float(loss)
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss, p = step_fn(p, ids)
            float(loss)
            dt = (time.perf_counter() - t0) / steps
            best = dt if best is None else min(best, dt)
        return batch * seq / best

    tok_full = timed_step(M.build_train_step(cfg, mesh))

    # perfect-dispatch bound: same model, top-2-equivalent dense FFN
    from paddle_tpu.models.llama import _rope_tables as _rope
    from paddle_tpu.models.llama_hybrid import _rms, _chunked_ce_sum
    from paddle_tpu.models.llama import apply_rotary_pos_emb
    from paddle_tpu.ops.pallas.flash_attention import sdpa

    def loss_dense(p, ids):
        inp, lab = ids[:, :-1], ids[:, 1:]
        b, s = inp.shape
        x = jnp.take(p["embed"], inp, axis=0)
        cos, sin = _rope(s, cfg.head_dim, cfg.rope_theta)
        nh = kvh = cfg.num_attention_heads
        hd = cfg.head_dim
        for i in range(cfg.num_hidden_layers):
            lp = jax.tree_util.tree_map(lambda a: a[i], p["layers"])
            r = x
            h = _rms(x, lp["input_ln"], cfg.rms_norm_eps)
            wqkv = jnp.concatenate([lp["q"], lp["k"], lp["v"]], axis=1)
            qkv = h @ wqkv
            q = qkv[..., :nh * hd].reshape(b, s, nh, hd)
            k = qkv[..., nh * hd:2 * nh * hd].reshape(b, s, kvh, hd)
            v = qkv[..., 2 * nh * hd:].reshape(b, s, kvh, hd)
            q, k = apply_rotary_pos_emb(q, k, cos, sin)
            a = sdpa(q, k, v, is_causal=True)
            x = r + (a.reshape(b, s, nh * hd) @ lp["o"])
            r = x
            h = _rms(x, lp["post_ln"], cfg.rms_norm_eps)
            flat = h.reshape(b * s, cfg.hidden_size)
            y = jax.nn.silu(flat @ lp["w1"][0]) @ lp["w2"][0] \
                + jax.nn.silu(flat @ lp["w1"][1]) @ lp["w2"][1]
            x = r + y.reshape(b, s, cfg.hidden_size)
        h = _rms(x, p["norm"], cfg.rms_norm_eps)
        return _chunked_ce_sum(h, lab, p["head"]) / (b * s)

    def dense_step(p, ids):
        loss, grads = jax.value_and_grad(loss_dense)(p, ids)
        p = jax.tree_util.tree_map(
            lambda a, g: (a.astype(jnp.float32)
                          - 3e-4 * g.astype(jnp.float32)).astype(a.dtype),
            p, grads)
        return loss, p

    tok_dense = timed_step(jax.jit(dense_step, donate_argnums=(0,)))
    # throughput probe: its own key (NOT _emit's "tflops" field)
    print(json.dumps({
        "probe": "rawjax_moe_step", "ktok_per_sec":
        round(tok_full / 1e3, 1),
        "perfect_dispatch_ktok_s": round(tok_dense / 1e3, 1),
        "routing_overhead_frac": round(1 - tok_full / tok_dense, 4)}),
        flush=True)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": dev.device_kind,
                      "platform": dev.platform}), flush=True)
    matmul_ceilings()
    conv_ceilings()
    moe_ffn_ceiling()
    rawjax_resnet(with_bn=False)
    rawjax_resnet(with_bn=True)
    rawjax_moe_step()


if __name__ == "__main__":
    main()
