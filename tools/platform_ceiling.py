"""Platform-ceiling measurements — the re-runnable evidence behind
BASELINE.md's "ResNet/MoE are platform-shape-bound" claim (VERDICT r3
weak #2/#3: the claim must be driver-verifiable, not builder lore).

Measures with SELF-FEEDING timed chains (x_{t+1} = f(x_t)): plain
scan-delta chains whose iterations are bit-identical in bf16 read
impossible TF/s on this tunnel (verified: a@a chains at 2.7 PF/s), so
every probe feeds its output back into its input:

  * big/medium square matmuls — the chip's practical matmul ceiling;
  * the three conv shapes ResNet50 spends its time in;
  * raw-jax ResNet50 train step (BN on and off) — the framework-free
    ceiling the vision rung is judged against;
  * the MoE expert-FFN matmul at the bench rung's shapes.

Usage: python tools/platform_ceiling.py   # prints one JSON line each
"""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

def _emit(name, tfs, detail=None):
    print(json.dumps({"probe": name, "tflops": round(tfs, 2),
                      **(detail or {})}), flush=True)
    return tfs


def _chain_time(step, x0, iters=None, reps=3, target=0.6):
    """Self-feeding timed chain: x_{t+1} = step(x_t), so every
    iteration's INPUT BITS differ and neither XLA nor the tunnel relay
    can collapse repeats — the failure mode that makes plain scan-delta
    chains report impossible TF/s for big matmuls (the op_bench
    methodology note; verified on this tunnel: a@a chains read 2.7
    PF/s).  Returns seconds per step via a two-length delta so dispatch
    and fetch latency cancel."""
    import time

    def chain(n):
        @jax.jit
        def run(x):
            def body(x, _):
                return step(x), None
            x, _ = jax.lax.scan(body, x, None, length=n)
            # reduce over EVERY leaf: depending on one leaf lets XLA
            # dead-code the whole chain when that leaf happens to be a
            # fixed point (observed: summing an unused-BN param turned
            # the resnet probe into a no-op reading 115 PF/s)
            return sum(jnp.sum(l.astype(jnp.float32))
                       for l in jax.tree_util.tree_leaves(x))
        return run

    # every timed call gets FRESH input values: the relay memoizes
    # repeated (executable, buffers) dispatches (op_bench methodology
    # note) — 1% steps so the bf16 bits actually change
    def variant(i):
        return jax.tree_util.tree_map(
            lambda a: (a * (1 + (i + 1) * 0.01)).astype(a.dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, x0)

    variants = [variant(i) for i in range(2 * reps + 2)]
    jax.block_until_ready(variants)
    vi = iter(variants)

    probe = chain(8)
    float(probe(x0))
    t0 = time.perf_counter()
    float(probe(next(vi)))
    est = max((time.perf_counter() - t0) / 8, 1e-7)
    n2 = int(min(4000, max(24, target / est)))
    n1 = max(4, n2 // 4)
    r1, r2 = chain(n1), chain(n2)
    float(r1(x0))
    float(r2(x0))
    deltas = []
    for _ in range(reps):
        a1, a2 = next(vi), next(vi)
        t0 = time.perf_counter()
        float(r1(a1))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(r2(a2))
        t2 = time.perf_counter() - t0
        deltas.append((t2 - t1) / (n2 - n1))
    pos = sorted(d for d in deltas if d > 0)
    return pos[len(pos) // 2] if pos else float("inf")


def _renorm(y):
    """Keep a self-feeding chain's values ~unit-scale (and the bits
    changing) without meaningful cost next to the op under test."""
    yf = y.astype(jnp.float32)
    return (yf * jax.lax.rsqrt(jnp.mean(jnp.square(yf)) + 1e-6)).astype(
        y.dtype)


def matmul_ceilings():
    rs = np.random.RandomState(0)
    for n in (8192, 4096, 2048):
        a = jnp.asarray(rs.randn(n, n) * 0.1, jnp.bfloat16)
        dt = _chain_time(lambda x: _renorm(x @ x), a)
        _emit(f"matmul_{n}", 2 * n ** 3 / dt / 1e12)
    # the skinny-N shape decode lives in
    a = jnp.asarray(rs.randn(8, 4096) * 0.1, jnp.bfloat16)
    b = jnp.asarray(rs.randn(4096, 256) * 0.1, jnp.bfloat16)

    def skinny(x):
        y = x @ b                      # [8, 256]
        # fold the result back so the next input's bits change
        return _renorm(x + jnp.pad(y, ((0, 0), (0, 4096 - 256))))
    dt = _chain_time(skinny, a)
    _emit("matmul_skinny_8x4096x256", 2 * 8 * 4096 * 256 / dt / 1e12)


def conv_ceilings():
    rs = np.random.RandomState(1)
    shapes = [  # (N, H, W, C, k) — resnet50's hot trio (stride 1)
        (128, 56, 56, 64, 3),
        (128, 28, 28, 128, 3),
        (128, 14, 14, 256, 3),
    ]
    for (n, h, w, c, k) in shapes:
        x = jnp.asarray(rs.randn(n, h, w, c) * 0.1, jnp.bfloat16)
        kw = jnp.asarray(rs.randn(k, k, c, c) * 0.1, jnp.bfloat16)

        def f(x, kw=kw):
            dn = jax.lax.conv_dimension_numbers(
                x.shape, kw.shape, ("NHWC", "HWIO", "NHWC"))
            return _renorm(jax.lax.conv_general_dilated(
                x, kw, (1, 1), "SAME", dimension_numbers=dn))
        dt = _chain_time(f, x)
        flops = 2 * n * h * w * c * c * k * k
        _emit(f"conv{k}x{k}_{h}x{w}x{c}", flops / dt / 1e12)


# --------------------------- raw-jax resnet50 (framework-free ceiling)
_BLOCKS = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]


def _rn_params(key):
    p = {}
    ks = iter(jax.random.split(key, 256))

    def conv_w(ci, co, k):
        return jax.random.normal(next(ks), (k, k, ci, co)) \
            * (1.0 / np.sqrt(ci * k * k))

    p["stem"] = conv_w(3, 64, 7)
    p["stem_bn"] = (jnp.ones(64), jnp.zeros(64))
    cin = 64
    for bi, (cmid, n, stride) in enumerate(_BLOCKS):
        cout = cmid * 4
        for j in range(n):
            blk = {"w1": conv_w(cin, cmid, 1),
                   "bn1": (jnp.ones(cmid), jnp.zeros(cmid)),
                   "w2": conv_w(cmid, cmid, 3),
                   "bn2": (jnp.ones(cmid), jnp.zeros(cmid)),
                   "w3": conv_w(cmid, cout, 1),
                   "bn3": (jnp.ones(cout), jnp.zeros(cout))}
            if j == 0:
                blk["wd"] = conv_w(cin, cout, 1)
                blk["bnd"] = (jnp.ones(cout), jnp.zeros(cout))
            p[f"b{bi}_{j}"] = blk
            cin = cout
    p["fc"] = jax.random.normal(next(ks), (cin, 1000)) * 0.01
    return p


def _conv(x, w, s):
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
    k = w.shape[0]
    return jax.lax.conv_general_dilated(
        x.astype(jnp.bfloat16), w.astype(jnp.bfloat16), (s, s),
        [(k // 2, k // 2)] * 2, dimension_numbers=dn)


def _bn_relu(x, gb, with_bn):
    if not with_bn:
        return jax.nn.relu(x)
    g, b = gb
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=(0, 1, 2))
    v = jnp.maximum(jnp.mean(jnp.square(xf), axis=(0, 1, 2))
                    - m * m, 0.0)
    out = (xf - m) * jax.lax.rsqrt(v + 1e-5) * g + b
    return jax.nn.relu(out).astype(x.dtype)


def _rn_fwd(p, x, with_bn):
    x = _conv(x, p["stem"], 2)
    x = _bn_relu(x, p["stem_bn"], with_bn)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    cin = 64
    for bi, (cmid, n, stride) in enumerate(_BLOCKS):
        for j in range(n):
            s = stride if j == 0 else 1
            blk = p[f"b{bi}_{j}"]
            r = x
            y = _bn_relu(_conv(x, blk["w1"], s), blk["bn1"], with_bn)
            y = _bn_relu(_conv(y, blk["w2"], 1), blk["bn2"], with_bn)
            y = _conv(y, blk["w3"], 1)
            if j == 0:
                r = _conv(x, blk["wd"], s)
                if with_bn:
                    r = _bn_relu(r, blk["bnd"], True)
            x = jax.nn.relu(y + r)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return x @ p["fc"].astype(jnp.float32)


# ResNet50 fwd ~4.1 GFLOP/image at 224: train step ~3x
_RN_FLOPS_IMG = 4.1e9 * 3


def rawjax_resnet(with_bn):
    batch = 128
    p = _rn_params(jax.random.key(0))
    y = jnp.asarray(np.random.RandomState(0).randint(0, 1000, (batch,)))

    def loss(p, x):
        logits = _rn_fwd(p, x, with_bn)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - tgt)

    x = jnp.asarray(np.random.RandomState(1).rand(batch, 224, 224, 3),
                    jnp.bfloat16)

    # params MUTATE along the chain (real SGD), so iterations are never
    # bit-identical — the honest self-feeding form
    def step(p):
        g = jax.grad(loss)(p, x)
        return jax.tree_util.tree_map(lambda a, b: a - 1e-4 * b, p, g)

    dt = _chain_time(step, p, target=2.0)
    img_s = batch / dt
    peak = 197e12 if jax.devices()[0].platform == "tpu" else 1e12
    mfu = img_s * _RN_FLOPS_IMG / peak
    _emit(f"rawjax_resnet50_{'bn' if with_bn else 'nobn'}",
          img_s * _RN_FLOPS_IMG / 1e12,
          {"images_per_sec": round(img_s, 1), "mfu": round(mfu, 4),
           "batch": batch})


def moe_ffn_ceiling():
    """The grouped expert-FFN matmul at the MoE rung's shapes:
    [E, cap, H] x [E, H, I] einsum."""
    rs = np.random.RandomState(2)
    e, cap, h, i = 8, 2048, 1024, 1408
    x = jnp.asarray(rs.randn(e, cap, h) * 0.1, jnp.bfloat16)
    w1 = jnp.asarray(rs.randn(e, h, i) * 0.05, jnp.bfloat16)
    w2 = jnp.asarray(rs.randn(e, i, h) * 0.05, jnp.bfloat16)

    def f(x):
        u = jnp.einsum("ech,ehi->eci", x, w1)
        return _renorm(jnp.einsum("eci,eih->ech", jax.nn.silu(u), w2))
    dt = _chain_time(f, x)
    flops = 2 * e * cap * h * i * 2
    _emit("moe_expert_ffn", flops / dt / 1e12,
          {"experts": e, "capacity": cap})


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": dev.device_kind,
                      "platform": dev.platform}), flush=True)
    matmul_ceilings()
    conv_ceilings()
    moe_ffn_ceiling()
    rawjax_resnet(with_bn=False)
    rawjax_resnet(with_bn=True)


if __name__ == "__main__":
    main()
