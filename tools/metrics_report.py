#!/usr/bin/env python
"""Pretty-print an observability dump (observability.dump() output).

Usage:
    python tools/metrics_report.py <dump-dir | metrics.json> [--prom]

Reads metrics.json (+ retraces.json / trace.json / flight.json /
resources.json / profile.json / captures.json / usage.json /
quant.json / lora.json / exemplars.json when present) from the dump
directory FLAGS_metrics_dir pointed at, and renders counters, gauges,
histograms, SLO verdicts, fault-tolerance events, finish reasons, the
span-trace summary, the sampling-profiler + diagnostic-capture
summary, the per-tenant usage ledger, the multi-LoRA adapter census +
offline batch lane, the tail-latency attribution table + worst
SLO-violation exemplars, and the retrace log as aligned tables.
--prom cats the raw Prometheus text instead (what a scraper would
see).

Every section is optional: a dump produced by an older build (no SLO
counters, no trace.json) renders the sections it has and silently
skips the rest — this tool must never crash on a missing key.

Works standalone — no paddle_tpu / jax import, so it can run against a
dump copied off a training host.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _read_json(path):
    """Side-file loader: missing or corrupt files (older dumps, partial
    writes) degrade to None instead of killing the report."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _load(path):
    dir_ = path if os.path.isdir(path) else os.path.dirname(path)
    json_path = (os.path.join(path, "metrics.json")
                 if os.path.isdir(path) else path)
    prom_path = os.path.join(dir_, "metrics.prom")
    if not os.path.exists(json_path):
        sys.exit(f"metrics_report: no metrics.json at {json_path!r} "
                 f"(set FLAGS_metrics_dir and rerun, or pass the dump dir)")
    with open(json_path) as f:
        metrics = json.load(f)
    retraces = _read_json(os.path.join(dir_, "retraces.json"))
    trace = _read_json(os.path.join(dir_, "trace.json"))
    flight = _read_json(os.path.join(dir_, "flight.json"))
    resources = _read_json(os.path.join(dir_, "resources.json"))
    profile = _read_json(os.path.join(dir_, "profile.json"))
    captures = _read_json(os.path.join(dir_, "captures.json"))
    usage = _read_json(os.path.join(dir_, "usage.json"))
    quant = _read_json(os.path.join(dir_, "quant.json"))
    lora = _read_json(os.path.join(dir_, "lora.json"))
    exemplars = _read_json(os.path.join(dir_, "exemplars.json"))
    return (metrics, retraces, trace, flight, resources, profile,
            captures, usage, quant, lora, exemplars, prom_path)


def _fmt_value(v):
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}" if isinstance(v, float) else str(v)


def _fmt_labels(labels):
    return ",".join(f"{k}={v}" for k, v in labels.items()) if labels else "-"


def _table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    def line(r):
        return "  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
    out = [line(headers), line(["-" * w for w in widths])]
    out += [line(r) for r in rows]
    return "\n".join(out)


def _histogram_block(name, entry):
    lines = [f"histogram {name}"]
    for s in entry["series"]:
        lbl = _fmt_labels(s.get("labels", {}))
        count, total = s.get("count", 0), s.get("sum", 0.0)
        avg = total / count if count else 0.0
        lines.append(f"  [{lbl}] count={count} sum={total:.6g} "
                     f"avg={avg:.6g}")
        prev = 0
        for le, c in s.get("buckets", []):
            if c == prev:
                continue        # only show populated buckets
            le_s = "+Inf" if le == "+Inf" else f"{le:g}"
            bar = "#" * min(40, int(40 * (c - prev) / max(1, count)))
            lines.append(f"    le={le_s:>8}: {c - prev:>8}  {bar}")
            prev = c
    return "\n".join(lines)


def _load_quantiles():
    """Shared bucket-quantile estimator
    (paddle_tpu/observability/quantiles.py) loaded by file path — the
    module is deliberately import-free so this tool keeps its
    no-paddle_tpu/no-jax contract.  None when the tool was copied off
    the repo without it (older dumps still render; see _hist_stats)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "paddle_tpu", "observability",
                        "quantiles.py")
    try:
        spec = importlib.util.spec_from_file_location("_pt_quantiles",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


_QUANTILES = _load_quantiles()


def _hist_stats(entry):
    """(count, sum, avg, approx-p50, approx-p99) over all series of a
    histogram entry — percentile = upper edge of the cumulative bucket
    that crosses the rank (what a Prometheus quantile would report).
    Delegates to the shared quantiles helper when available."""
    series = entry.get("series", [])
    if _QUANTILES is not None:
        buckets, count, total = _QUANTILES.merge_series_buckets(series)
        if not count:
            return 0, 0.0, 0.0, None, None
        return (count, total, total / count,
                _QUANTILES.quantile_from_buckets(buckets, count, 0.5),
                _QUANTILES.quantile_from_buckets(buckets, count, 0.99))
    # standalone fallback: same arithmetic, no file dependency
    buckets: dict = {}
    count, total = 0, 0.0
    for s in series:
        count += s.get("count", 0)
        total += s.get("sum", 0.0)
        prev = 0
        for le, c in s.get("buckets", []):
            buckets[le] = buckets.get(le, 0) + (c - prev)
            prev = c
    if not count:
        return 0, 0.0, 0.0, None, None

    def pct(q):
        rank, acc = q * count, 0
        for le, c in sorted(buckets.items(),
                            key=lambda kv: float("inf")
                            if kv[0] == "+Inf" else kv[0]):
            acc += c
            if acc >= rank:
                return le
        return "+Inf"

    return count, total, total / count, pct(0.5), pct(0.99)


def _serving_section(metrics):
    """Serving-engine summary: TTFT/TPOT latency lines + the throughput
    and pressure counters the engine exports (serving_* namespace)."""
    if not any(k.startswith("serving_") for k in metrics):
        return None
    lines = ["Serving"]
    for name, title in (("serving_ttft_seconds", "TTFT"),
                        ("serving_tpot_seconds", "TPOT"),
                        ("serving_e2e_seconds", "E2E")):
        if name not in metrics:
            continue
        count, _, avg, p50, p99 = _hist_stats(metrics[name])
        if not count:
            lines.append(f"  {title:<5} no samples")
            continue
        fmt = lambda v: "+Inf" if v == "+Inf" else f"{float(v) * 1e3:g}ms"
        lines.append(f"  {title:<5} n={count} avg={avg * 1e3:.3g}ms "
                     f"p50<={fmt(p50)} p99<={fmt(p99)}")
    rows = []
    for name in ("serving_tokens_total", "serving_decode_steps_total",
                 "serving_admissions_total", "serving_evictions_total",
                 "serving_backpressure_total", "serving_requests_total",
                 "serving_decode_step_traces_total",
                 "serving_host_syncs_total",
                 "serving_prefix_cache_pages_total",
                 "serving_prefix_cached_tokens_total",
                 "serving_prefix_cache_evictions_total",
                 "serving_prefix_cache_cow_total",
                 "serving_prefix_cached_pages",
                 "serving_queue_depth", "serving_active_slots",
                 "serving_pages_in_use", "serving_pages_total"):
        entry = metrics.get(name)
        if not entry or entry.get("type") == "histogram":
            continue
        for s in entry.get("series", []):
            rows.append((name, _fmt_labels(s.get("labels", {})),
                         _fmt_value(s.get("value", 0))))
    if rows:
        lines.append(_table(rows, ("name", "labels", "value")))
    prefix = metrics.get("serving_prefix_cache_pages_total")
    if prefix:
        hits = misses = 0
        for s in prefix.get("series", []):
            if s.get("labels", {}).get("result") == "hit":
                hits += s.get("value", 0)
            else:
                misses += s.get("value", 0)
        if hits + misses:
            lines.append(
                f"  prefix-cache page hit rate: "
                f"{100.0 * hits / (hits + misses):.1f}% "
                f"({_fmt_value(hits)}/{_fmt_value(hits + misses)} "
                f"full-chunk lookups)")
    syncs = metrics.get("serving_host_syncs_total")
    steps = metrics.get("serving_decode_steps_total")
    if syncs and steps:
        ring = sum(s.get("value", 0) for s in syncs.get("series", [])
                   if s.get("labels", {}).get("kind") == "ring")
        n_steps = sum(s.get("value", 0)
                      for s in steps.get("series", []))
        if ring and n_steps:
            lines.append(f"  host syncs: {_fmt_value(ring)} ring fetches "
                         f"over {_fmt_value(n_steps)} decode steps "
                         f"({n_steps / ring:.1f} steps/sync)")
    back = metrics.get("serving_backpressure_total")
    if back:
        events = sum(s.get("value", 0) for s in back.get("series", []))
        if events:
            lines.append(f"  backpressure events: {_fmt_value(events)} "
                         f"(queue blocked on pages/slots)")
    return "\n".join(lines)


def _spec_section(metrics):
    """Speculative-decoding summary (serving_spec_* namespace): draft
    outcomes, acceptance rate, and the tokens-committed-per-verify-step
    distribution.  Dumps from builds without speculation (or runs with
    spec_k=0) have none of these keys and produce no section."""
    if not any(k.startswith("serving_spec_") for k in metrics):
        return None
    lines = ["Speculative decoding"]
    by_result = {}
    for s in (metrics.get("serving_spec_tokens_total") or {}).get(
            "series", []):
        by_result[s.get("labels", {}).get("result", "?")] = \
            s.get("value", 0)
    proposed = by_result.get("proposed", 0)
    if proposed:
        lines.append(
            f"  drafts: {_fmt_value(by_result.get('accepted', 0))} "
            f"accepted / {_fmt_value(by_result.get('rejected', 0))} "
            f"rejected of {_fmt_value(proposed)} proposed "
            f"({100.0 * by_result.get('accepted', 0) / proposed:.1f}% "
            f"acceptance)")
    steps = sum(s.get("value", 0)
                for s in (metrics.get("serving_spec_verify_steps_total")
                          or {}).get("series", []))
    per_step = metrics.get("serving_spec_tokens_per_step")
    if per_step:
        count, total, avg, p50, _ = _hist_stats(per_step)
        if count:
            lines.append(
                f"  verify steps: {_fmt_value(steps)} device steps, "
                f"{_fmt_value(total)} tokens committed "
                f"({avg:.2f} tokens/step, p50<={_fmt_value(p50)})")
    traces = sum(s.get("value", 0)
                 for s in (metrics.get("serving_spec_verify_traces_total")
                           or {}).get("series", []))
    if traces:
        lines.append(f"  verify program traces: {_fmt_value(traces)} "
                     f"(the no-retrace contract wants exactly 1 per "
                     f"engine)")
    return "\n".join(lines) if len(lines) > 1 else None


def _http_section(metrics):
    """HTTP front-end + router summary (serving_http_* / router_*):
    request rate by route/status, rejects (429/503), stream cancels,
    per-replica routing outcomes and circuit state."""
    if not any(k.startswith(("serving_http_", "router_"))
               for k in metrics):
        return None
    lines = ["HTTP / router"]
    lat = metrics.get("serving_http_request_seconds")
    if lat:
        count, _, avg, p50, p99 = _hist_stats(lat)
        if count:
            fmt = lambda v: "+Inf" if v == "+Inf" \
                else f"{float(v) * 1e3:g}ms"
            lines.append(f"  request latency n={count} "
                         f"avg={avg * 1e3:.3g}ms "
                         f"p50<={fmt(p50)} p99<={fmt(p99)}")
    rows = []
    for name in ("serving_http_requests_total",
                 "serving_http_rejections_total",
                 "serving_http_stream_cancels_total",
                 "serving_http_inflight",
                 "router_requests_total", "router_retries_total",
                 "router_picks_total", "router_probes_total",
                 "router_replica_up"):
        entry = metrics.get(name)
        if not entry or entry.get("type") == "histogram":
            continue
        for s in entry.get("series", []):
            rows.append((name, _fmt_labels(s.get("labels", {})),
                         _fmt_value(s.get("value", 0))))
    if rows:
        lines.append(_table(rows, ("name", "labels", "value")))
    rej = metrics.get("serving_http_rejections_total")
    if rej:
        total = {s.get("labels", {}).get("reason", "?"):
                 s.get("value", 0) for s in rej.get("series", [])}
        if total:
            lines.append("  rejections: " + ", ".join(
                f"{k}={_fmt_value(v)}"
                for k, v in sorted(total.items()))
                + "  (backpressure→429, draining→503, invalid→400)")
    up = metrics.get("router_replica_up")
    if up:
        n_up = sum(1 for s in up.get("series", [])
                   if s.get("value", 0) >= 1)
        n_all = len(up.get("series", []))
        lines.append(f"  replicas in rotation: {n_up}/{n_all}")
    picks = metrics.get("router_picks_total")
    if picks:
        by_kind = {s.get("labels", {}).get("kind", "?"):
                   s.get("value", 0) for s in picks.get("series", [])}
        total = sum(by_kind.values())
        if total:
            aff = by_kind.get("affinity", 0)
            lines.append(f"  affinity routing: "
                         f"{100.0 * aff / total:.1f}% of picks "
                         f"({_fmt_value(aff)}/{_fmt_value(total)}) hit "
                         f"the prefix-hash target")
    return "\n".join(lines)


def _faults_section(metrics):
    """Fault-tolerance summary (chaos harness + self-healing +
    router failover): fault injections by site, recovery events by
    kind, quarantined requests, mid-stream failovers.  Dumps from
    builds without the fault layer have none of these keys and
    produce no section."""
    injected = metrics.get("serving_fault_injected_total")
    recovery = metrics.get("serving_recovery_total")
    failovers = metrics.get("router_failovers_total")
    if not (injected or recovery or failovers):
        return None
    lines = ["Fault tolerance"]
    rows = []
    for s in (injected or {}).get("series", []):
        rows.append(("serving_fault_injected_total",
                     _fmt_labels(s.get("labels", {})),
                     _fmt_value(s.get("value", 0))))
    by_kind: dict = {}
    for s in (recovery or {}).get("series", []):
        kind = s.get("labels", {}).get("kind", "?")
        by_kind[kind] = by_kind.get(kind, 0) + s.get("value", 0)
        rows.append(("serving_recovery_total",
                     _fmt_labels(s.get("labels", {})),
                     _fmt_value(s.get("value", 0))))
    n_failovers = sum(s.get("value", 0)
                      for s in (failovers or {}).get("series", []))
    if failovers:
        rows.append(("router_failovers_total", "-",
                     _fmt_value(n_failovers)))
    if rows:
        lines.append(_table(rows, ("name", "labels", "value")))
    total_inj = sum(s.get("value", 0)
                    for s in (injected or {}).get("series", []))
    summary = []
    if total_inj:
        summary.append(f"{_fmt_value(total_inj)} faults injected")
    if by_kind:
        summary.append(f"{_fmt_value(sum(by_kind.values()))} recoveries")
    if by_kind.get("quarantine"):
        summary.append(f"{_fmt_value(by_kind['quarantine'])} requests "
                       f"quarantined")
    if n_failovers:
        summary.append(f"{_fmt_value(n_failovers)} mid-stream "
                       f"failovers")
    if summary:
        lines.append("  " + ", ".join(summary))
    return "\n".join(lines) if len(lines) > 1 else None


def _scheduling_section(metrics):
    """Overload-handling summary: chunked prefill, priority
    preempt-and-swap (host KV spill tier), and SLO shedding by class.
    Dumps from builds without the overload layer have none of these
    keys and produce no section."""
    names = ("serving_prefill_chunks_total", "serving_preemptions_total",
             "serving_spilled_pages_total", "serving_restored_pages_total",
             "serving_slo_shed_total")
    if not any(n in metrics for n in names):
        return None

    def total(name):
        return sum(s.get("value", 0)
                   for s in (metrics.get(name) or {}).get("series", []))

    lines = ["Scheduling / overload"]
    chunks = total("serving_prefill_chunks_total")
    if chunks:
        lines.append(f"  chunked prefill: {_fmt_value(chunks)} chunks "
                     f"interleaved with decode")
    preempts = total("serving_preemptions_total")
    spilled = total("serving_spilled_pages_total")
    restored = total("serving_restored_pages_total")
    if preempts or spilled:
        line = f"  preemptions: {_fmt_value(preempts)}"
        if spilled:
            line += (f", {_fmt_value(spilled)} pages spilled to host / "
                     f"{_fmt_value(restored)} restored "
                     f"({_fmt_value(total('serving_spill_bytes_total'))} bytes)")
        parked = total("serving_host_spill_pages")
        if parked:
            line += f", {_fmt_value(parked)} still parked"
        lines.append(line)
    shed = metrics.get("serving_slo_shed_total")
    if shed:
        by_cls = {s.get("labels", {}).get("class", "?"): s.get("value", 0)
                  for s in shed.get("series", [])}
        if any(by_cls.values()):
            lines.append("  shed (429) by class: " + ", ".join(
                f"{k}={_fmt_value(v)}" for k, v in sorted(
                    by_cls.items())))
    return "\n".join(lines) if len(lines) > 1 else None


def _slo_section(metrics):
    """SLO verdicts (serving_slo_requests_total / serving_slo_burn_rate)
    + finish reasons (serving_finish_total) + watchdog stalls."""
    names = ("serving_slo_requests_total", "serving_slo_burn_rate",
             "serving_finish_total", "serving_watchdog_stalls_total")
    if not any(n in metrics for n in names):
        return None
    lines = ["SLO / request outcomes"]
    slo = metrics.get("serving_slo_requests_total")
    if slo:
        per_dim: dict = {}
        for s in slo.get("series", []):
            lbl = s.get("labels", {})
            dim = lbl.get("dimension", "?")
            good, bad = per_dim.setdefault(dim, [0, 0])
            if lbl.get("result") == "good":
                good += s.get("value", 0)
            else:
                bad += s.get("value", 0)
            per_dim[dim] = [good, bad]
        burn = {}
        for s in (metrics.get("serving_slo_burn_rate") or {}).get(
                "series", []):
            burn[s.get("labels", {}).get("dimension", "?")] = \
                s.get("value", 0.0)
        for dim in sorted(per_dim):
            good, bad = per_dim[dim]
            total = good + bad
            if not total:
                continue
            line = (f"  {dim:<5} {_fmt_value(good)}/{_fmt_value(total)} "
                    f"good ({100.0 * good / total:.1f}%)")
            if dim in burn:
                line += f"  burn-rate {burn[dim]:.3g}"
            lines.append(line + ("  << violating" if bad else ""))
    finish = metrics.get("serving_finish_total")
    if finish:
        by_reason = {s.get("labels", {}).get("reason", "?"):
                     s.get("value", 0)
                     for s in finish.get("series", [])}
        if by_reason:
            lines.append("  finish reasons: " + ", ".join(
                f"{k}={_fmt_value(v)}" for k, v in sorted(
                    by_reason.items())))
    stalls = metrics.get("serving_watchdog_stalls_total")
    if stalls:
        n = sum(s.get("value", 0) for s in stalls.get("series", []))
        if n:
            lines.append(f"  watchdog stalls: {_fmt_value(n)} "
                         f"(see watchdog_*.json hang dumps)")
    return "\n".join(lines) if len(lines) > 1 else None


def _tracing_section(trace, flight):
    """Span-ring + flight-recorder summary from trace.json /
    flight.json — absent files (older dumps) produce no section."""
    lines = []
    if isinstance(trace, dict) and trace.get("spans"):
        spans = [s for s in trace["spans"] if isinstance(s, dict)]
        by_name: dict = {}
        traces = set()
        for s in spans:
            n, d = by_name.setdefault(s.get("name", "?"), [0, 0.0])
            by_name[s.get("name", "?")] = [n + 1,
                                           d + (s.get("duration_s") or 0.0)]
            if s.get("trace_id"):
                traces.add(s["trace_id"])
        lines.append(f"  {len(spans)} spans across {len(traces)} traces "
                     f"(recorded={trace.get('recorded', len(spans))} "
                     f"dropped={trace.get('dropped', 0)})")
        rows = [(name, n, f"{1e3 * d / n:.3g}ms")
                for name, (n, d) in sorted(by_name.items())]
        lines.append(_table(rows, ("span", "count", "avg")))
    if isinstance(flight, dict) and flight.get("events"):
        evs = [e for e in flight["events"] if isinstance(e, dict)]
        by_cat: dict = {}
        for e in evs:
            key = f"{e.get('category', '?')}.{e.get('event', '?')}"
            by_cat[key] = by_cat.get(key, 0) + 1
        lines.append(f"  flight ring: {len(evs)} events "
                     f"(capacity {flight.get('capacity', '?')}): " +
                     ", ".join(f"{k}={v}"
                               for k, v in sorted(by_cat.items())))
    if not lines:
        return None
    return "\n".join(["Tracing"] + lines)


def _fmt_bytes(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.4g}{unit}"
        n /= 1024
    return f"{n:.4g}TiB"


def _resources_section(resources):
    """Resource-observatory summary from resources.json (HBM peak,
    pool census + fragmentation, compile seconds by jit, goodput,
    tokens/s + MFU) — older dumps without the file produce no section,
    and partial payloads render what they have."""
    if not isinstance(resources, dict):
        return None
    lines = ["Resources"]
    mem = resources.get("memory") or {}
    for dev, entry in sorted((mem.get("devices") or {}).items()):
        if not isinstance(entry, dict):
            continue
        parts = []
        if "bytes_in_use" in entry:
            parts.append(f"in-use {_fmt_bytes(entry['bytes_in_use'])}")
        if "peak_bytes_in_use" in entry:
            parts.append(f"peak {_fmt_bytes(entry['peak_bytes_in_use'])}")
        if isinstance(entry.get("mesh"), dict):
            # mesh position from the serving runner — present even on
            # backends (CPU) that export no memory_stats, so every mesh
            # device shows a per-device line
            parts.append("mesh " + ",".join(
                f"{axis}={pos}"
                for axis, pos in sorted(entry["mesh"].items())))
        if parts:
            lines.append(f"  {dev}: " + ", ".join(parts))
    if mem.get("host_rss_bytes"):
        lines.append(f"  host RSS: {_fmt_bytes(mem['host_rss_bytes'])} "
                     f"({mem.get('samples', 0)} samples)")
    pool = resources.get("pool") or {}
    if pool.get("total"):
        lines.append(
            f"  KV pool: {_fmt_value(pool.get('in_use', 0))} in use / "
            f"{_fmt_value(pool.get('cached', 0))} cached / "
            f"{_fmt_value(pool.get('free', 0))} free of "
            f"{_fmt_value(pool['total'])} pages, fragmentation "
            f"{100.0 * float(pool.get('fragmentation_ratio') or 0):.1f}%")
    comp = resources.get("compiles") or {}
    jits = comp.get("jits") or {}
    if jits:
        rows = [(name, e.get("count", 0), f"{e.get('seconds', 0):.3g}s")
                for name, e in sorted(
                    jits.items(),
                    key=lambda kv: -(kv[1].get("seconds") or 0))
                if isinstance(e, dict)]
        lines.append(f"  {comp.get('total_compiles', len(rows))} jit "
                     f"compiles, {comp.get('total_seconds', 0):.3g}s "
                     "estimated (first-call timings)")
        lines.append(_table(rows, ("jit", "compiles", "seconds")))
    eager = comp.get("eager_by_op") or {}
    storms = {k: v for k, v in eager.items() if v > 3}
    if storms:
        lines.append("  eager retrace storms: " + ", ".join(
            f"{k}={v}" for k, v in sorted(storms.items())))
    good = resources.get("goodput") or {}
    if good.get("ratio") is not None:
        useful = good.get("useful_tokens", 0)
        wasted = good.get("wasted_tokens", 0)
        lines.append(
            f"  goodput: {100.0 * float(good['ratio']):.1f}% "
            f"({_fmt_value(useful)} useful / {_fmt_value(wasted)} "
            "wasted tokens)")
        finishes = good.get("finishes") or {}
        if finishes:
            lines.append("  finishes: " + ", ".join(
                f"{k}={_fmt_value(v)}"
                for k, v in sorted(finishes.items())))
    tput = resources.get("throughput") or {}
    if tput.get("tokens"):
        line = (f"  throughput: {_fmt_value(tput['tokens'])} tokens, "
                f"{tput.get('tokens_per_s', 0):.4g} tok/s")
        if tput.get("mfu") is not None:
            line += (f", MFU {100.0 * float(tput['mfu']):.2f}% "
                     f"({tput.get('device_kind', '?')})")
        lines.append(line)
    return "\n".join(lines) if len(lines) > 1 else None


def _profiling_section(profile, captures, metrics):
    """Sampling-profiler + diagnostic-capture summary from
    profile.json / captures.json (with obs_captures_total from
    metrics.json as a fallback when the side-files are absent) —
    dumps that predate the profiling subsystem have none of these
    keys and produce no section."""
    lines = ["Profiling"]
    if isinstance(profile, dict):
        stats = profile.get("stats") or {}
        if stats:
            lines.append(
                f"  sampler: {_fmt_value(stats.get('samples', 0))} "
                f"sweeps, {_fmt_value(stats.get('observations', 0))} "
                f"stack observations, "
                f"{_fmt_value(stats.get('distinct_stacks', 0))} "
                f"distinct stacks, "
                f"{_fmt_value(stats.get('dropped', 0))} dropped "
                f"(interval {float(stats.get('interval_s') or 0):g}s)")
        by_phase = profile.get("by_phase") or {}
        if by_phase:
            total = sum(by_phase.values()) or 1
            lines.append("  samples by phase: " + ", ".join(
                f"{ph}={_fmt_value(n)} ({100.0 * n / total:.0f}%)"
                for ph, n in sorted(by_phase.items(),
                                    key=lambda kv: -kv[1])))
        tops = profile.get("top_stacks") or []
        if tops:
            leaves = {}
            for ent in tops:
                if not isinstance(ent, dict):
                    continue
                stack = ent.get("stack") or []
                leaf = stack[-1] if stack else "(no frames)"
                leaves[leaf] = (leaves.get(leaf, 0)
                                + int(ent.get("count") or 0))
            hot = sorted(leaves.items(), key=lambda kv: -kv[1])[:5]
            lines.append("  hottest frames (self time): " + ", ".join(
                f"{f}={n}" for f, n in hot))
    by_rule = None
    if isinstance(captures, dict):
        lines.append(
            f"  captures: {_fmt_value(captures.get('captures', 0))} "
            f"written, {_fmt_value(captures.get('rate_limited', 0))} "
            f"rate-limited (min interval "
            f"{float(captures.get('min_interval_s') or 0):g}s, keep "
            f"{_fmt_value(captures.get('max_captures', 0))}, dir "
            f"{captures.get('dir') or '-'})")
        by_rule = captures.get("by_rule") or None
        for b in captures.get("retained") or []:
            if isinstance(b, dict):
                lines.append(
                    f"    capture_{b.get('capture', '?')}: rule "
                    f"{b.get('rule', '?')} -> "
                    f"{b.get('path') or '(memory only)'}")
    if by_rule is None:
        # older in-memory-only path: fall back to the counter family
        by_rule = {}
        entry = (metrics or {}).get("obs_captures_total") or {}
        for s in entry.get("series", []):
            rule = (s.get("labels") or {}).get("rule", "-")
            by_rule[rule] = by_rule.get(rule, 0) + int(
                s.get("value") or 0)
    if by_rule:
        lines.append("  captures by rule: " + ", ".join(
            f"{k}={_fmt_value(v)}" for k, v in sorted(by_rule.items())))
    return "\n".join(lines) if len(lines) > 1 else None


def _usage_section(usage):
    """Per-tenant cost table from usage.json (page-seconds ledger) —
    dumps produced without a usage meter (or by older builds) have no
    file and produce no section.  Rows sort by total page-second bill
    (device + host) so the heaviest tenant — the fair-share target —
    is the first line."""
    if not isinstance(usage, dict):
        return None
    tenants = usage.get("tenants") or {}
    if not tenants:
        return None
    lines = ["Usage / tenants"]

    def bill(kv):
        row = kv[1]
        return -(float(row.get("page_seconds") or 0)
                 + float(row.get("host_page_seconds") or 0))

    rows = []
    for name, row in sorted(tenants.items(), key=bill):
        finished = row.get("finished", 0)
        good = row.get("goodput_requests", 0)
        rows.append((
            name,
            _fmt_value(row.get("requests", 0)),
            f"{100.0 * good / finished:.0f}%" if finished else "-",
            _fmt_value(row.get("prefill_computed_tokens", 0)),
            _fmt_value(row.get("prefill_cached_tokens", 0)),
            _fmt_value(row.get("decode_tokens", 0)),
            f"{float(row.get('page_seconds') or 0):.4g}",
            f"{float(row.get('host_page_seconds') or 0):.4g}",
            f"{float(row.get('queue_seconds') or 0):.4g}",
            _fmt_value(row.get("preemptions", 0)),
            _fmt_value(row.get("shed", 0)),
        ))
    lines.append(_table(rows, ("tenant", "reqs", "good", "computed",
                               "cached", "decode", "page-s", "host-s",
                               "queue-s", "preempt", "shed")))
    computed = sum(r.get("prefill_computed_tokens", 0)
                   for r in tenants.values())
    cached = sum(r.get("prefill_cached_tokens", 0)
                 for r in tenants.values())
    if computed + cached:
        lines.append(
            f"  prefill cache savings: {_fmt_value(cached)}/"
            f"{_fmt_value(computed + cached)} prompt tokens served "
            f"from cache ({100.0 * cached / (computed + cached):.1f}%)")
    lines.append(
        f"  {len(tenants)} tenants tracked "
        f"({_fmt_value(usage.get('evicted_tenants', 0))} folded into "
        f"the {EVICTED_TENANT} rollup), "
        f"{_fmt_value(usage.get('live_requests', 0))} requests still "
        f"live at dump time")
    cons = usage.get("conservation")
    if isinstance(cons, dict):
        lines.append(
            f"  page-seconds conservation: "
            f"device_delta={_fmt_value(cons.get('device_delta', 0))} "
            f"host_delta={_fmt_value(cons.get('host_delta', 0))} "
            f"(both must be 0; charged == pool integral)")
    return "\n".join(lines)


# mirrors paddle_tpu.observability.usage.EVICTED_TENANT — hardcoded so
# this tool keeps its no-paddle_tpu/no-jax contract
EVICTED_TENANT = "(evicted)"


def _quant_section(quant):
    """Quantized-serving summary from quant.json — dumps from dense
    engines (or older builds) have no file and produce no section."""
    if not isinstance(quant, dict):
        return None
    lines = ["Quantization"]
    lines.append(f"  weights: {quant.get('weight_kind', 'dense')}")
    page = quant.get("page_bytes")
    dense = quant.get("dense_page_bytes")
    kv = "int8 pages" if quant.get("kv_quant") else "dense pages"
    if page and dense:
        lines.append(
            f"  KV pages: {kv}, {_fmt_bytes(page)}/page pair vs "
            f"{_fmt_bytes(dense)} dense "
            f"({100.0 * float(page) / float(dense):.1f}% of dense — "
            f"pages-per-token cost scales the same way)")
    else:
        lines.append(f"  KV pages: {kv}")
    spilled = quant.get("spilled_pages", 0)
    if spilled:
        moved = float(quant.get("spill_bytes") or 0)
        est = float(quant.get("spill_bytes_dense_estimate") or 0)
        line = (f"  spill tier: {spilled} pages parked, "
                f"{_fmt_bytes(moved)} moved")
        if est > moved:
            line += (f" (dense would have moved {_fmt_bytes(est)} — "
                     f"{_fmt_bytes(est - moved)} saved)")
        lines.append(line)
    return "\n".join(lines)


def _lora_section(lora, metrics):
    """Multi-LoRA adapter census + offline batch lane from lora.json
    (engine / serving-worker ``lora_snapshot()``) with the per-adapter
    decode-token counter from metrics.json folded in.  Dense dumps
    (or older builds) have no file and produce no section."""
    if not isinstance(lora, dict):
        return None
    lines = ["Adapters / batch lane"]
    if lora.get("capacity") is not None:
        resident = lora.get("resident") or []
        parked = lora.get("parked") or []
        pinned = lora.get("pinned") or {}
        lines.append(
            f"  bank: {len(resident)}/{_fmt_value(lora['capacity'])} "
            f"rows resident (rank {_fmt_value(lora.get('rank', '?'))}), "
            f"{len(parked)} parked on host, "
            f"{_fmt_value(lora.get('loads', 0))} loads / "
            f"{_fmt_value(lora.get('evictions', 0))} evictions")
        device = lora.get("bank_bytes_device")
        lines.append(
            f"  bank bytes: {_fmt_bytes(lora.get('bank_bytes', 0))} "
            f"packed" + (f", {_fmt_bytes(device)} on device"
                         if device else ""))
        # decode tokens per adapter from the usage counter family —
        # absent when no request named an adapter (or no meter ran)
        tokens: dict = {}
        entry = (metrics or {}).get(
            "serving_usage_adapter_tokens_total") or {}
        for s in entry.get("series", []):
            name = (s.get("labels") or {}).get("adapter", "?")
            tokens[name] = tokens.get(name, 0) + (s.get("value") or 0)
        reqs = lora.get("requests") or {}
        if reqs or tokens:
            rows = [(name, _fmt_value(reqs.get(name, 0)),
                     _fmt_value(tokens.get(name, 0)),
                     "resident" if name in resident else "parked",
                     _fmt_value(pinned.get(name, 0)))
                    for name in sorted(set(reqs) | set(tokens))]
            lines.append(_table(rows, ("adapter", "reqs", "decode",
                                       "state", "pinned")))
    jobs = lora.get("batch_jobs") or {}
    for jid, prog in sorted(jobs.items()):
        if not isinstance(prog, dict):
            continue
        total = prog.get("total", 0)
        lines.append(
            f"  batch {jid}: {prog.get('status', '?')} "
            f"{_fmt_value(prog.get('completed', 0))}/"
            f"{_fmt_value(total)} rows "
            f"({_fmt_value(prog.get('failed', 0))} failed, "
            f"{_fmt_value(prog.get('preemptions', 0))} preemptions, "
            f"{_fmt_value(prog.get('output_tokens', 0))} tokens) -> "
            f"{prog.get('output_path') or '-'}")
    return "\n".join(lines) if len(lines) > 1 else None


def _tail_section(exemplars):
    """Tail-latency forensics from exemplars.json (the request log's
    snapshot: latency attribution totals by cause, worst-K
    SLO-violation exemplars per dimension, and the conservation
    check).  Dumps produced without ``FLAGS_serving_request_log`` —
    or by older builds — have no file and produce no section."""
    if not isinstance(exemplars, dict):
        return None
    lines = ["Tail latency"]
    totals = exemplars.get("attribution_totals_s") or {}
    spent = sum(float(v or 0) for v in totals.values())
    if spent:
        rows = [(cause, f"{float(v or 0):.6g}",
                 f"{100.0 * float(v or 0) / spent:.1f}%")
                for cause, v in sorted(
                    totals.items(), key=lambda kv: -float(kv[1] or 0))
                if float(v or 0)]
        lines.append(_table(rows, ("cause", "seconds", "share")))
    store = exemplars.get("exemplars") or {}
    for dim, recs in sorted((store.get("by_dimension") or {}).items()):
        recs = [r for r in (recs or []) if isinstance(r, dict)]
        if not recs:
            continue
        worst = recs[0]
        lines.append(
            f"  worst {dim}: {float(worst.get('score_s') or 0):.6g}s "
            f"request={worst.get('request')} "
            f"tenant={worst.get('tenant') or '-'} "
            f"adapter={worst.get('adapter') or '-'} "
            f"trace={worst.get('trace_id') or '-'} "
            f"({len(recs)} kept)")
    if store:
        lines.append(
            f"  exemplars: {_fmt_value(store.get('kept', 0))} kept of "
            f"{_fmt_value(store.get('offered', 0))} violations offered "
            f"(worst-{_fmt_value(store.get('k', 0))} per dimension)")
    finished = exemplars.get("finished", 0)
    if finished:
        lines.append(
            f"  attribution conservation: max |sum(buckets) - e2e| = "
            f"{_fmt_value(exemplars.get('conservation_max_delta', 0))} "
            f"over {_fmt_value(finished)} finished requests "
            f"(must be 0; bucket seconds telescope to measured E2E)")
    return "\n".join(lines) if len(lines) > 1 else None


def report(metrics, retraces, trace=None, flight=None, resources=None,
           profile=None, captures=None, usage=None, quant=None,
           lora=None, exemplars=None):
    simple_rows = {"counter": [], "gauge": []}
    hist_blocks = []
    for name, entry in sorted(metrics.items()):
        kind = entry.get("type")
        if kind == "histogram":
            hist_blocks.append(_histogram_block(name, entry))
            continue
        for s in entry.get("series", []):
            simple_rows[kind].append(
                (name, _fmt_labels(s.get("labels", {})),
                 _fmt_value(s.get("value", 0))))
    out = []
    for kind, title in (("counter", "Counters"), ("gauge", "Gauges")):
        if simple_rows[kind]:
            out += [title, _table(simple_rows[kind],
                                  ("name", "labels", "value")), ""]
    if hist_blocks:
        out += ["Histograms"] + hist_blocks + [""]
    serving = _serving_section(metrics)
    if serving:
        out += [serving, ""]
    spec = _spec_section(metrics)
    if spec:
        out += [spec, ""]
    http = _http_section(metrics)
    if http:
        out += [http, ""]
    faults = _faults_section(metrics)
    if faults:
        out += [faults, ""]
    sched = _scheduling_section(metrics)
    if sched:
        out += [sched, ""]
    slo = _slo_section(metrics)
    if slo:
        out += [slo, ""]
    tracing = _tracing_section(trace, flight)
    if tracing:
        out += [tracing, ""]
    res = _resources_section(resources)
    if res:
        out += [res, ""]
    prof = _profiling_section(profile, captures, metrics)
    if prof:
        out += [prof, ""]
    use = _usage_section(usage)
    if use:
        out += [use, ""]
    q = _quant_section(quant)
    if q:
        out += [q, ""]
    lr = _lora_section(lora, metrics)
    if lr:
        out += [lr, ""]
    tail = _tail_section(exemplars)
    if tail:
        out += [tail, ""]
    if retraces and retraces.get("entries"):
        entries = sorted(retraces["entries"],
                         key=lambda e: (-e["count"], e["op"]))
        out += ["Retrace log (one row per new eager-cache signature)",
                _table([(e["op"], e["count"], e["signature"])
                        for e in entries],
                       ("op", "hits", "abstract signature")), ""]
        by_op = retraces.get("by_op") or {}
        storms = {k: v for k, v in by_op.items() if v > 3}
        if storms:
            out.append("retrace storms (>3 distinct signatures): " +
                       ", ".join(f"{k}={v}"
                                 for k, v in sorted(storms.items())))
    return "\n".join(out).rstrip() or "empty dump"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="dump directory or metrics.json path")
    ap.add_argument("--prom", action="store_true",
                    help="print the raw Prometheus text export")
    args = ap.parse_args(argv)
    (metrics, retraces, trace, flight, resources, profile, captures,
     usage, quant, lora, exemplars, prom_path) = _load(args.path)
    if args.prom:
        if not os.path.exists(prom_path):
            sys.exit(f"metrics_report: no metrics.prom at {prom_path!r}")
        with open(prom_path) as f:
            print(f.read(), end="")
        return 0
    print(report(metrics, retraces, trace, flight, resources,
                 profile, captures, usage, quant, lora, exemplars))
    return 0


if __name__ == "__main__":
    sys.exit(main())
