#!/usr/bin/env python
"""check: the one-shot local gate — lint + perf gate (+ optional tests).

Runs each gate as a subprocess, prints a one-line verdict per step, and
exits with a single combined status, so a pre-push hook is just::

    python tools/check.py            # lint + perf gate
    python tools/check.py --changed  # lint only files != HEAD (fast)
    python tools/check.py --tests    # also run the fast pytest subset
    python tools/check.py --no-perf  # lint only (e.g. on a laptop)

Exit status: 0 when every selected step passes, 1 when any fails, 2 on
usage errors.  Steps always all run (a lint failure does not hide a
perf regression).  The pytest subset defaults to the analysis suite's
own tests — pass an argument to ``--tests`` to run something else,
e.g. ``--tests tests/test_serving.py``.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO_ROOT, "tools")

DEFAULT_TESTS = "tests/test_lint.py"


def _step(name: str, cmd: list[str], env=None) -> tuple[str, int, float]:
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, cwd=_REPO_ROOT, env=env)
    return name, proc.returncode, time.perf_counter() - t0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="check.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="pass through to lint.py --changed: lint only "
                         "files differing from REF (default HEAD)")
    ap.add_argument("--no-perf", action="store_true",
                    help="skip the perf gate (tools/perf_gate.py)")
    ap.add_argument("--tests", nargs="?", const=DEFAULT_TESTS,
                    default=None, metavar="TARGET",
                    help="also run a fast pytest subset "
                         f"(default: {DEFAULT_TESTS})")
    args = ap.parse_args(argv)

    py = sys.executable
    steps = []

    lint_cmd = [py, os.path.join(_TOOLS, "lint.py")]
    if args.changed is not None:
        lint_cmd += ["--changed", args.changed]
    steps.append(("lint", lint_cmd, None))

    if not args.no_perf:
        steps.append(("perf-gate",
                      [py, os.path.join(_TOOLS, "perf_gate.py")], None))

    if args.tests is not None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")   # the gate must not
        # depend on an accelerator being free on the dev machine
        steps.append(("pytest",
                      [py, "-m", "pytest", "-q", "-p",
                       "no:cacheprovider"] + args.tests.split(),
                      env))

    results = [_step(name, cmd, env) for name, cmd, env in steps]

    print("\n" + "-" * 56)
    failed = False
    for name, rc, dt in results:
        verdict = "ok" if rc == 0 else f"FAIL (exit {rc})"
        print(f"  {name:<10} {verdict:<14} {dt:6.1f}s")
        failed = failed or rc != 0
    print("-" * 56)
    print("check: " + ("FAILED" if failed else "all gates passed"))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
