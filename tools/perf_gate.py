#!/usr/bin/env python
"""perf-gate: deterministic serving-efficiency regression gate.

Runs small serve scenarios on a tiny model and gates on **counters**
(retraces, host syncs per step, logits transfers, pages per token,
prefix hit rate, goodput ratio) — never wall time, so the gate is
stable on CPU under tier-1.

Usage:
    python tools/perf_gate.py                    # gate vs committed baseline
    python tools/perf_gate.py --json             # machine-readable output
    python tools/perf_gate.py --update-baseline  # accept current counters
    python tools/perf_gate.py --scenarios steady_decode,prefix_cache
    python tools/perf_gate.py --list-scenarios

Exit status mirrors tools/lint.py: 0 when every counter is within its
baseline (counters may *improve*: fewer retraces / higher hit rate pass
and are reported as improvements — tighten with ``--update-baseline``),
1 on a regression or a counter with no baseline entry, 2 on usage
errors (unknown scenario, missing baseline file).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools",
                                "perf_baseline.json")

# comparison direction per counter: "low" = current <= baseline passes,
# "high" = current >= baseline passes, "exact" = must match
DIRECTIONS = {
    "decode_traces": "low",
    "prefill_compiles": "low",
    "host_syncs": "low",
    "host_syncs_per_decode_step": "low",
    "logits_fetches": "low",
    "pages_per_token": "low",
    "pages_allocated": "low",
    "cow_copies": "exact",
    "prefix_hit_rate": "high",
    "cached_tokens": "high",
    "steps_per_sync": "high",
    "goodput_ratio": "high",
    "host_syncs_delta_vs_tp1": "exact",
    "pages_per_token_delta_vs_tp1": "exact",
    "mesh_tp": "exact",
    # speculative decoding: the verify program must be its own single
    # trace beside the plain step (exactly 2 decode traces, 1 verify
    # trace), commit more than one token per device step on repetitive
    # text, keep the drafter's acceptance above its floor, and stay
    # bit-identical to the plain engine (parity gates at exactly 1)
    "spec_decode_traces": "exact",
    "verify_traces": "exact",
    "tokens_per_decode_step": "high",
    "acceptance_rate": "high",
    "decode_steps_saved_vs_plain": "high",
    "greedy_parity_vs_plain": "exact",
    # fault recovery: one injected poisoned step must cost exactly one
    # rebuild, replay every in-flight request (sharing the prefix cache
    # on the way back in), keep greedy outputs identical to the
    # unfaulted run, and hand back every page
    "recoveries": "exact",
    "quarantines": "exact",
    "replayed_requests": "exact",
    "recovered_parity": "exact",
    "leaked_pages": "exact",
    "faults_injected": "exact",
    "replay_cached_tokens": "high",
    # overload degradation: preempt-and-swap must spill and restore an
    # exact page count with zero spill failures, keep the preempted
    # request's greedy output identical to an uninterrupted run, and
    # hand back every page; chunked prefill must split a long admission
    # into an exact chunk count and bound the longest decode-free
    # prefill burst (the head-of-line-blocking witness) — all without
    # a single new decode trace
    "preemptions": "exact",
    "spill_aborts": "exact",
    "spilled_pages": "exact",
    "restored_pages": "exact",
    "preempt_parity": "exact",
    "prefill_chunks": "exact",
    "chunk_parity": "exact",
    "max_prefill_gap": "low",
    # telemetry: the sampler must be deterministic under a fake clock
    # (exact ticks/samples/alerts) and free under the control run
    # (exactly zero extra host syncs / decode traces)
    "sampler_ticks": "exact",
    "samples_taken": "exact",
    "series_tracked": "exact",
    "alert_rules": "exact",
    "alerts_fired": "exact",
    "host_syncs_delta_vs_off": "exact",
    "decode_traces_delta_vs_off": "exact",
    # profiling: the sampler must sweep exactly once per driven step
    # with zero stack-table drops, the injected slow_step alert must
    # produce exactly one on-disk capture (a second fire inside the
    # rate-limit window is rejected, not written), and arming the
    # whole stack must add ZERO host syncs / decode traces over the
    # bare control (the zero-overhead-off contract of
    # FLAGS_obs_profile_interval_s / FLAGS_obs_capture_*)
    "captures_written": "exact",
    "capture_files": "exact",
    "capture_rate_limited": "exact",
    "profile_samples_delta_vs_steps": "exact",
    "profile_dropped": "exact",
    # usage metering: every per-request ledger field must sum exactly
    # to the matching engine/pool global (attribution is accounting,
    # not sampling), the page-seconds conservation identity must hold
    # at 0 for both tiers, the preemption spill must bill the victim's
    # tenant alone, outputs must be bit-identical to the meter-off run,
    # and arming the meter must add ZERO host syncs / decode traces
    "ledger_computed_tokens": "exact",
    "ledger_cached_delta": "exact",
    "ledger_decode_delta": "exact",
    "ledger_spilled_delta": "exact",
    "ledger_restored_delta": "exact",
    "ledger_spill_bytes_minus_restore_bytes": "exact",
    "ledger_preemptions_delta": "exact",
    "victim_tenant_spilled_pages": "exact",
    "bystander_spilled_pages": "exact",
    "page_seconds_conservation_delta": "exact",
    "host_page_seconds_conservation_delta": "exact",
    "tenants_tracked": "exact",
    "usage_parity_vs_off": "exact",
    # multi-LoRA serving: two live adapters in one mixed batch must
    # share the ONE decode trace, match the merged-weights dense
    # reference token-for-token, actually diverge from the base model,
    # and an armed-but-unused store must cost exactly nothing (dense
    # parity, zero extra host syncs / decode traces)
    "adapters_resident": "exact",
    "lora_loads": "exact",
    "lora_evictions": "exact",
    "lora_parity_vs_merged": "exact",
    "lora_off_parity_vs_dense": "exact",
    "adapter_divergence": "exact",
    # offline batch lane: the job must complete every row with zero
    # failures while interactive arrivals preempt its residents, the
    # preempted rows must resume token-for-token (row parity vs an
    # idle engine), interactive outputs must be untouched, and the
    # pool must balance
    "batch_rows_completed": "exact",
    "batch_rows_failed": "exact",
    "batch_job_done": "exact",
    "batch_row_parity": "exact",
    "interactive_parity_vs_idle": "exact",
    # tail-latency forensics: every finished timeline's bucket seconds
    # must telescope exactly to its measured E2E (the conservation
    # identity pinned at 0), the event / exemplar counts are exact
    # under the nanosecond SLO (every request violates every
    # dimension, so the reservoir census is arithmetic, not timing),
    # greedy outputs are bit-identical to the forensics-off run, and
    # arming the RequestLog adds ZERO host syncs / decode traces (the
    # zero-overhead-off contract of the ``requestlog is not None``
    # seams)
    "requests_tracked": "exact",
    "requests_finished": "exact",
    "timeline_events": "exact",
    "attribution_conservation_max_delta": "exact",
    "exemplars_captured": "exact",
    "forensics_parity_vs_off": "exact",
}


def _force_cpu():
    """The gate's counters are platform-independent, but CPU is the
    only backend tier-1 guarantees — never touch an accelerator.  The
    tp_decode scenario additionally needs >= 2 host devices, so ask XLA
    for 8 before the backend initializes (a no-op once it has — under
    pytest the conftest already forced the same count)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass            # backend already initialized (e.g. under pytest)


def _engine(**kw):
    """Fresh tiny model + engine per scenario: counters are read from
    the engine's own python mirrors, so scenarios never see each
    other's (or the host process's) metrics."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import create_engine
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=128)
    return create_engine(LlamaForCausalLM(cfg), **kw)


def _tiny_state():
    """The gate's tiny config + its generation-state dict — scenarios
    that transform the checkpoint (the merged-weight LoRA reference)
    build Engines from state directly instead of through a model."""
    import paddle_tpu as paddle
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    state = {k: (v._data if isinstance(v, Tensor) else v)
             for k, v in model.functional_state().items()}
    return cfg, state


def _gen(max_new_tokens):
    from paddle_tpu.models.generation import GenerationConfig
    return GenerationConfig(max_new_tokens=max_new_tokens)


def _goodput(reqs) -> float:
    useful = sum(r.num_generated for r in reqs
                 if r.finish_reason in ("length", "eos"))
    total = sum(r.num_generated for r in reqs)
    return round(useful / total, 6) if total else 1.0


def _reinject_retrace(eng):
    """Test hook: rebuild the decode-step jit so the next decode call
    traces again — the exact regression serving_decode_step_traces_total
    exists to catch."""
    eng.runner.reinject_step()


def scenario_steady_decode(inject_retrace=False) -> dict:
    """Greedy decode across two admission waves: the decode step must
    trace ONCE for the engine's lifetime, each step costs exactly one
    host sync (sync_interval=1), and no logits ever cross the wire."""
    eng = _engine(max_slots=2, page_size=4, sync_interval=1)
    reqs = [eng.submit([1, 2, 3, 4, 5, 6], _gen(8)),
            eng.submit([3, 4, 5, 6, 7, 8], _gen(8))]
    eng.run_until_complete(max_steps=400)
    if inject_retrace:
        _reinject_retrace(eng)
    reqs.append(eng.submit([5, 6, 7, 8, 9, 10, 11], _gen(8)))
    eng.run_until_complete(max_steps=400)
    tokens = sum(r.num_generated for r in reqs)
    return {
        "decode_traces": eng.decode_traces,
        "prefill_compiles": (len(eng._prefill_fns)
                             + len(eng._prefill_cached_fns)),
        "host_syncs_per_decode_step": round(
            eng.host_syncs / max(eng.decode_steps, 1), 6),
        "logits_fetches": eng.logit_fetches,
        "pages_per_token": round(
            eng.blocks.pages_allocated / max(tokens, 1), 6),
        "goodput_ratio": _goodput(reqs),
    }


def scenario_prefix_cache() -> dict:
    """A second wave sharing a 12-token (3-page) prefix must hit the
    chain index for every shared chunk, pay pages only for its suffix,
    and CoW exactly once for the tail that diverges after one token."""
    eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                  enable_prefix_cache=True)
    prefix = list(range(1, 13))
    reqs = [eng.submit(prefix + [20, 21], _gen(4))]
    eng.run_until_complete(max_steps=200)
    reqs.append(eng.submit(prefix + [20, 25], _gen(4)))   # CoW tail
    reqs.append(eng.submit(prefix + [30, 31], _gen(4)))   # fresh tail
    eng.run_until_complete(max_steps=200)
    b = eng.blocks
    lookups = b.prefix_hits + b.prefix_misses
    return {
        "prefix_hit_rate": round(b.prefix_hits / max(lookups, 1), 6),
        "cached_tokens": b.cached_tokens,
        "pages_allocated": b.pages_allocated,
        "cow_copies": b.cow_copies,
        "goodput_ratio": _goodput(reqs),
    }


def scenario_deferred_sync() -> dict:
    """sync_interval=4 greedy decode must amortize the ring fetch over
    4 device steps — host syncs are the serving scalability ceiling."""
    eng = _engine(max_slots=2, page_size=4, sync_interval=4)
    reqs = [eng.submit([1, 2, 3, 4, 5, 6], _gen(8)),
            eng.submit([2, 3, 4, 5, 6, 7], _gen(8))]
    eng.run_until_complete(max_steps=400)
    del reqs
    return {
        "steps_per_sync": round(
            eng.decode_steps / max(eng.host_syncs, 1), 6),
        "host_syncs": eng.host_syncs,
        "decode_traces": eng.decode_traces,
    }


def scenario_goodput_cancel() -> dict:
    """A client cancel after 3 streamed tokens wastes exactly those 3
    tokens; the surviving request's 8 are useful — ratio 8/11.  Counted
    from request outcomes (no wall clocks, no deadlines)."""
    eng = _engine(max_slots=2, page_size=4, sync_interval=1)

    def cancel_after_3(req, tok):
        if req.num_generated >= 3:
            req.cancel()

    reqs = [eng.submit([1, 2, 3, 4, 5, 6], _gen(8)),
            eng.submit([2, 3, 4, 5, 6, 7], _gen(8),
                       on_token=cancel_after_3)]
    eng.run_until_complete(max_steps=400)
    return {
        "goodput_ratio": _goodput(reqs),
        "decode_traces": eng.decode_traces,
        "logits_fetches": eng.logit_fetches,
    }


def scenario_tp_decode() -> dict:
    """Tensor-parallel decode on a tp=2 host-device mesh, same workload
    twice (tp=1 then tp=2) with an admit + a mid-decode cancel-eviction
    in wave two: the mesh must keep ONE decode trace across admit/evict,
    and pay exactly the single-chip host-sync and page bills (the
    ``*_delta_vs_tp1`` counters gate at 0)."""

    def drive(tp):
        eng = _engine(max_slots=2, page_size=4, sync_interval=1, mesh=tp)

        def cancel_after_3(req, tok):
            if req.num_generated >= 3:
                req.cancel()

        reqs = [eng.submit([1, 2, 3, 4, 5, 6], _gen(8)),
                eng.submit([3, 4, 5, 6, 7, 8], _gen(8))]
        eng.run_until_complete(max_steps=400)
        reqs.append(eng.submit([5, 6, 7, 8, 9, 10, 11], _gen(8)))
        reqs.append(eng.submit([2, 4, 6, 8], _gen(8),
                               on_token=cancel_after_3))
        eng.run_until_complete(max_steps=400)
        return eng, reqs

    e1, _ = drive(1)
    e2, reqs = drive(2)
    tokens = sum(r.num_generated for r in reqs)
    ppt = round(e2.blocks.pages_allocated / max(tokens, 1), 6)
    ppt1 = round(e1.blocks.pages_allocated / max(tokens, 1), 6)
    return {
        "mesh_tp": e2.tp,
        "decode_traces": e2.decode_traces,
        "prefill_compiles": (len(e2._prefill_fns)
                             + len(e2._prefill_cached_fns)),
        "host_syncs_per_decode_step": round(
            e2.host_syncs / max(e2.decode_steps, 1), 6),
        "host_syncs_delta_vs_tp1": e2.host_syncs - e1.host_syncs,
        "pages_per_token_delta_vs_tp1": round(ppt - ppt1, 6),
        "logits_fetches": e2.logit_fetches,
        "goodput_ratio": _goodput(reqs),
    }


def scenario_spec_decode() -> dict:
    """Speculative decoding on repetitive text: the same greedy
    workload runs with spec_k=0 and spec_k=4, and the spec engine must
    emit identical tokens while committing > 1 token per device step
    (the tentpole win), tracing exactly two decode programs (plain +
    verify) across two admission waves, and spending strictly fewer
    device steps and host syncs than the plain engine — counters only,
    no wall clocks.  Single slot, so tokens/step measures speculation
    rather than batching (concurrent slots would inflate it even with
    spec_k=0)."""

    def drive(spec_k):
        eng = _engine(max_slots=1, page_size=4, sync_interval=1,
                      spec_k=spec_k)
        # prompts whose greedy continuations collapse into repeats —
        # the n-gram drafter's best case, deterministic under seed 0
        reqs = [eng.submit([5, 6, 5, 6, 5, 6], _gen(12))]
        eng.run_until_complete(max_steps=400)
        # second wave: admission after a finished request must not
        # retrace either the plain or the verify program
        reqs.append(eng.submit([3, 4, 3, 4, 3, 4], _gen(12)))
        eng.run_until_complete(max_steps=400)
        return eng, reqs

    plain, ref_reqs = drive(0)
    eng, reqs = drive(4)
    st = eng.stats()
    tokens = sum(r.num_generated for r in reqs)
    return {
        "greedy_parity_vs_plain": int(
            [r.output_tokens for r in reqs]
            == [r.output_tokens for r in ref_reqs]),
        "spec_decode_traces": eng.decode_traces,
        "verify_traces": st["verify_traces"],
        "tokens_per_decode_step": round(
            tokens / max(eng.decode_steps, 1), 6),
        "acceptance_rate": round(st["spec_acceptance_rate"], 6),
        "decode_steps_saved_vs_plain": (plain.decode_steps
                                        - eng.decode_steps),
        "host_syncs": eng.host_syncs,
        "goodput_ratio": _goodput(reqs),
    }


def scenario_fault_recovery() -> dict:
    """A poisoned decode step mid-batch under the engine supervisor:
    exactly one runner rebuild, both in-flight requests replayed (the
    shared prompt prefix rides back in through the prefix cache), token
    outputs identical to an unfaulted run, and a clean pool census.
    The unfaulted drive doubles as the zero-overhead control — it runs
    the same supervised loop with fault injection off."""
    from paddle_tpu.serving import EngineSupervisor, FaultPlan

    prefix = list(range(1, 13))

    def drive(plan):
        eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                      enable_prefix_cache=True, faults=plan)
        sup = EngineSupervisor(eng, max_recoveries=3)
        reqs = [eng.submit(prefix + [20, 21], _gen(8)),
                eng.submit(prefix + [20, 25], _gen(8))]
        steps = 0
        while not all(r.is_finished() for r in reqs) and steps < 400:
            sup.step()
            steps += 1
        return eng, reqs

    ref_eng, ref_reqs = drive(None)
    plan = FaultPlan(seed=0)
    plan.add("step_raise", at=5)
    eng, reqs = drive(plan)
    return {
        "recoveries": eng.recoveries,
        "quarantines": eng.quarantines,
        "replayed_requests": eng.replayed_requests,
        "recovered_parity": int([r.output_tokens for r in reqs]
                                == [r.output_tokens for r in ref_reqs]),
        "leaked_pages": eng.blocks.pool_accounting()["leak"],
        "faults_injected": plan.injected.get("step_raise", 0),
        # cache-served prompt tokens ABOVE the unfaulted run = what the
        # replay path got back from the prefix cache instead of
        # recomputing
        "replay_cached_tokens": (eng.blocks.cached_tokens
                                 - ref_eng.blocks.cached_tokens),
        "decode_traces": eng.decode_traces,
        "goodput_ratio": _goodput(reqs),
    }


def scenario_telemetry() -> dict:
    """Fake-clock sampler determinism: the same faulted workload runs
    twice — with a ticking TimeSeriesStore + the default alert rules,
    and without — gating that the sampler takes an exact number of
    samples, fires exactly the expected alerts, and adds ZERO host
    syncs / decode traces over the sampler-off control (the
    zero-overhead contract of FLAGS_obs_timeseries_interval_s).
    Sources read engine python mirrors, not the process registry, so
    the scenario is isolated no matter which scenarios ran before."""
    from paddle_tpu import observability as obs
    from paddle_tpu.serving import EngineSupervisor, FaultPlan

    prompt = list(range(1, 9))

    def drive(with_store):
        plan = FaultPlan(seed=0)
        plan.add("nan_logits", at=1, slot=0, phase="prefill")
        eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                      faults=plan)
        sup = EngineSupervisor(eng, max_recoveries=3)
        store = None
        fake = [0.0]
        reqs = []
        if with_store:
            store = obs.TimeSeriesStore(capacity=256,
                                        clock=lambda: fake[0])
            store.add_source("tokens", lambda: float(
                sum(r.num_generated for r in reqs)))
            store.add_source("active_slots",
                             lambda: float(eng.scheduler.active_count))
            store.add_source("fragmentation",
                             lambda: eng.blocks.fragmentation())
            store.add_source("recoveries", lambda: float(
                eng.recoveries + eng.quarantines))
            store.add_rate("tok_s", of="tokens")
            for rule in obs.default_rules(shed_burn_rate=1.0):
                store.add_rule(rule)
            store.tick()        # t=0 baseline before any fault
        reqs += [eng.submit(prompt + [20], _gen(8)),
                 eng.submit(prompt + [25], _gen(8))]
        steps = 0
        while not all(r.is_finished() for r in reqs) and steps < 400:
            sup.step()
            steps += 1
            if store is not None:
                fake[0] += 1.0
                store.tick()
        return eng, store

    eng_off, _ = drive(False)
    eng_on, store = drive(True)
    return {
        "sampler_ticks": store.ticks,
        "samples_taken": store.samples,
        "series_tracked": len(store.windows(n=1)),
        "alert_rules": len(store.rules),
        "alerts_fired": store.alerts_fired,
        "quarantines": eng_on.quarantines,
        "leaked_pages": eng_on.blocks.pool_accounting()["leak"],
        # the zero-overhead contract: sampling adds no device work
        "host_syncs_delta_vs_off": eng_on.host_syncs
        - eng_off.host_syncs,
        "decode_traces_delta_vs_off": eng_on.decode_traces
        - eng_off.decode_traces,
    }


def scenario_profiling() -> dict:
    """Alert-triggered diagnostic capture + sampling profiler,
    counters only, fake clocks throughout.  The same slow-step-marked
    workload runs twice — bare, and with the full PR-15 stack armed
    (TimeSeriesStore + a deterministic slow_steps alert rule +
    DiagnosticCapture into a throwaway dir + a SamplingProfiler swept
    inline once per step).  Gates: the alert fires exactly once, the
    capture lands exactly once on disk, a second on_alert inside the
    rate-limit window is rejected (not written), the profiler takes
    exactly one sweep per driven step with zero drops, and the armed
    run adds ZERO host syncs / decode traces over the bare control."""
    import tempfile
    from paddle_tpu import observability as obs
    from paddle_tpu.serving import FaultPlan

    prompt = list(range(1, 9))

    def drive(with_obs, tmp=None):
        plan = FaultPlan(seed=0)
        # marker fault: the injected-count drives the alert; a zero
        # sleep keeps the gate fast and the workload byte-identical
        plan.add("slow_step", at=3, seconds=0.0)
        eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                      faults=plan)
        store = prof = cap = None
        fake = [0.0]
        if with_obs:
            store = obs.TimeSeriesStore(capacity=256,
                                        clock=lambda: fake[0])
            store.add_source("slow_steps", lambda: float(
                plan.injected.get("slow_step", 0)))
            store.add_rule(obs.AlertRule(
                "slow_step_injected", "slow_steps", above=0,
                min_samples=1,
                help_="deterministic capture trigger for the gate"))
            prof = obs.SamplingProfiler(0.0)   # inline sweeps only
            cap = obs.DiagnosticCapture(
                dir_=tmp, min_interval_s=3600.0, max_captures=4,
                profiler=prof, clock=lambda: fake[0])
            cap.attach(store)
            store.tick()        # t=0 baseline before the fault lands
        reqs = [eng.submit(prompt + [20], _gen(8)),
                eng.submit(prompt + [25], _gen(8))]
        steps = 0
        while not all(r.is_finished() for r in reqs) and steps < 400:
            eng.step()
            steps += 1
            if store is not None:
                fake[0] += 1.0
                prof.sample(fake[0])
                store.tick()
        return eng, store, prof, cap, steps

    eng_off, *_ = drive(False)
    with tempfile.TemporaryDirectory() as tmp:
        eng_on, store, prof, cap, steps = drive(True, tmp)
        # a second fire inside the rate-limit window: rejected exactly
        cap.on_alert("slow_step_injected", {"value": 1.0},
                     now=float(steps))
        files = len([f for f in os.listdir(tmp)
                     if f.startswith("capture_")])
    return {
        "alerts_fired": store.alerts_fired,
        "captures_written": cap.captures,
        "capture_files": files,
        "capture_rate_limited": cap.rate_limited,
        "profile_samples_delta_vs_steps": prof.samples - steps,
        "profile_dropped": prof.dropped,
        "leaked_pages": eng_on.blocks.pool_accounting()["leak"],
        # the zero-overhead contract: the armed stack adds no device
        # work over the bare control
        "host_syncs_delta_vs_off": eng_on.host_syncs
        - eng_off.host_syncs,
        "decode_traces_delta_vs_off": eng_on.decode_traces
        - eng_off.decode_traces,
    }


def scenario_overload_degrade() -> dict:
    """Graceful degradation under overload, counters only.

    Preempt half: two low-priority residents fill both slots and
    decode for a while; a high-priority submit must preempt the
    most-recently-admitted one — spilling its full KV pages to the
    host tier (exact page count, zero aborts), re-queueing it, and
    restoring the parked pages on resume.  The preempted request's
    greedy tokens must equal an uninterrupted run's (parity gates at
    exactly 1) and the pool census must balance.

    Chunk half: a 40-token prompt admitted behind a decoding resident
    with prefill_chunk=8 must prefill in exactly 5 chunks, and the
    longest run of prefill tokens with no intervening decode step
    (max_prefill_gap, the head-of-line-blocking witness) must stay at
    the chunk size instead of the full prompt length.  Both halves
    reuse the existing decode/prefill programs — decode_traces gates
    at 1 per engine."""
    # --- preempt-and-swap (prefix cache off: spills, not cache, must
    # carry the KV back) ---
    eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                  enable_prefix_cache=False, preempt=True)
    lo_a = eng.submit([1, 2, 3, 4, 5, 6], _gen(8))
    lo_b = eng.submit([3, 4, 5, 6, 7, 8], _gen(8))
    for _ in range(4):              # both residents mid-decode
        eng.step()
    hi = eng.submit([5, 6, 7, 8, 9, 10], _gen(8), priority=1)
    eng.run_until_complete(max_steps=400)
    reqs = [lo_a, lo_b, hi]

    ref = _engine(max_slots=3, page_size=4, sync_interval=1,
                  enable_prefix_cache=False)
    ref_reqs = [ref.submit([1, 2, 3, 4, 5, 6], _gen(8)),
                ref.submit([3, 4, 5, 6, 7, 8], _gen(8)),
                ref.submit([5, 6, 7, 8, 9, 10], _gen(8))]
    ref.run_until_complete(max_steps=400)

    # --- chunked prefill (long admission behind a decoding resident) ---
    long_prompt = list(range(1, 41))
    eng2 = _engine(max_slots=2, page_size=4, sync_interval=1,
                   enable_prefix_cache=False, prefill_chunk=8)
    short = eng2.submit([1, 2, 3, 4, 5, 6], _gen(16))
    for _ in range(3):              # short request is decoding
        eng2.step()
    chunked = eng2.submit(long_prompt, _gen(4))
    eng2.run_until_complete(max_steps=400)

    ref2 = _engine(max_slots=2, page_size=4, sync_interval=1,
                   enable_prefix_cache=False, prefill_chunk=0)
    ref2_req = ref2.submit(long_prompt, _gen(4))
    ref2.run_until_complete(max_steps=400)

    return {
        "preemptions": eng.preemptions,
        "spill_aborts": eng.spill_aborts,
        "spilled_pages": eng.blocks.spilled_pages,
        "restored_pages": eng.blocks.restored_pages,
        "preempt_parity": int(
            [r.output_tokens for r in reqs]
            == [r.output_tokens for r in ref_reqs]),
        "leaked_pages": (eng.blocks.pool_accounting()["leak"]
                         + eng2.blocks.pool_accounting()["leak"]),
        "decode_traces": max(eng.decode_traces, eng2.decode_traces),
        "prefill_chunks": eng2.prefill_chunks,
        "max_prefill_gap": eng2.max_prefill_gap,
        "chunk_parity": int(chunked.output_tokens
                            == ref2_req.output_tokens),
        "goodput_ratio": _goodput(reqs + [short, chunked]),
    }


def scenario_usage_meter() -> dict:
    """Per-request cost attribution + tenant metering, counters only.

    The same 3-tenant preempt-and-swap workload (two low-priority
    residents, then a high-priority arrival that preempts one of them)
    runs twice — bare, and with a UsageMeter wired in.  Gates: every per-request ledger field sums exactly to
    the matching engine/pool global (computed/cached prefill split,
    decode tokens, spilled/restored pages, spill bytes == restore
    bytes, preemptions), the page-seconds conservation identity holds
    at delta == 0 on both the device and host tiers, the spill bills
    the preempted tenant alone (bystanders at 0), greedy outputs are
    bit-identical to the meter-off run, and arming the meter adds ZERO
    host syncs / decode traces (the zero-overhead-off contract of the
    ``usage is not None`` seams)."""
    from paddle_tpu.observability.usage import UsageMeter, request_ledger

    def drive(meter):
        eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                      enable_prefix_cache=False, preempt=True,
                      usage=meter)
        lo_a = eng.submit([1, 2, 3, 4, 5, 6], _gen(8), tenant="teamA")
        lo_b = eng.submit([3, 4, 5, 6, 7, 8], _gen(8), tenant="teamB")
        for _ in range(4):              # both residents mid-decode
            eng.step()
        hi = eng.submit([5, 6, 7, 8, 9, 10], _gen(8), priority=1,
                        tenant="teamC")
        eng.run_until_complete(max_steps=400)
        return eng, [lo_a, lo_b, hi]

    eng_off, ref_reqs = drive(None)
    meter = UsageMeter()
    eng, reqs = drive(meter)
    snap = meter.snapshot()
    rows = snap["tenants"]
    cons = snap["conservation"]
    ledgers = [request_ledger(r) for r in reqs]

    def total(field):
        return sum(led[field] for led in ledgers)

    # both low residents admit in the same scheduler pass (identical
    # admitted_at), so slot order breaks the tie: slot 0 == teamA
    victim = rows.get("teamA", {})
    bystanders = (rows.get("teamB", {}).get("spilled_pages", 0)
                  + rows.get("teamC", {}).get("spilled_pages", 0))
    return {
        "preemptions": eng.preemptions,
        "spill_aborts": eng.spill_aborts,
        "spilled_pages": eng.blocks.spilled_pages,
        "restored_pages": eng.blocks.restored_pages,
        "ledger_computed_tokens": total("prefill_computed_tokens"),
        "ledger_cached_delta": (total("prefill_cached_tokens")
                                - eng.blocks.cached_tokens),
        "ledger_decode_delta": (
            sum(r.get("decode_tokens", 0) for r in rows.values())
            - sum(r.num_generated for r in reqs)),
        "ledger_spilled_delta": (total("spilled_pages")
                                 - eng.blocks.spilled_pages),
        "ledger_restored_delta": (total("restored_pages")
                                  - eng.blocks.restored_pages),
        "ledger_spill_bytes_minus_restore_bytes": (
            total("spill_bytes") - total("restore_bytes")),
        "ledger_preemptions_delta": (total("preemptions")
                                     - eng.preemptions),
        "victim_tenant_spilled_pages": victim.get("spilled_pages", 0),
        "bystander_spilled_pages": bystanders,
        "page_seconds_conservation_delta": cons["device_delta"],
        "host_page_seconds_conservation_delta": cons["host_delta"],
        "tenants_tracked": len(rows),
        "usage_parity_vs_off": int(
            [r.output_tokens for r in reqs]
            == [r.output_tokens for r in ref_reqs]),
        "leaked_pages": eng.blocks.pool_accounting()["leak"],
        "host_syncs_delta_vs_off": eng.host_syncs - eng_off.host_syncs,
        "decode_traces_delta_vs_off": (eng.decode_traces
                                       - eng_off.decode_traces),
        "goodput_ratio": _goodput(reqs),
    }


def scenario_quant_decode() -> dict:
    """Quantized serving (int8 weights + int8 KV pages) vs the dense
    reference on the identical two-wave workload, counters only.

    Gates: ONE decode trace with quantized weights and pools, greedy
    parity within tolerance (>= 75% token match on the tiny random
    model — int8 weight error may flip a late low-margin argmax, so
    exact parity would be flaky by construction while genuine breakage
    lands far below the floor), the KV page byte cost pinned at the
    closed-form ratio ``(hd + 4) / (4 * hd)`` of dense (the pages-per-
    token byte cost under ``--kv-quant``; 375/1000 at head_dim=8), the
    spill tier moving the same reduced bytes (read_page parks int8 +
    scales, never a dequantized copy), and the quant-off control: the
    dense run beside it must show zero extra host syncs and zero extra
    decode traces, the zero-overhead-off pin every scenario carries."""

    def drive(quant, kv_quant):
        eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                      quant=quant, kv_quant=kv_quant)
        reqs = [eng.submit([1, 2, 3, 4, 5, 6], _gen(8)),
                eng.submit([3, 4, 5, 6, 7, 8], _gen(8))]
        eng.run_until_complete(max_steps=400)
        reqs.append(eng.submit([5, 6, 7, 8, 9, 10, 11], _gen(8)))
        eng.run_until_complete(max_steps=400)
        return eng, reqs

    eng_off, ref_reqs = drive(None, None)
    eng, reqs = drive("int8", True)
    match = total = 0
    for r, rr in zip(reqs, ref_reqs):
        a, b = r.output_tokens, rr.output_tokens
        total += max(len(a), len(b))
        match += sum(int(x == y) for x, y in zip(a, b))
    snap = eng.quant_snapshot()
    dense_page = sum(a.nbytes for a in eng_off.runner.read_page(0))
    quant_page = sum(a.nbytes for a in eng.runner.read_page(0))
    return {
        "decode_traces": eng.decode_traces,
        "quant_parity_within_tol": int(match >= 0.75 * max(total, 1)),
        "pages_per_token_x1000": round(
            1000 * snap["page_bytes"] / snap["dense_page_bytes"]),
        "spill_bytes_ratio_vs_dense_x1000": round(
            1000 * quant_page / dense_page),
        "host_syncs_delta_vs_off": eng.host_syncs - eng_off.host_syncs,
        "decode_traces_delta_vs_off": (eng.decode_traces
                                       - eng_off.decode_traces),
        "goodput_ratio": _goodput(reqs),
    }


def scenario_lora_decode() -> dict:
    """Multi-LoRA serving vs the merged-weights dense reference,
    counters only.

    A mixed batch (adapter 'a' on one slot, adapter 'b' on the other)
    must run in ONE decode trace with both adapters live in the bank,
    and each request's greedy tokens must equal a dense engine built
    from ``W + (alpha/r) A^T B`` merged weights — token-for-token, the
    gather-from-bank path against the fold-into-checkpoint ground
    truth.  The adapters must also actually change the outputs (a zero
    delta would make the parity vacuous).  The off half pins the
    zero-overhead contract: an engine with the store ATTACHED but only
    dense requests must produce bit-identical tokens and exactly zero
    extra host syncs / decode traces vs a store-less engine."""
    from paddle_tpu.serving.engine import Engine
    from paddle_tpu.serving.lora import (AdapterStore, merge_adapter,
                                         random_adapter)

    cfg, state = _tiny_state()
    rank, alpha = 4, 8.0
    wa = random_adapter(cfg, rank, seed=1)
    wb = random_adapter(cfg, rank, seed=2)
    prompts = ([1, 2, 3, 4, 5, 6], [3, 4, 5, 6, 7, 8])

    def store():
        s = AdapterStore(cfg, capacity=2)
        s.register("a", wa, alpha=alpha)
        s.register("b", wb, alpha=alpha)
        return s

    def drive(st=None, lora=None, adapters=(None, None)):
        eng = Engine(config=cfg,
                     state=dict(state if st is None else st),
                     max_slots=2, page_size=4, sync_interval=1,
                     lora=lora)
        reqs = [eng.submit(list(p), _gen(8), adapter=ad)
                for p, ad in zip(prompts, adapters)]
        eng.run_until_complete(max_steps=400)
        return eng, [list(r.output_tokens) for r in reqs]

    dense_eng, dense_out = drive()
    off_eng, off_out = drive(lora=store())      # armed, requests dense
    live = store()
    eng, out = drive(lora=live, adapters=("a", "b"))
    _, merged_a = drive(st=merge_adapter(state, cfg, wa, alpha=alpha))
    _, merged_b = drive(st=merge_adapter(state, cfg, wb, alpha=alpha))
    snap = live.snapshot()
    return {
        "decode_traces": eng.decode_traces,
        "adapters_resident": len(snap["resident"]),
        "lora_loads": snap["loads"],
        "lora_evictions": snap["evictions"],
        "lora_parity_vs_merged": int(out == [merged_a[0], merged_b[1]]),
        "adapter_divergence": int(out[0] != dense_out[0]
                                  and out[1] != dense_out[1]),
        "lora_off_parity_vs_dense": int(off_out == dense_out),
        "host_syncs_delta_vs_off": off_eng.host_syncs
        - dense_eng.host_syncs,
        "decode_traces_delta_vs_off": (off_eng.decode_traces
                                       - dense_eng.decode_traces),
        "leaked_pages": eng.blocks.pool_accounting()["leak"],
    }


def scenario_batch_lane() -> dict:
    """Offline batch lane under interactive pressure, counters only.

    A 6-row JSONL job drip-feeds through a 2-slot preemptive engine
    with a 2-request window; two interactive priority-0 requests land
    mid-job and must preempt the batch residents (preemptions is
    pinned exact — the lane runs at priority -2, below every
    interactive class).  Gates: the job completes every row with zero
    failures, each preempted row resumes token-for-token (row outputs
    equal an idle engine's run of the same prompt), the interactive
    outputs equal an idle engine's (the lane never perturbs them), the
    whole dance reuses the ONE decode trace, and the pool balances."""
    import json as _json
    import tempfile
    from paddle_tpu.serving.lora import BatchJob

    eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                  enable_prefix_cache=False, preempt=True)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "batch.jsonl")
        with open(path, "w") as f:
            for i in range(6):
                f.write(_json.dumps({"prompt": [1, 2, 3, 4],
                                     "max_tokens": 6,
                                     "id": f"r{i}"}) + "\n")
        job = BatchJob.from_jsonl(path, window=2)
        interactive = []
        steps = 0
        while (job.pump(eng.submit) or eng.scheduler.has_work()) \
                and steps < 2000:
            if steps == 3:
                interactive += [eng.submit([5, 6, 7], _gen(4)),
                                eng.submit([6, 7, 8], _gen(4))]
            eng.step()
            steps += 1
        prog = job.progress()
        with open(prog["output_path"]) as f:
            rows = [_json.loads(line) for line in f]

    ref = _engine(max_slots=2, page_size=4, sync_interval=1,
                  enable_prefix_cache=False)
    ref_reqs = [ref.submit([5, 6, 7], _gen(4)),
                ref.submit([6, 7, 8], _gen(4))]
    batch_ref = ref.submit([1, 2, 3, 4], _gen(6))
    ref.run_until_complete(max_steps=200)
    batch_tokens = list(batch_ref.output_tokens)
    return {
        "batch_job_done": int(prog["status"] == "completed"),
        "batch_rows_completed": prog["completed"],
        "batch_rows_failed": prog["failed"],
        "batch_row_parity": int(
            len(rows) == 6
            and all(r.get("tokens") == batch_tokens for r in rows)),
        "interactive_parity_vs_idle": int(
            [list(r.output_tokens) for r in interactive]
            == [list(r.output_tokens) for r in ref_reqs]),
        "preemptions": eng.preemptions,
        "leaked_pages": eng.blocks.pool_accounting()["leak"],
        "decode_traces": eng.decode_traces,
        "goodput_ratio": _goodput(interactive),
    }


def scenario_tail_forensics() -> dict:
    """Tail-latency forensics, counters only.

    The overload workload (the preempt-and-swap half plus the chunked-
    prefill half of overload_degrade) runs twice — bare, and with a
    RequestLog attached behind an always-violating SLOTracker
    (nanosecond targets: every finished request trips every dimension,
    so the exemplar census is arithmetic, not timing).  Gates: every
    finished timeline's bucket seconds telescope exactly to its
    measured E2E (attribution_conservation_max_delta pinned at 0 —
    the advancing-cursor construction, checked against wall clocks),
    the lifecycle event count is exact across preemption / spill /
    resume / chunked admission, the reservoir keeps exactly one
    exemplar per request per dimension, greedy outputs are
    bit-identical to the forensics-off run, and arming the log adds
    ZERO host syncs / decode traces (the zero-overhead-off contract
    of the ``requestlog is not None`` seams)."""
    from paddle_tpu.observability.requestlog import RequestLog
    from paddle_tpu.serving.slo import SLOConfig, SLOTracker

    def slo():
        # nanosecond targets: any measured latency violates, so every
        # finished request lands in the exemplar store exactly once
        # per dimension (ttft, tpot, e2e)
        return SLOTracker(SLOConfig(ttft_s=1e-9, tpot_s=1e-9,
                                    e2e_s=1e-9))

    def drive(with_log):
        # --- preempt-and-swap half (decode -> preempted -> resume) ---
        log1 = RequestLog(k=8) if with_log else None
        eng = _engine(max_slots=2, page_size=4, sync_interval=1,
                      enable_prefix_cache=False, preempt=True,
                      slo=slo(), requestlog=log1)
        lo_a = eng.submit([1, 2, 3, 4, 5, 6], _gen(8))
        lo_b = eng.submit([3, 4, 5, 6, 7, 8], _gen(8))
        for _ in range(4):              # both residents mid-decode
            eng.step()
        hi = eng.submit([5, 6, 7, 8, 9, 10], _gen(8), priority=1)
        eng.run_until_complete(max_steps=400)

        # --- chunked-prefill half (chunk_gap attribution) ---
        log2 = RequestLog(k=8) if with_log else None
        eng2 = _engine(max_slots=2, page_size=4, sync_interval=1,
                       enable_prefix_cache=False, prefill_chunk=8,
                       slo=slo(), requestlog=log2)
        short = eng2.submit([1, 2, 3, 4, 5, 6], _gen(16))
        for _ in range(3):              # short request is decoding
            eng2.step()
        chunked = eng2.submit(list(range(1, 41)), _gen(4))
        eng2.run_until_complete(max_steps=400)
        return (eng, eng2, [lo_a, lo_b, hi, short, chunked],
                log1, log2)

    e_off, e2_off, ref_reqs, _, _ = drive(False)
    e_on, e2_on, reqs, log1, log2 = drive(True)
    s1, s2 = log1.snapshot(), log2.snapshot()
    return {
        "requests_tracked": (s1["requests_tracked"]
                             + s2["requests_tracked"]),
        "requests_finished": s1["finished"] + s2["finished"],
        "timeline_events": s1["events_total"] + s2["events_total"],
        "attribution_conservation_max_delta": max(
            s1["conservation_max_delta"],
            s2["conservation_max_delta"]),
        "exemplars_captured": (s1["exemplars"]["kept"]
                               + s2["exemplars"]["kept"]),
        "preemptions": e_on.preemptions,
        "prefill_chunks": e2_on.prefill_chunks,
        "forensics_parity_vs_off": int(
            [r.output_tokens for r in reqs]
            == [r.output_tokens for r in ref_reqs]),
        "leaked_pages": (e_on.blocks.pool_accounting()["leak"]
                         + e2_on.blocks.pool_accounting()["leak"]),
        "host_syncs_delta_vs_off": (
            e_on.host_syncs + e2_on.host_syncs
            - e_off.host_syncs - e2_off.host_syncs),
        "decode_traces_delta_vs_off": (
            e_on.decode_traces + e2_on.decode_traces
            - e_off.decode_traces - e2_off.decode_traces),
        "goodput_ratio": _goodput(reqs),
    }


SCENARIOS = {
    "steady_decode": scenario_steady_decode,
    "prefix_cache": scenario_prefix_cache,
    "deferred_sync": scenario_deferred_sync,
    "goodput_cancel": scenario_goodput_cancel,
    "tp_decode": scenario_tp_decode,
    "spec_decode": scenario_spec_decode,
    "fault_recovery": scenario_fault_recovery,
    "telemetry": scenario_telemetry,
    "overload_degrade": scenario_overload_degrade,
    "profiling": scenario_profiling,
    "usage_meter": scenario_usage_meter,
    "quant_decode": scenario_quant_decode,
    "lora_decode": scenario_lora_decode,
    "batch_lane": scenario_batch_lane,
    "tail_forensics": scenario_tail_forensics,
}


def run_scenarios(names, inject_retrace=False) -> dict:
    results = {}
    for name in names:
        fn = SCENARIOS[name]
        if name == "steady_decode":
            results[name] = fn(inject_retrace=inject_retrace)
        else:
            results[name] = fn()
    return results


def compare(results: dict, baseline: dict):
    """Direction-aware comparison.  Returns (regressions,
    improvements); a counter with no baseline entry is a regression
    (the gate must be told, via --update-baseline, that it exists)."""
    regressions, improvements = [], []
    for scen in sorted(results):
        base_scen = baseline.get(scen, {})
        for name in sorted(results[scen]):
            cur = results[scen][name]
            entry = {"scenario": scen, "counter": name, "current": cur,
                     "direction": DIRECTIONS.get(name, "exact")}
            if name not in base_scen:
                entry["baseline"] = None
                entry["why"] = "no baseline entry"
                regressions.append(entry)
                continue
            ref = base_scen[name]
            entry["baseline"] = ref
            d = entry["direction"]
            if d == "low":
                if cur > ref:
                    regressions.append(entry)
                elif cur < ref:
                    improvements.append(entry)
            elif d == "high":
                if cur < ref:
                    regressions.append(entry)
                elif cur > ref:
                    improvements.append(entry)
            else:
                if cur != ref:
                    regressions.append(entry)
    return regressions, improvements


def load_baseline(path: str) -> dict | None:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return data.get("scenarios", {})


def save_baseline(path: str, results: dict):
    with open(path, "w") as f:
        json.dump({"version": 1, "scenarios": results}, f, indent=2,
                  sort_keys=True)
        f.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perf_gate.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--scenarios", default=None,
                    help="comma-separated scenario subset "
                         f"(default: {' '.join(sorted(SCENARIOS))})")
    ap.add_argument("--json", action="store_true",
                    help="emit results as JSON")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/"
                         "perf_baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current counters as the new "
                         "baseline and exit 0")
    ap.add_argument("--list-scenarios", action="store_true",
                    help="list scenario names and exit")
    ap.add_argument("--inject-retrace", action="store_true",
                    help="test hook: force an extra decode-step trace "
                         "in steady_decode (the gate must exit 1)")
    args = ap.parse_args(argv)

    if args.list_scenarios:
        width = max(len(s) for s in SCENARIOS)
        for name in sorted(SCENARIOS):
            print(f"{name:<{width}}  {SCENARIOS[name].__doc__.splitlines()[0]}")
        return 0

    if args.scenarios:
        names = [s.strip() for s in args.scenarios.split(",")
                 if s.strip()]
        unknown = [s for s in names if s not in SCENARIOS]
        if unknown:
            print(f"perf_gate.py: unknown scenario(s): "
                  f"{', '.join(unknown)} (have: "
                  f"{', '.join(sorted(SCENARIOS))})", file=sys.stderr)
            return 2
    else:
        names = sorted(SCENARIOS)

    _force_cpu()
    results = run_scenarios(names,
                            inject_retrace=args.inject_retrace)

    if args.update_baseline:
        # subset runs only refresh the scenarios they ran
        merged = load_baseline(args.baseline) or {}
        merged.update(results)
        save_baseline(args.baseline, merged)
        print(f"wrote {len(merged)} scenario"
              f"{'' if len(merged) == 1 else 's'} to "
              f"{os.path.relpath(args.baseline, _REPO_ROOT)}")
        return 0

    baseline = load_baseline(args.baseline)
    if baseline is None:
        print(f"perf_gate.py: no baseline at {args.baseline} — run "
              "with --update-baseline first", file=sys.stderr)
        return 2

    regressions, improvements = compare(results, baseline)
    if args.json:
        sys.stdout.write(json.dumps(
            {"scenarios": results, "regressions": regressions,
             "improvements": improvements}, indent=2, sort_keys=True))
        sys.stdout.write("\n")
    else:
        for e in regressions:
            print(f"REGRESSION {e['scenario']}.{e['counter']}: "
                  f"{e['current']} vs baseline {e['baseline']} "
                  f"(want {e['direction']})"
                  + (f" — {e['why']}" if "why" in e else ""))
        for e in improvements:
            print(f"improved {e['scenario']}.{e['counter']}: "
                  f"{e['current']} vs baseline {e['baseline']} "
                  "(tighten with --update-baseline)")
        n_counters = sum(len(v) for v in results.values())
        print(f"{len(names)} scenario{'' if len(names) == 1 else 's'}, "
              f"{n_counters} counters: "
              f"{len(regressions)} regression"
              f"{'' if len(regressions) == 1 else 's'}, "
              f"{len(improvements)} improvement"
              f"{'' if len(improvements) == 1 else 's'}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
