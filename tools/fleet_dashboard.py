#!/usr/bin/env python
"""Live terminal dashboard over a serving fleet.

Usage:
    python tools/fleet_dashboard.py <host:port> [--interval 2] [--once]

Point it at a router or a single replica — both serve
``GET /debug/fleet`` (kind "router" aggregates per-replica summaries;
kind "replica" is one server's own census).  Renders:

  * an alert banner (firing anomaly rules, tagged per replica under a
    router);
  * the cluster / replica census: slots, queue, KV-page pool +
    fragmentation, SLO burn rates, spec acceptance, recovery counts;
  * latency quantiles (p50/p95/p99) estimated from the published
    cumulative buckets — merged ACROSS replicas before estimating,
    which is why replicas publish raw buckets and not quantiles;
  * the per-tenant cost table (page-seconds ledger) when the replicas
    run a usage meter (``FLAGS_serving_usage_meter``) — raw-merged
    across replicas under a router, heaviest bill first;
  * a diagnostics line per replica: continuous-profiler sweep counts
    and alert-triggered capture tallies (requires
    ``FLAGS_obs_profile_interval_s`` /
    ``FLAGS_obs_timeseries_interval_s`` on the replicas);
  * a tail-latency line per replica: the top latency-attribution
    cause across finished requests plus the worst SLO-violation
    exemplar (requires ``FLAGS_serving_request_log`` on the replicas);
  * sparkline history from each replica's recent time-series windows
    (requires ``FLAGS_obs_timeseries_interval_s`` on the replicas).

``--once`` prints a single deterministic frame and exits 0 (what the
tier-1 smoke test drives); the default is a live loop that redraws
every ``--interval`` seconds until Ctrl-C.

Works standalone — no paddle_tpu / jax import.  The bucket-quantile
estimator is shared with the library by loading
``paddle_tpu/observability/quantiles.py`` by file path (the module is
deliberately import-free to make that possible).
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import sys
import time

_BLOCKS = "▁▂▃▄▅▆▇█"


def _load_quantiles():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "paddle_tpu", "observability",
                        "quantiles.py")
    try:
        spec = importlib.util.spec_from_file_location("_pt_quantiles",
                                                      path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


_QUANTILES = _load_quantiles()


def fetch(address: str, path: str = "/debug/fleet", timeout: float = 5.0):
    host, _, port = address.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 80),
                                      timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise RuntimeError(f"GET {path} -> {resp.status}")
        return json.loads(body)
    finally:
        conn.close()


def spark(values, width: int = 24) -> str:
    """Unicode sparkline of the last ``width`` values; flat series
    render as a flat mid-line, empty series as '-'."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _BLOCKS[3] * len(vals)
    scale = (len(_BLOCKS) - 1) / (hi - lo)
    return "".join(_BLOCKS[int((v - lo) * scale)] for v in vals)


def _fmt(v, digits: int = 3) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "y" if v else "n"
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e12:
            return str(int(v))
        return f"{v:.{digits}g}"
    return str(v)


def _fmt_pct(v) -> str:
    return "-" if v is None else f"{100.0 * float(v):.1f}%"


def _fmt_ms(v) -> str:
    if v is None:
        return "-"
    if v == "+Inf":
        return "+Inf"
    return f"{float(v) * 1e3:g}ms"


def _table(rows, headers) -> str:
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]

    def line(r):
        return "  ".join(str(c).ljust(w)
                         for c, w in zip(r, widths)).rstrip()

    return "\n".join([line(headers),
                      line(["-" * w for w in widths])]
                     + [line(r) for r in rows])


def _alert_banner(alerts) -> list[str]:
    if not alerts:
        return []
    lines = [f"!! {len(alerts)} ALERT{'S' if len(alerts) > 1 else ''} "
             f"FIRING"]
    for a in alerts:
        where = f"[{a['replica']}] " if a.get("replica") else ""
        lines.append(f"  {where}{a.get('rule', '?')}: "
                     f"{a.get('condition', '')} "
                     f"(value={_fmt(a.get('value'))})")
    return lines


def _latency_lines(latency, indent: str = "  ") -> list[str]:
    """p50/p95/p99 per dimension from raw cumulative buckets, via the
    shared estimator.  ``latency`` maps dim -> {buckets, count, sum} or
    dim -> list of those (router view: one per replica, merged here)."""
    if not latency or _QUANTILES is None:
        return []
    lines = []
    for dim, snaps in sorted(latency.items()):
        if isinstance(snaps, dict):
            snaps = [snaps]
        merged, count, total = _QUANTILES.merge_series_buckets(snaps)
        if not count:
            continue
        qs = _QUANTILES.bucket_quantiles(merged, count,
                                         (0.5, 0.95, 0.99))
        lines.append(
            f"{indent}{dim:<5} n={count} avg={total / count * 1e3:.3g}ms"
            f" p50<={_fmt_ms(qs[0.5])} p95<={_fmt_ms(qs[0.95])}"
            f" p99<={_fmt_ms(qs[0.99])}")
    return ["Latency (bucket-estimated)"] + lines if lines else []


def _series_lines(series, names=None) -> list[str]:
    """Sparklines for selected series windows ({name: [[t, v], ...]})."""
    if not series:
        return []
    names = names or ("tok_s", "queue_depth", "active_slots",
                      "pages_free", "fragmentation", "burn_rate_max",
                      "acceptance_rate", "prefix_hit_rate")
    lines = []
    for name in names:
        pts = series.get(name)
        if not pts:
            continue
        vals = [p[1] for p in pts if p[1] is not None]
        if not vals:
            continue
        lines.append(f"  {name:<16} {spark(vals)}  last={_fmt(vals[-1])}")
    return ["History"] + lines if lines else []


def _diagnostics_line(fl, indent: str = "  ") -> list[str]:
    """Profiler + alert-evidence capture line from a replica's
    fleet_summary ("profiling" / "captures" keys).  Replicas that
    predate the profiling subsystem — or run with it off — publish
    neither key and produce no line."""
    prof = (fl or {}).get("profiling") or {}
    caps = (fl or {}).get("captures") or {}
    parts = []
    if prof:
        parts.append(
            f"profiler {_fmt(prof.get('samples'))} sweeps @ "
            f"{_fmt(prof.get('interval_s'))}s "
            f"({_fmt(prof.get('distinct_stacks'))} stacks, "
            f"{_fmt(prof.get('dropped'))} dropped)")
    if caps:
        part = (f"captures {_fmt(caps.get('captures'))} written / "
                f"{_fmt(caps.get('rate_limited'))} rate-limited")
        by_rule = caps.get("by_rule") or {}
        if by_rule:
            part += " (" + ", ".join(
                f"{k}={_fmt(v)}"
                for k, v in sorted(by_rule.items())) + ")"
        parts.append(part)
    return [indent + "diagnostics: " + ", ".join(parts)] if parts else []


def _adapters_line(fl, indent: str = "  ") -> list[str]:
    """Multi-LoRA + offline-lane line from a replica's fleet summary
    ("adapters" / "batches" keys).  Dense replicas with no adapter
    store and no batch jobs publish neither and produce no line."""
    ad = (fl or {}).get("adapters") or {}
    jobs = (fl or {}).get("batches") or {}
    parts = []
    if ad:
        resident = ad.get("resident") or []
        parts.append(
            f"{len(resident)}/{_fmt(ad.get('capacity'))} resident "
            f"(rank {_fmt(ad.get('rank'))}, "
            f"{_fmt(ad.get('loads'))} loads / "
            f"{_fmt(ad.get('evictions'))} evictions, "
            f"{len(ad.get('parked') or [])} parked)")
    if jobs:
        done = sum(1 for j in jobs.values()
                   if isinstance(j, dict)
                   and j.get("status") == "completed")
        rows = sum(int((j or {}).get("completed") or 0)
                   for j in jobs.values())
        parts.append(f"batch jobs {done}/{len(jobs)} completed "
                     f"({_fmt(rows)} rows out)")
    return [indent + "adapters: " + ", ".join(parts)] if parts else []


def _tail_line(fl, indent: str = "  ") -> list[str]:
    """Tail-latency forensics line from a replica's fleet summary
    ("tail" key, published when ``FLAGS_serving_request_log`` is on):
    the top latency cause across finished requests plus the worst
    SLO-violation exemplar.  Forensics-off replicas — and older
    builds — publish no key and produce no line."""
    tail = (fl or {}).get("tail") or {}
    if not tail:
        return []
    parts = [f"top cause {tail.get('top_cause', '?')} "
             f"({_fmt(tail.get('top_cause_s'))}s over "
             f"{_fmt(tail.get('finished'))} finished)"]
    worst = tail.get("worst_exemplar") or {}
    if worst:
        part = (f"worst {worst.get('dimension', '?')} "
                f"{_fmt(worst.get('score_s'))}s "
                f"req={worst.get('request')}")
        if worst.get("age_s") is not None:
            part += f" ({_fmt(worst.get('age_s'))}s ago)"
        parts.append(part)
    return [indent + "tail: " + ", ".join(parts)]


def _merge_usage(snaps):
    """Raw-merge per-replica usage snapshots: per-tenant counters sum,
    nested dicts (the slo verdict table) recurse, never averaging — a
    standalone copy of the ``merge_usage`` discipline from
    ``paddle_tpu.observability.usage``, kept here so the dashboard
    keeps its no-paddle_tpu/no-jax contract.  Returns the merged
    snapshot plus how many replicas actually published one (metering
    off / dead replicas are skipped, same as the router's own merge)."""
    def merge_row(dst, src):
        for k, v in src.items():
            if isinstance(v, dict):
                merge_row(dst.setdefault(k, {}), v)
            elif isinstance(v, (int, float)):
                dst[k] = dst.get(k, 0) + v
            else:
                dst.setdefault(k, v)

    tenants: dict = {}
    merged = 0
    for snap in snaps:
        if not isinstance(snap, dict) or not snap.get("tenants"):
            continue
        merged += 1
        for name, row in snap["tenants"].items():
            merge_row(tenants.setdefault(name, {}), row)
    return {"tenants": tenants}, merged


def _usage_lines(usage, title="Tenants (page-seconds ledger)",
                 top: int = 8) -> list[str]:
    """Per-tenant cost table from a usage-meter snapshot (the
    fleet_summary ``usage`` key) — replicas running without a meter
    publish none and produce no block.  Heaviest page-second bill
    (device + host) first: the first row is the fair-share target."""
    tenants = (usage or {}).get("tenants") or {}
    if not tenants:
        return []

    def bill(kv):
        row = kv[1]
        return -(float(row.get("page_seconds") or 0)
                 + float(row.get("host_page_seconds") or 0))

    ranked = sorted(tenants.items(), key=bill)
    rows = [(name,
             _fmt(row.get("requests")),
             _fmt(row.get("decode_tokens")),
             f"{float(row.get('page_seconds') or 0):.4g}",
             f"{float(row.get('host_page_seconds') or 0):.4g}",
             _fmt(row.get("preemptions")),
             _fmt(row.get("shed")))
            for name, row in ranked[:top]]
    lines = [title, _table(rows, ("tenant", "reqs", "decode", "page-s",
                                  "host-s", "preempt", "shed"))]
    if len(ranked) > top:
        lines.append(f"  (+{len(ranked) - top} more tenants)")
    cons = (usage or {}).get("conservation")
    if isinstance(cons, dict):
        lines.append(
            f"  conservation: "
            f"device_delta={_fmt(cons.get('device_delta'))} "
            f"host_delta={_fmt(cons.get('host_delta'))} "
            f"(both must be 0)")
    return lines


def _replica_row(address, up, fl):
    pool = (fl or {}).get("pool") or {}
    slots = (fl or {}).get("slots") or {}
    queue = (fl or {}).get("queue") or {}
    slo = (fl or {}).get("slo") or {}
    spec = (fl or {}).get("spec") or {}
    rec = (fl or {}).get("recovery") or {}
    series = (fl or {}).get("series") or {}
    tok = series.get("tok_s")
    tok_s = tok[-1][1] if tok else None
    return (address,
            "up" if up else "DOWN",
            f"{_fmt(slots.get('active'))}/{_fmt(slots.get('max'))}",
            _fmt(queue.get("depth")),
            f"{_fmt(pool.get('free'))}/{_fmt(pool.get('total'))}",
            _fmt_pct(pool.get("fragmentation_ratio")),
            _fmt(slo.get("max_burn_rate")),
            _fmt(tok_s),
            _fmt_pct(spec.get("spec_acceptance_rate"))
            if spec.get("spec_proposed") else "-",
            _fmt(rec.get("recoveries")))


_REPLICA_HEADERS = ("replica", "state", "slots", "queue",
                    "pages free", "frag", "burn", "tok/s",
                    "accept", "recov")


def render_router(payload) -> str:
    cluster = payload.get("cluster") or {}
    out = [f"FLEET  replicas={cluster.get('up', '?')}/"
           f"{cluster.get('replicas', '?')} up  "
           f"summaries={cluster.get('summaries', 0)}  "
           f"failovers={payload.get('failovers', 0)}"]
    out += _alert_banner(cluster.get("alerts_firing") or [])
    pages = cluster.get("pages") or {}
    slots = cluster.get("slots") or {}
    out.append(
        f"  slots {_fmt(slots.get('active'))}/{_fmt(slots.get('max'))}"
        f"  queue={_fmt(cluster.get('queue_depth'))}"
        f"  pages free={_fmt(pages.get('free'))}/"
        f"{_fmt(pages.get('total'))}"
        f" (live={_fmt(pages.get('live'))}"
        f" cached={_fmt(pages.get('cached'))})"
        f"  max burn={_fmt(cluster.get('max_burn_rate'))}"
        f"  prefix digests={_fmt(cluster.get('prefix_digests'))}")
    replicas = payload.get("replicas") or {}
    rows, latency = [], {}
    for addr, entry in sorted(replicas.items()):
        fl = entry.get("summary")
        rows.append(_replica_row(addr, entry.get("up"), fl))
        for dim, snap in ((fl or {}).get("latency") or {}).items():
            latency.setdefault(dim, []).append(snap)
    if rows:
        out += ["", _table(rows, _REPLICA_HEADERS)]
    lat = _latency_lines(latency)
    if lat:
        out += [""] + lat
    merged, n_meters = _merge_usage(
        (entry.get("summary") or {}).get("usage")
        for entry in replicas.values())
    use = _usage_lines(
        merged, title=f"Tenants (page-seconds ledger, raw-merged over "
                      f"{n_meters} replica{'s' if n_meters != 1 else ''})")
    if use:
        out += [""] + use
    for addr, entry in sorted(replicas.items()):
        fl = entry.get("summary") or {}
        adapters = _adapters_line(fl)
        diag = _diagnostics_line(fl)
        tail = _tail_line(fl)
        hist = _series_lines(fl.get("series"))
        if adapters or diag or tail or hist:
            out += (["", f"[{addr}]"] + adapters + diag + tail
                    + (hist[1:] if hist else []))
    return "\n".join(out)


def render_replica(payload) -> str:
    out = [f"REPLICA {payload.get('address', '?')}  "
           f"model={payload.get('model', '?')}"
           + ("  DRAINING" if payload.get("draining") else "")]
    alerts = (payload.get("alerts") or {}).get("firing") or []
    out += _alert_banner(alerts)
    out += ["", _table([_replica_row(payload.get("address", "?"),
                                     not payload.get("draining"),
                                     payload)],
                       _REPLICA_HEADERS)]
    prefix = payload.get("prefix") or {}
    out.append(f"  prefix cache: {_fmt(prefix.get('cached_pages'))} "
               f"pages held, {_fmt(prefix.get('cached_tokens'))} tokens"
               f" served from cache, hit rate "
               f"{_fmt_pct(prefix.get('hit_rate'))} "
               f"({_fmt(len(prefix.get('roots') or []))} root chains)")
    rec = payload.get("recovery") or {}
    if any(rec.values()):
        out.append(f"  recovery: {_fmt(rec.get('recoveries'))} rebuilds,"
                   f" {_fmt(rec.get('quarantines'))} quarantines,"
                   f" {_fmt(rec.get('replayed_requests'))} replays")
    out += _adapters_line(payload)
    out += _diagnostics_line(payload)
    out += _tail_line(payload)
    sched = payload.get("scheduling") or {}
    if any(v for k, v in sched.items() if k != "prefill_chunk"):
        line = (f"  overload: {_fmt(sched.get('prefill_chunks'))} "
                f"prefill chunks (max gap "
                f"{_fmt(sched.get('max_prefill_gap'))} tok), "
                f"{_fmt(sched.get('preemptions'))} preemptions, "
                f"{_fmt(sched.get('host_parked_pages'))} pages parked")
        shed = sched.get("shed_by_class") or {}
        if shed:
            line += ", shed " + ", ".join(
                f"{k}={_fmt(v)}" for k, v in sorted(shed.items()))
        out.append(line)
    use = _usage_lines(payload.get("usage"))
    if use:
        out += [""] + use
    lat = _latency_lines(payload.get("latency"))
    if lat:
        out += [""] + lat
    hist = _series_lines(payload.get("series"))
    if hist:
        out += [""] + hist
    return "\n".join(out)


def render(payload) -> str:
    if payload.get("kind") == "router":
        return render_router(payload)
    return render_replica(payload)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("address", help="router or replica host:port")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period for the live loop (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (deterministic; "
                         "what the smoke test runs)")
    args = ap.parse_args(argv)
    if args.once:
        print(render(fetch(args.address)))
        return 0
    try:
        while True:
            frame = render(fetch(args.address))
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
