"""Resource observatory (paddle_tpu/observability/resources.py).

Covers the process-wide ResourceTracker (goodput math, throughput/MFU,
memory sampling, compile ledger), the block manager's exact pool
accounting (the live+cached+free census invariant across admission,
CoW, eviction and rollback; fragmentation bands; per-seq footprints),
the engine/server integration (`resource_snapshot`, the
``GET /debug/resources`` endpoint, watchdog dumps embedding a
snapshot), and the resources.json dump + report rendering.
"""
import importlib.util
import json
import os
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.flags import FLAGS
from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
from paddle_tpu.observability.registry import default_registry
from paddle_tpu.observability.resources import (CompileLedger,
                                                resource_tracker)
from paddle_tpu.serving import (BlockManager, GenerationConfig,
                                ServingClient, Watchdog, create_engine,
                                serve)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(5)
    cfg = llama_tiny(vocab_size=128, hidden_size=64,
                     intermediate_size=128)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture()
def flag(request):
    """Set a FLAGS entry for one test and restore it afterwards."""
    saved = {}

    def _set(name, value):
        if name not in saved:
            saved[name] = FLAGS[name]
        FLAGS[name] = value

    yield _set
    FLAGS.update(saved)


# ------------------------------------------------------ resource tracker
class TestResourceTracker:
    def test_goodput_math(self):
        obs.reset()
        t = resource_tracker()
        assert t.snapshot()["goodput"]["ratio"] is None  # no finishes yet
        t.note_finish("length", 6)
        t.note_finish("eos", 3)
        t.note_finish("cancelled", 2)
        t.note_finish("deadline", 1)
        g = t.snapshot()["goodput"]
        assert g["useful_tokens"] == 9
        assert g["wasted_tokens"] == 3
        assert g["ratio"] == 9 / 12
        assert g["finishes"] == {"length": 1, "eos": 1,
                                 "cancelled": 1, "deadline": 1}
        # the same split lands on the serving_goodput_* metrics
        fam = default_registry().get("serving_goodput_tokens_total")
        assert fam.labels("useful").value == 9
        assert fam.labels("wasted").value == 3
        assert default_registry().get(
            "serving_goodput_ratio").value == pytest.approx(0.75)

    def test_throughput_and_mfu(self):
        obs.reset()
        t = resource_tracker()
        t.set_model(n_params=10**9, device_kind="TPU v5e")
        t.note_phase("decode", 1.5)
        t.note_phase("host_sync", 0.5)
        t.note_tokens(100)
        tp = t.snapshot()["throughput"]
        assert tp["tokens"] == 100
        assert tp["tokens_per_s"] == pytest.approx(50.0)
        assert tp["peak_flops"] == pytest.approx(197e12)
        # decode ~2 FLOPs/param/token
        assert tp["mfu"] == pytest.approx(50.0 * 2 * 10**9 / 197e12,
                                          abs=1e-6)

    def test_mfu_none_on_unknown_device(self):
        obs.reset()
        t = resource_tracker()
        t.set_model(n_params=1000, device_kind="cpu")
        t.note_phase("decode", 1.0)
        t.note_tokens(10)
        tp = t.snapshot()["throughput"]
        assert tp["peak_flops"] is None
        assert tp["mfu"] is None

    def test_peak_tflops_flag_overrides_device_table(self, flag):
        obs.reset()
        flag("FLAGS_resource_peak_tflops", 2.0)
        t = resource_tracker()
        t.set_model(n_params=10**9, device_kind="cpu")  # unknown kind
        t.note_phase("decode", 1.0)
        t.note_tokens(10)
        tp = t.snapshot()["throughput"]
        assert tp["peak_flops"] == pytest.approx(2e12)
        assert tp["mfu"] == pytest.approx(0.01)   # 10 tok/s * 2e9 / 2e12

    def test_sample_memory_never_raises_and_records_rss(self):
        obs.reset()
        t = resource_tracker()
        t.sample_memory()                   # CPU backend: no device stats
        mem = t.snapshot()["memory"]
        assert mem["samples"] == 1
        assert isinstance(mem["devices"], dict)
        assert mem["host_rss_bytes"] > 0    # linux /proc probe
        assert default_registry().get("host_rss_bytes").value > 0

    def test_compile_ledger(self):
        obs.reset()
        led = CompileLedger()
        led.record("decode_step", 0.25, "slots=4")
        led.record("decode_step", 0.5, "slots=4")       # dup signature
        led.record("prefill[8]", -1.0, "ids=[1,8]")     # clamped to 0
        snap = led.snapshot()
        assert snap["jits"]["decode_step"]["count"] == 2
        assert snap["jits"]["decode_step"]["seconds"] == pytest.approx(0.75)
        assert snap["jits"]["decode_step"]["signatures"] == ["slots=4"]
        assert snap["jits"]["prefill[8]"]["seconds"] == 0.0
        assert snap["total_compiles"] == 3
        assert snap["total_seconds"] == pytest.approx(0.75)

    def test_obs_reset_clears_tracker(self):
        t = resource_tracker()
        t.note_tokens(5)
        t.note_finish("length", 5)
        t.compiles.record("decode_step", 0.1)
        obs.reset()
        snap = t.snapshot()
        assert snap["throughput"]["tokens"] == 0
        assert snap["goodput"]["ratio"] is None
        assert snap["compiles"]["total_compiles"] == 0


# --------------------------------------------------- pool accounting
def _census_ok(bm):
    acc = bm.pool_accounting()
    assert acc["leak"] == 0
    assert acc["live"] + acc["cached"] + acc["free"] == acc["total"]
    return acc


class TestBlockManagerAccounting:
    def test_census_invariant_across_lifecycle(self):
        bm = BlockManager(num_pages=8, page_size=4,
                          enable_prefix_cache=True)
        _census_ok(bm)
        A = tuple(range(100, 112))              # 3 full chunks
        bm.allocate_seq(0, A, max_new_tokens=4)     # 4 pages, all fresh
        acc = _census_ok(bm)
        assert acc == {"live": 4, "cached": 0, "free": 4, "total": 8,
                       "allocated_total": 4, "leak": 0}
        # same prompt while A is live: shares 2 chain pages, acquires 2
        bm.allocate_seq(1, A, max_new_tokens=4)
        acc = _census_ok(bm)
        assert acc["live"] == 6                 # shared pages counted once
        assert acc["allocated_total"] == 6      # only fresh pages counted
        bm.free_seq(0)
        acc = _census_ok(bm)
        # A's registered 3rd chunk parks; its decode page frees
        assert acc["cached"] == 1 and acc["live"] == 4
        bm.free_seq(1)
        acc = _census_ok(bm)
        assert acc["live"] == 0
        # eviction under pressure: a disjoint prompt recycles LRU pages
        bm.allocate_seq(2, tuple(range(200, 212)), max_new_tokens=16)
        _census_ok(bm)
        bm.free_seq(2)
        _census_ok(bm)

    def test_rollback_not_counted_as_allocation(self):
        bm = BlockManager(num_pages=4, page_size=4,
                          enable_prefix_cache=True)
        A = tuple(range(10, 18))
        bm.allocate_seq(0, A, max_new_tokens=4)     # 3 pages
        assert bm.pages_allocated == 3
        # the suffix does not fit -> None; refs roll back, nothing counted
        assert bm.allocate_seq(1, A + tuple(range(90, 98)),
                               max_new_tokens=8) is None
        assert bm.pages_allocated == 3
        _census_ok(bm)
        bm.free_seq(0)
        _census_ok(bm)

    def test_free_pages_gauge_tracks_free_list(self):
        obs.reset()
        bm = BlockManager(num_pages=8, page_size=4)
        bm.allocate(0, 3)
        assert default_registry().get("serving_pages_free").value == 5
        bm.free_seq(0)
        assert default_registry().get("serving_pages_free").value == 8
        assert default_registry().get(
            "serving_pages_allocated_total").value == 3

    def test_fragmentation_zero_bands(self):
        bm = BlockManager(num_pages=4, page_size=4)
        assert bm.fragmentation(None) == 0.0    # nothing waiting
        assert bm.fragmentation(0) == 0.0
        assert bm.fragmentation(3) == 0.0       # all-free pool: usable
        bm.allocate(0, 4)
        assert bm.fragmentation(1) == 0.0       # idle == 0

    def test_fragmentation_one_when_unplaceable(self):
        bm = BlockManager(num_pages=4, page_size=4)
        bm.allocate(0, 3)
        # 1 idle page, request needs 2 -> every idle page is unusable
        assert bm.fragmentation(2) == 1.0

    def test_fragmentation_all_parked_pages_reclaimable(self):
        bm = BlockManager(num_pages=4, page_size=4,
                          enable_prefix_cache=True)
        bm.allocate_seq(0, tuple(range(50, 62)), max_new_tokens=4)
        bm.free_seq(0)                          # 3 parked chain pages
        # leaf-first peeling reclaims the whole parked chain
        assert bm.fragmentation(4) == 0.0
        _census_ok(bm)

    def test_fragmentation_pinned_parent_middle_band(self):
        # White-box: a parked parent whose cached child is LIVE cannot
        # be evicted (leaf-first), so it is idle-but-unusable.  Normal
        # admission always refs prefixes ahead of suffixes, so wire the
        # pathological shape directly.
        from collections import OrderedDict
        bm = BlockManager(num_pages=4, page_size=4,
                          enable_prefix_cache=True)
        bm._free = [2, 3]
        bm._lru = OrderedDict({0: None})        # page 0 parked
        bm._ref = {1: 1}                        # page 1 live
        bm._tables = {7: [1]}
        bm._children = {0: {1}}                 # 0's child is the live 1
        bm._key_of = {0: ((), tuple(range(4)))}
        # idle = 2 free + 1 parked; usable = 2 (page 0 pinned)
        assert bm._reclaimable() == 0
        assert bm.fragmentation(2) == pytest.approx(1 / 3)
        assert bm.fragmentation(3) == 1.0       # cannot place at all

    def test_record_fragmentation_publishes_gauge(self):
        obs.reset()
        bm = BlockManager(num_pages=4, page_size=4)
        bm.allocate(0, 3)
        ratio = bm.record_fragmentation(2)
        assert ratio == 1.0
        assert default_registry().get(
            "serving_page_fragmentation_ratio").value == 1.0

    def test_seq_footprint_shared_vs_exclusive(self):
        bm = BlockManager(num_pages=8, page_size=4,
                          enable_prefix_cache=True)
        A = tuple(range(100, 112))
        bm.allocate_seq(0, A, max_new_tokens=4)
        bm.allocate_seq(1, A, max_new_tokens=4)
        fp = bm.seq_footprint(1)
        assert fp == {"pages": 4, "shared": 2, "exclusive": 2,
                      "cached_len": 8, "committed_tokens": 12,
                      "committed_pages": 3}
        bm.free_seq(0)
        fp = bm.seq_footprint(1)
        assert fp["shared"] == 0 and fp["exclusive"] == 4
        assert bm.seq_footprint(99) == {"pages": 0, "shared": 0,
                                        "exclusive": 0, "cached_len": 0,
                                        "committed_tokens": 0,
                                        "committed_pages": 0}


# ------------------------------------------------- engine integration
class TestEngineResources:
    def test_resource_snapshot_and_compile_ledger(self, tiny_model):
        obs.reset()
        eng = create_engine(tiny_model, max_slots=2, page_size=16,
                            num_pages=64, max_model_len=128,
                            enable_prefix_cache=True)
        shared = np.arange(1, 20)
        a = eng.submit(shared, GenerationConfig(max_new_tokens=4))
        b = eng.submit(np.concatenate([shared, [21, 22]]),
                       GenerationConfig(max_new_tokens=4))
        eng.run_until_complete(max_steps=100)
        assert a.finish_reason == "length" and b.finish_reason == "length"

        snap = eng.resource_snapshot()
        assert snap["pool"]["leak"] == 0
        assert snap["pool"]["live"] == 0        # all requests finalized
        assert snap["pool"]["allocated_total"] > 0
        assert snap["requests"] == {}
        assert snap["counters"]["decode_steps"] > 0
        assert snap["counters"]["decode_traces"] == 1
        assert snap["counters"]["pages_allocated"] == \
            snap["pool"]["allocated_total"]
        for phase in ("prefill_s", "decode_s", "host_sync_s"):
            assert snap["timings"][phase] > 0.0

        st = eng.stats()
        assert st["decode_steps"] == snap["counters"]["decode_steps"]
        assert st["pages_allocated"] == snap["pool"]["allocated_total"]
        assert st["timings"] == snap["timings"]

        tr = resource_tracker().snapshot()
        jits = tr["compiles"]["jits"]
        assert "decode_step" in jits
        assert any(k.startswith("prefill[") for k in jits)
        assert all(v["seconds"] >= 0 for v in jits.values())
        assert tr["goodput"]["ratio"] == 1.0    # both finished by length
        assert tr["goodput"]["useful_tokens"] == 8
        assert tr["throughput"]["tokens"] == 8
        assert tr["throughput"]["n_params"] > 0
        assert tr["throughput"]["mfu"] is None  # cpu: no peak table entry
        # pool gauges read back through the registry match the engine
        assert tr["pool"]["total"] == 64
        assert tr["pool"]["in_use"] == 0

    def test_memory_polling_follows_flag(self, tiny_model, flag):
        obs.reset()
        flag("FLAGS_resource_memory_poll_steps", 1)   # poll every sync
        eng = create_engine(tiny_model, max_slots=1, page_size=16,
                            num_pages=32, max_model_len=64)
        eng.submit(np.arange(1, 6), GenerationConfig(max_new_tokens=3))
        eng.run_until_complete(max_steps=50)
        assert resource_tracker().snapshot()["memory"]["samples"] > 0

        obs.reset()
        flag("FLAGS_resource_memory_poll_steps", 0)   # disabled
        eng = create_engine(tiny_model, max_slots=1, page_size=16,
                            num_pages=32, max_model_len=64)
        eng.submit(np.arange(1, 6), GenerationConfig(max_new_tokens=3))
        eng.run_until_complete(max_steps=50)
        assert resource_tracker().snapshot()["memory"]["samples"] == 0

    def test_cancel_counts_as_wasted(self, tiny_model):
        obs.reset()
        eng = create_engine(tiny_model, max_slots=1, page_size=16,
                            num_pages=32, max_model_len=64)

        def cancel_after_2(req, tok):
            if req.num_generated >= 2:
                req.cancel()

        r = eng.submit(np.arange(1, 6),
                       GenerationConfig(max_new_tokens=20),
                       on_token=cancel_after_2)
        eng.run_until_complete(max_steps=100)
        assert r.finish_reason == "cancelled"
        g = resource_tracker().snapshot()["goodput"]
        assert g["useful_tokens"] == 0
        assert g["wasted_tokens"] == r.num_generated
        assert g["ratio"] == 0.0


# ------------------------------------------------------ server + watchdog
class _FakeEngine:
    def __init__(self, active=1):
        self.progress = 0
        self.scheduler = SimpleNamespace(active_count=active)


class TestServerResources:
    def test_debug_resources_endpoint(self, tiny_model):
        obs.reset()
        srv = serve(tiny_model, max_slots=2, page_size=16, num_pages=64,
                    max_model_len=128, enable_prefix_cache=True)
        try:
            cl = ServingClient(srv.address)
            cl.completion(list(range(1, 10)), max_tokens=3)
            doc = cl.request("GET", "/debug/resources")
        finally:
            srv.stop(drain_timeout=5.0)
        # process-wide tracker half
        assert doc["goodput"]["useful_tokens"] >= 3
        assert doc["compiles"]["total_compiles"] >= 2  # prefill + decode
        assert "devices" in doc["memory"]
        assert doc["throughput"]["tokens"] >= 3
        # engine-local half: exact census with a leak check
        eng = doc["engine"]
        assert eng["pool"]["leak"] == 0
        assert eng["pool"]["total"] == 64
        assert "fragmentation_ratio" in eng["pool"]
        assert eng["counters"]["decode_steps"] >= 1
        assert eng["timings"]["decode_s"] > 0

    def test_watchdog_dump_embeds_resource_snapshot(self, tmp_path):
        obs.reset()
        resource_tracker().note_finish("length", 4)
        eng = _FakeEngine()
        wd = Watchdog(eng, 10.0, dump_dir=str(tmp_path))
        wd.check(now=0.0)
        assert wd.check(now=10.0) is True
        doc = json.loads(open(wd.last_dump_path).read())
        res = doc["resources"]
        assert res["goodput"]["useful_tokens"] == 4
        assert set(res) >= {"memory", "compiles", "goodput",
                            "throughput", "pool"}


# ------------------------------------------------------- dump + report
class TestDumpAndReport:
    def test_dump_writes_resources_json_and_report_renders(self, tmp_path):
        obs.reset()
        t = resource_tracker()
        t.set_model(n_params=1234, device_kind="cpu")
        t.note_phase("decode", 0.5)
        t.note_tokens(10)
        t.note_finish("length", 8)
        t.note_finish("cancelled", 2)
        t.compiles.record("decode_step", 0.125, "slots=4")
        t.sample_memory()
        out = obs.dump(str(tmp_path))
        assert out == str(tmp_path)
        doc = json.loads((tmp_path / "resources.json").read_text())
        assert doc["goodput"]["ratio"] == 0.8
        assert doc["compiles"]["jits"]["decode_step"]["count"] == 1

        mod = _load_tool("metrics_report")
        metrics, retraces, trace, flight, resources, *_ = \
            mod._load(str(tmp_path))
        assert resources["goodput"]["useful_tokens"] == 8
        text = mod.report(metrics, retraces, trace=trace, flight=flight,
                          resources=resources)
        assert "Resources" in text
        assert "decode_step" in text
        assert "goodput" in text.lower()

    def test_report_tolerates_missing_resources(self, tmp_path):
        obs.reset()
        obs.dump(str(tmp_path))
        os.remove(tmp_path / "resources.json")
        mod = _load_tool("metrics_report")
        resources = mod._load(str(tmp_path))[4]
        assert resources is None
        metrics, retraces, trace, flight, resources, *_ = \
            mod._load(str(tmp_path))
        text = mod.report(metrics, retraces, trace=trace, flight=flight,
                          resources=resources)
        assert "Resources" not in text
