"""Layer tests (reference: test/legacy_test layer tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(42)


class TestLinear:
    def test_forward(self):
        paddle.seed(0)
        lin = nn.Linear(4, 3)
        x = rng.randn(2, 4).astype(np.float32)
        out = lin(paddle.to_tensor(x))
        ref = x @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-5)

    def test_backward(self):
        lin = nn.Linear(4, 3)
        x = paddle.to_tensor(rng.randn(2, 4).astype(np.float32))
        loss = lin(x).sum()
        loss.backward()
        assert lin.weight.grad is not None
        assert lin.bias.grad is not None
        np.testing.assert_allclose(lin.bias.grad.numpy(), [2.0] * 3)

    def test_state_dict(self):
        lin = nn.Linear(4, 3)
        sd = lin.state_dict()
        assert set(sd.keys()) == {"weight", "bias"}
        lin2 = nn.Linear(4, 3)
        lin2.set_state_dict(sd)
        np.testing.assert_allclose(lin2.weight.numpy(), lin.weight.numpy())


class TestConv2D:
    def test_forward_shape(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = paddle.to_tensor(rng.randn(2, 3, 16, 16).astype(np.float32))
        out = conv(x)
        assert out.shape == [2, 8, 8, 8]

    def test_vs_manual(self):
        conv = nn.Conv2D(1, 1, 2, bias_attr=False)
        x = rng.randn(1, 1, 3, 3).astype(np.float32)
        out = conv(paddle.to_tensor(x)).numpy()
        w = conv.weight.numpy()[0, 0]
        expect = np.zeros((2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                expect[i, j] = (x[0, 0, i:i + 2, j:j + 2] * w).sum()
        np.testing.assert_allclose(out[0, 0], expect, atol=1e-5)

    def test_grad(self):
        conv = nn.Conv2D(2, 4, 3, padding=1)
        x = paddle.to_tensor(rng.randn(1, 2, 8, 8).astype(np.float32),
                             stop_gradient=False)
        conv(x).sum().backward()
        assert conv.weight.grad is not None
        assert x.grad is not None
        assert x.grad.shape == [1, 2, 8, 8]

    def test_groups(self):
        conv = nn.Conv2D(4, 8, 3, groups=2)
        x = paddle.to_tensor(rng.randn(1, 4, 8, 8).astype(np.float32))
        assert conv(x).shape == [1, 8, 6, 6]


class TestNorms:
    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = rng.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1
        bn.train()
        out = bn(paddle.to_tensor(x))
        # normalized output: per-channel mean ~0, var ~1
        o = out.numpy()
        np.testing.assert_allclose(o.mean(axis=(0, 2, 3)), np.zeros(3),
                                   atol=1e-4)
        np.testing.assert_allclose(o.var(axis=(0, 2, 3)), np.ones(3),
                                   atol=1e-3)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), np.zeros(3))
        bn.eval()
        out_eval = bn(paddle.to_tensor(x))
        assert out_eval.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = rng.randn(2, 4, 8).astype(np.float32)
        out = ln(paddle.to_tensor(x)).numpy()
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(16)
        x = rng.randn(2, 16).astype(np.float32)
        out = rn(paddle.to_tensor(x)).numpy()
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        x = rng.randn(2, 4, 3, 3).astype(np.float32)
        out = gn(paddle.to_tensor(x))
        assert out.shape == [2, 4, 3, 3]


class TestActivationsDropout:
    def test_relu_gelu(self):
        x = rng.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(F.relu(paddle.to_tensor(x)).numpy(),
                                   np.maximum(x, 0))
        g = F.gelu(paddle.to_tensor(x)).numpy()
        from scipy.stats import norm
        ref = x * norm.cdf(x)
        np.testing.assert_allclose(g, ref, atol=1e-4)

    def test_softmax(self):
        x = rng.randn(2, 5).astype(np.float32)
        out = F.softmax(paddle.to_tensor(x), axis=-1).numpy()
        e = np.exp(x - x.max(-1, keepdims=True))
        np.testing.assert_allclose(out, e / e.sum(-1, keepdims=True),
                                   atol=1e-5)

    def test_dropout_train_eval(self):
        paddle.seed(1)
        x = paddle.ones([1000])
        d = nn.Dropout(0.5)
        d.train()
        out = d(x).numpy()
        frac_zero = (out == 0).mean()
        assert 0.4 < frac_zero < 0.6
        kept = out[out != 0]
        np.testing.assert_allclose(kept, 2.0 * np.ones_like(kept))
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), np.ones(1000))


class TestLosses:
    def test_cross_entropy(self):
        logits = rng.randn(4, 5).astype(np.float32)
        labels = np.array([0, 2, 4, 1])
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(float(loss), ref, atol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = rng.randn(4, 5).astype(np.float32)
        labels = np.array([0, -100, 4, -100])
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels), ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 4]]).mean()
        np.testing.assert_allclose(float(loss), ref, atol=1e-5)

    def test_cross_entropy_soft_label(self):
        logits = rng.randn(3, 4).astype(np.float32)
        soft = rng.rand(3, 4).astype(np.float32)
        soft /= soft.sum(-1, keepdims=True)
        loss = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(soft), soft_label=True)
        logp = logits - logits.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        ref = (-(soft * logp).sum(-1)).mean()
        np.testing.assert_allclose(float(loss), ref, atol=1e-5)

    def test_mse_l1(self):
        a = rng.randn(3, 4).astype(np.float32)
        b = rng.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            float(F.mse_loss(paddle.to_tensor(a), paddle.to_tensor(b))),
            ((a - b) ** 2).mean(), atol=1e-5)
        np.testing.assert_allclose(
            float(F.l1_loss(paddle.to_tensor(a), paddle.to_tensor(b))),
            np.abs(a - b).mean(), atol=1e-6)

    def test_bce_with_logits(self):
        logit = rng.randn(4).astype(np.float32)
        label = (rng.rand(4) > 0.5).astype(np.float32)
        out = F.binary_cross_entropy_with_logits(
            paddle.to_tensor(logit), paddle.to_tensor(label))
        p = 1 / (1 + np.exp(-logit))
        ref = -(label * np.log(p) + (1 - label) * np.log(1 - p)).mean()
        np.testing.assert_allclose(float(out), ref, atol=1e-5)


class TestContainersEmbedding:
    def test_sequential(self):
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.to_tensor(rng.randn(3, 4).astype(np.float32))
        assert model(x).shape == [3, 2]
        assert len(model.parameters()) == 4

    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        idx = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        out = emb(idx)
        assert out.shape == [2, 2, 4]
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])

    def test_embedding_grad(self):
        emb = nn.Embedding(10, 4)
        idx = paddle.to_tensor(np.array([1, 1, 2]))
        emb(idx).sum().backward()
        g = emb.weight.grad.numpy()
        np.testing.assert_allclose(g[1], 2 * np.ones(4))
        np.testing.assert_allclose(g[2], np.ones(4))
        np.testing.assert_allclose(g[0], np.zeros(4))

    def test_pooling(self):
        x = paddle.to_tensor(rng.randn(1, 2, 4, 4).astype(np.float32))
        assert nn.MaxPool2D(2)(x).shape == [1, 2, 2, 2]
        assert nn.AvgPool2D(2)(x).shape == [1, 2, 2, 2]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
        xm = x.numpy()
        np.testing.assert_allclose(
            nn.AdaptiveAvgPool2D(1)(x).numpy()[..., 0, 0],
            xm.mean(axis=(2, 3)), atol=1e-6)

    def test_named_parameters(self):
        model = nn.Sequential(nn.Linear(2, 3), nn.Linear(3, 4))
        names = [n for n, _ in model.named_parameters()]
        assert names == ["0.weight", "0.bias", "1.weight", "1.bias"]


class TestMultiHeadAttention:
    def test_shapes(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.to_tensor(rng.randn(2, 6, 16).astype(np.float32))
        out = mha(x)
        assert out.shape == [2, 6, 16]

    def test_matches_manual_softmax(self):
        mha = nn.MultiHeadAttention(8, 2, dropout=0.0)
        x = paddle.to_tensor(rng.randn(1, 3, 8).astype(np.float32))
        out = mha(x).numpy()
        # manual reference
        q = (x.numpy() @ mha.q_proj.weight.numpy() + mha.q_proj.bias.numpy())
        k = (x.numpy() @ mha.k_proj.weight.numpy() + mha.k_proj.bias.numpy())
        v = (x.numpy() @ mha.v_proj.weight.numpy() + mha.v_proj.bias.numpy())
        q = q.reshape(1, 3, 2, 4).transpose(0, 2, 1, 3)
        k = k.reshape(1, 3, 2, 4).transpose(0, 2, 1, 3)
        v = v.reshape(1, 3, 2, 4).transpose(0, 2, 1, 3)
        s = q @ k.transpose(0, 1, 3, 2) / 2.0
        e = np.exp(s - s.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        o = (p @ v).transpose(0, 2, 1, 3).reshape(1, 3, 8)
        ref = o @ mha.out_proj.weight.numpy() + mha.out_proj.bias.numpy()
        np.testing.assert_allclose(out, ref, atol=1e-4)

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.to_tensor(rng.randn(2, 5, 16).astype(np.float32))
        assert enc(x).shape == [2, 5, 16]


def test_fold_unfold_channelshuffle_softmax2d_pairwise():
    """New layers vs torch (reference: nn/layer/common.py Fold,
    vision.py ChannelShuffle, activation.py Softmax2D, distance.py)."""
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu import nn
    import paddle_tpu.nn.functional as F

    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 8, 8)).astype("float32")

    u = F.unfold_(paddle.to_tensor(x), 3, strides=2, paddings=1)
    ut = torch.nn.functional.unfold(torch.tensor(x), 3, stride=2, padding=1)
    np.testing.assert_array_equal(u.numpy(), ut.numpy())

    f = nn.Fold((8, 8), 3, strides=2, paddings=1)(u)
    ft = torch.nn.functional.fold(ut, (8, 8), 3, stride=2, padding=1)
    np.testing.assert_array_equal(f.numpy(), ft.numpy())

    s2 = nn.Softmax2D()(paddle.to_tensor(x))
    assert np.abs(s2.numpy().sum(1) - 1).max() < 1e-6

    cs = nn.ChannelShuffle(3)(paddle.to_tensor(x))
    cst = torch.nn.functional.channel_shuffle(torch.tensor(x), 3)
    np.testing.assert_array_equal(cs.numpy(), cst.numpy())

    a = rng.standard_normal((4, 5)).astype("float32")
    b = rng.standard_normal((4, 5)).astype("float32")
    pd = nn.PairwiseDistance()(paddle.to_tensor(a), paddle.to_tensor(b))
    pdt = torch.nn.PairwiseDistance()(torch.tensor(a), torch.tensor(b))
    np.testing.assert_allclose(pd.numpy(), pdt.numpy(), rtol=1e-5)
