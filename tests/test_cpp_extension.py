"""Custom C++ op toolchain: compile with g++, run eager + under jit.

Reference test style: test/custom_op/ (compile user op, check output and
use inside a network)."""
import os
import tempfile
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

CPP = """
#include <cstdint>
#include <cmath>

extern "C" void square_plus_one(const void* xv, void* yv, int64_t n) {
  const float* x = static_cast<const float*>(xv);
  float* y = static_cast<float*>(yv);
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] * x[i] + 1.0f;
}

extern "C" void hypot_op(const void* av, const void* bv, void* yv,
                         int64_t n) {
  const float* a = static_cast<const float*>(av);
  const float* b = static_cast<const float*>(bv);
  float* y = static_cast<float*>(yv);
  for (int64_t i = 0; i < n; ++i) y[i] = std::sqrt(a[i]*a[i] + b[i]*b[i]);
}
"""


@pytest.fixture(scope="module")
def ext():
    d = tempfile.mkdtemp()
    src = os.path.join(d, "ops.cc")
    with open(src, "w") as f:
        f.write(textwrap.dedent(CPP))
    return cpp_extension.load(name="testext", sources=[src])


def test_elementwise_custom_op(ext):
    f = ext.elementwise_op("square_plus_one")
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    y = f(x)
    np.testing.assert_allclose(y.numpy(), x.numpy() ** 2 + 1)


def test_binary_custom_op(ext):
    f = ext.binary_op("hypot_op")
    a = paddle.to_tensor(np.full((4,), 3.0, "float32"))
    b = paddle.to_tensor(np.full((4,), 4.0, "float32"))
    np.testing.assert_allclose(f(a, b).numpy(), np.full((4,), 5.0), rtol=1e-6)


def test_custom_op_under_jit(ext):
    import jax
    f = ext.elementwise_op("square_plus_one")
    body = f.__op_body__

    @jax.jit
    def g(x):
        return body(x) * 2.0

    out = g(np.arange(4, dtype="float32"))
    np.testing.assert_allclose(np.asarray(out),
                               np.arange(4, dtype="float32") ** 2 * 2 + 2)


def test_compile_error_raises():
    d = tempfile.mkdtemp()
    src = os.path.join(d, "bad.cc")
    with open(src, "w") as f:
        f.write("this is not C++")
    with pytest.raises(RuntimeError, match="compile failed"):
        cpp_extension.load(name="bad", sources=[src])


def test_run_check():
    from paddle_tpu.utils import run_check
    run_check()
