"""static/distributed namespace completion tests (reference:
test/legacy_test/test_backward.py, test_ema.py, test_accuracy_op.py,
test/collective api surface)."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.static as static
import paddle_tpu.distributed as dist

rng = np.random.RandomState(13)


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestStaticAutodiff:
    def _build(self):
        paddle.enable_static()
        prog = static.Program()
        start = static.Program()
        with static.program_guard(prog, start):
            x = static.data("x", [4, 3], "float32")
            lin = paddle.nn.Linear(3, 2)
            y = lin(x)
            loss = paddle.sum(y)
        return prog, x, lin, loss

    def teardown_method(self):
        paddle.disable_static()

    def test_append_backward_grads_fetchable(self):
        prog, x, lin, loss = self._build()
        pairs = static.append_backward(loss)
        assert len(pairs) == 2
        exe = static.Executor()
        xv = rng.randn(4, 3).astype(np.float32)
        grad_names = [g.name for _, g in pairs]
        outs = exe.run(prog, feed={"x": xv},
                       fetch_list=[loss] + grad_names)
        # dLoss/dW = sum over batch of x (broadcast to [3,2])
        expect_w = np.tile(xv.sum(0)[:, None], (1, 2))
        got = {g: o for g, o in zip(grad_names, outs[1:])}
        wg = got[f"{lin.weight.name}@GRAD"]
        np.testing.assert_allclose(wg, expect_w, rtol=1e-5)
        bg = got[f"{lin.bias.name}@GRAD"]
        np.testing.assert_allclose(bg, np.full(2, 4.0), rtol=1e-5)

    def test_gradients_wrt_input(self):
        prog, x, lin, loss = self._build()
        (gx,) = static.gradients([loss], [x])
        exe = static.Executor()
        xv = rng.randn(4, 3).astype(np.float32)
        out = exe.run(prog, feed={"x": xv}, fetch_list=[gx])[0]
        expect = np.tile(lin.weight.numpy().sum(1), (4, 1))
        np.testing.assert_allclose(out, expect, rtol=1e-5)


class TestStaticMetricsOps:
    def teardown_method(self):
        paddle.disable_static()

    def test_accuracy(self):
        inp = t(np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32))
        lab = t(np.array([1, 0, 0], np.int64))
        acc = float(static.accuracy(inp, lab))
        np.testing.assert_allclose(acc, 2.0 / 3.0, rtol=1e-6)

    def test_auc(self):
        score = t(np.array([[0.9, 0.1], [0.3, 0.7], [0.6, 0.4],
                            [0.2, 0.8]], np.float32))
        lab = t(np.array([0, 1, 0, 1], np.int64))
        a, _ = static.auc(score, lab)
        np.testing.assert_allclose(float(a), 1.0)  # perfectly ranked

    def test_print_and_pyfunc(self):
        x = t(np.ones((2, 2), np.float32))
        out = static.Print(x, message="dbg")
        np.testing.assert_allclose(out.numpy(), x.numpy())
        y = t(np.zeros((2, 2), np.float32))
        res = static.py_func(lambda a: a * 3.0, x, y)
        np.testing.assert_allclose(res.numpy(), 3 * np.ones((2, 2)))


class TestEMAAndSerialization:
    def teardown_method(self):
        paddle.disable_static()

    def test_ema_apply_restore(self):
        paddle.enable_static()
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [1, 2], "float32")
            lin = paddle.nn.Linear(2, 2)
            lin(x)  # registers the params with the program
            ema = static.ExponentialMovingAverage(0.5)
        w0 = lin.weight.numpy().copy()
        ema.update()
        lin.weight.set_value(t(w0 * 3))
        ema.update()
        with ema.apply():
            applied = lin.weight.numpy().copy()
        restored = lin.weight.numpy()
        np.testing.assert_allclose(restored, w0 * 3, rtol=1e-6)
        assert not np.allclose(applied, restored)

    def test_program_serialization(self, tmp_path):
        paddle.enable_static()
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [2, 2], "float32")
            lin = paddle.nn.Linear(2, 2)
            y = lin(x)
        blob = static.serialize_persistables(program=prog)
        p = tmp_path / "persist.bin"
        static.save_to_file(str(p), blob)
        w_orig = lin.weight.numpy().copy()
        lin.weight.set_value(t(np.zeros((2, 2), np.float32)))
        static.deserialize_persistables(prog, static.load_from_file(str(p)))
        np.testing.assert_allclose(lin.weight.numpy(), w_orig)

    def test_build_strategy_compiled_program(self):
        paddle.enable_static()
        prog = static.Program()
        bs = static.BuildStrategy()
        cp = static.CompiledProgram(prog, build_strategy=bs)
        assert cp._program is prog


class TestDistCompat:
    def test_strategy_and_attrs(self):
        s = dist.Strategy({"pipeline": {"enable": True,
                                        "micro_batch_size": 4}})
        assert s.pipeline.enable and s.pipeline.micro_batch_size == 4
        assert not s.sharding.enable
        mesh = dist.ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
        da = dist.DistAttr(mesh, ["x", None])
        assert "x" in repr(da)

    def test_to_static_dist_model(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        model = nn.Linear(4, 2)
        o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
        dm = dist.to_static(model, loss=lambda a, b: ((a - b) ** 2).mean(),
                            optimizer=o)
        x = t(rng.randn(8, 4).astype(np.float32))
        y = t(rng.randn(8, 2).astype(np.float32))
        dm.train()
        l0 = float(dm(x, y))
        for _ in range(5):
            l1 = float(dm(x, y))
        assert l1 < l0
        dm.eval()
        le = dm(x, y)
        assert np.isfinite(float(le))
        sd = dm.state_dict()
        assert any(k.startswith("opt.") for k in sd)
        dm.set_state_dict(sd)

    def test_object_collectives_single_process(self):
        objs = ["a", {"b": 1}]
        assert dist.broadcast_object_list(objs) == objs
        out = []
        dist.scatter_object_list(out, ["x", "y"])
        assert out  # rank 0 gets its share
        assert dist.is_available()
        assert dist.get_backend() in ("GLOO", "XCCL_TPU")
        dist.destroy_process_group()
        assert dist.ReduceType.kRedSum == 0

    def test_alltoall_single_identity(self):
        src = t(rng.randn(4, 2).astype(np.float32))
        dst = t(np.zeros((4, 2), np.float32))
        dist.alltoall_single(dst, src)
        np.testing.assert_allclose(dst.numpy(), src.numpy())

    def test_dtensor_from_fn_and_entries(self):
        mesh = dist.ProcessMesh(list(range(1)), dim_names=["dp"])
        d = dist.dtensor_from_fn(paddle.ones, mesh, [dist.Replicate()],
                                 [4, 4])
        assert d.shape == [4, 4]
        e = dist.CountFilterEntry(5)
        assert e.count_filter == 5
        with pytest.raises(ValueError):
            dist.ProbabilityEntry(1.5)
        with pytest.raises(NotImplementedError):
            dist.InMemoryDataset()

    def test_split_mp_linear(self):
        x = t(rng.randn(4, 8).astype(np.float32))
        out = dist.split(x, (8, 6), num_partitions=1, operation="linear",
                         axis=1)
        assert out.shape == [4, 6]
        emb = dist.split(t(np.array([1, 3], np.int64)), (10, 4),
                         operation="embedding")
        assert emb.shape == [2, 4]

    def test_shard_dataloader_passthrough(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        xs = t(rng.randn(8, 3).astype(np.float32))
        ys = t(rng.randn(8, 1).astype(np.float32))
        loader = DataLoader(TensorDataset([xs, ys]), batch_size=4)
        mesh = dist.ProcessMesh(list(range(1)), dim_names=["dp"])
        sharded = dist.shard_dataloader(loader, [mesh])
        batches = list(iter(sharded))
        assert len(batches) == len(loader)

    def test_io_worker_info(self):
        import paddle_tpu.io as pio
        assert pio.get_worker_info() is None


class TestCommWatchdog:
    def test_detects_hung_task(self):
        from paddle_tpu.distributed.watchdog import CommTaskManager
        mgr = CommTaskManager(default_timeout=0.2, poll_interval=0.05)
        hung = []
        mgr.register_hang_hook(lambda task: hung.append(task.name))
        task = mgr.start_task("all_reduce", group="dp")
        import time
        time.sleep(0.6)
        assert hung == ["all_reduce"]
        assert task.flagged
        mgr.end_task(task)
        assert mgr.in_flight() == []
        mgr.shutdown()

    def test_completed_task_not_flagged(self):
        from paddle_tpu.distributed.watchdog import CommTaskManager
        mgr = CommTaskManager(default_timeout=0.3, poll_interval=0.05)
        hung = []
        mgr.register_hang_hook(lambda t_: hung.append(t_))
        with_task = mgr.start_task("broadcast")
        mgr.end_task(with_task)
        import time
        time.sleep(0.5)
        assert not hung
        mgr.shutdown()

    def test_comm_guard_wraps_wait(self):
        import numpy as np
        import paddle_tpu.distributed as dist
        x = paddle.to_tensor(np.ones(4, np.float32))
        with dist.comm_guard("custom_op") as task:
            assert not task.done
        assert task.done or task not in \
            dist.get_comm_task_manager().in_flight()
        dist.wait(x)  # exercises the guarded path


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
class TestDistModelCompiledBridge:
    """Round-3 (VERDICT weak #9): dist.to_static must COMPILE a sharded
    step (the Engine partition/plan bridge), not replay eager ops —
    params keep their mesh placements and the step traces once."""

    def test_sharded_params_compiled_step(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        mesh = dist.auto_mesh(dp=2, mp=4)
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 16))
        # tensor-parallel placements on the linear weights
        dist.shard_layer(model, mesh,
                         shard_fn=lambda name, layer, m: None)
        w0 = model[0].weight
        w0._data = jax.device_put(
            w0._data, jax.sharding.NamedSharding(
                mesh.jax_mesh, jax.sharding.PartitionSpec(None, "mp")))

        o = opt.SGD(learning_rate=0.05, parameters=model.parameters())
        traces = []

        def loss(a, b):
            traces.append(1)          # counts TRACES, not executions
            return ((a - b) ** 2).mean()

        dm = dist.to_static(model, loss=loss, optimizer=o)
        dm.train()
        x = t(rng.randn(8, 16).astype(np.float32))
        y = t(rng.randn(8, 16).astype(np.float32))
        l0 = float(dm(x, y))
        float(dm(x, y))   # step 2 retraces once: the lazily-created
        n_stable = len(traces)   # optimizer accumulators join the carry
        losses = [float(dm(x, y)) for _ in range(5)]
        assert losses[-1] < l0
        # compiled: steady-state steps replay the XLA program, no retrace
        assert len(traces) == n_stable, (len(traces), n_stable)
        # the tp placement survived the compiled updates
        spec = model[0].weight._data.sharding.spec
        assert "mp" in str(spec), spec

    def test_eval_mode_compiles_too(self):
        import paddle_tpu.nn as nn
        model = nn.Linear(4, 2)
        traces = []

        def loss(a, b):
            traces.append(1)
            return ((a - b) ** 2).mean()

        dm = dist.to_static(model, loss=loss, optimizer=None)
        dm.eval()
        x = t(rng.randn(8, 4).astype(np.float32))
        y = t(rng.randn(8, 2).astype(np.float32))
        v1 = float(dm(x, y))
        n1 = len(traces)
        v2 = float(dm(x, y))
        assert np.isfinite(v1) and v1 == v2
        assert len(traces) == n1          # cached program
        dm.predict()
        out = dm(x)
        assert out.shape == [8, 2]

    def test_train_without_optimizer_returns_loss(self):
        import paddle_tpu.nn as nn
        model = nn.Linear(4, 2)
        dm = dist.to_static(model, loss=lambda a, b: ((a - b) ** 2).mean())
        dm.train()
        x = t(rng.randn(8, 4).astype(np.float32))
        y = t(rng.randn(8, 2).astype(np.float32))
        out = dm(x, y)
        assert out.shape == [] and np.isfinite(float(out))

    def test_bn_buffers_persist_through_compiled_eval(self):
        import paddle_tpu.nn as nn
        model = nn.Sequential(nn.Linear(4, 6), nn.BatchNorm1D(6))
        dm = dist.to_static(model, loss=lambda a, b: (a ** 2).mean())
        dm.train()   # no optimizer: compiled eval path, train-mode BN
        x = t((rng.randn(16, 4) * 3 + 5).astype(np.float32))
        y = t(rng.randn(16, 6).astype(np.float32))
        before = np.asarray(model[1]._mean._data).copy()
        dm(x, y)
        after = np.asarray(model[1]._mean._data)
        assert not np.allclose(before, after)   # running stats advanced
