"""KV-cache generation vs full-forward iterative decode.

Reference analog: decoding-path parity tests for the fused attention /
masked_multihead inference kernels (test/legacy_test/
test_masked_multihead_attention_op.py style): the cached one-token step
must reproduce the full forward."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import llama_tiny, LlamaForCausalLM
from paddle_tpu.models import generation as G


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = llama_tiny(num_hidden_layers=2, hidden_size=64,
                     intermediate_size=128, vocab_size=97,
                     num_attention_heads=4, num_key_value_heads=2,
                     max_position_embeddings=64)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _reference_greedy(model, ids, n_new):
    """Naive decode: full forward each step, argmax of last logits."""
    cur = np.asarray(ids)
    with paddle.no_grad():
        for _ in range(n_new):
            logits = model(paddle.to_tensor(cur)).numpy()
            nxt = logits[:, -1].argmax(-1)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
    return cur


def test_greedy_matches_full_forward(model):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 97, (2, 7))
    ref = _reference_greedy(model, ids, 6)
    out = G.generate(model, paddle.to_tensor(ids), max_new_tokens=6)
    np.testing.assert_array_equal(out.numpy(), ref)


def test_ragged_prompts(model):
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 97, (2, 8))
    lengths = np.array([8, 5])
    ids[1, 5:] = 0      # right padding
    out = G.generate(model, paddle.to_tensor(ids), max_new_tokens=4,
                     lengths=paddle.to_tensor(lengths))
    # row 0 (full prompt) must match the unpadded reference
    ref0 = _reference_greedy(model, ids[:1], 4)
    np.testing.assert_array_equal(out.numpy()[0], ref0[0])
    # row 1 must match decoding its 5-token prompt alone
    ref1 = _reference_greedy(model, ids[1:2, :5], 4)
    np.testing.assert_array_equal(out.numpy()[1, 8:], ref1[0, 5:])


def test_sampling_modes(model):
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 97, (2, 5))
    out = G.generate(model, paddle.to_tensor(ids), max_new_tokens=5,
                     do_sample=True, temperature=0.8, top_k=10, seed=3)
    assert out.shape == [2, 10]
    out2 = G.generate(model, paddle.to_tensor(ids), max_new_tokens=5,
                      do_sample=True, top_p=0.9, seed=3)
    assert out2.shape == [2, 10]
    assert (out.numpy() < 97).all() and (out2.numpy() < 97).all()


def test_eos_padding(model):
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 97, (1, 5))
    out = G.generate(model, paddle.to_tensor(ids), max_new_tokens=8,
                     eos_token_id=1, pad_token_id=0)
    toks = out.numpy()[0, 5:]
    hits = np.where(toks == 1)[0]
    if hits.size:          # after EOS only pad/eos may follow
        after = toks[hits[0] + 1:]
        assert np.all((after == 0) | (after == 1)), toks


def test_bf16_generation_matches_forward():
    paddle.seed(11)
    cfg = llama_tiny(num_hidden_layers=2, hidden_size=64,
                     intermediate_size=128, vocab_size=53,
                     num_attention_heads=4, num_key_value_heads=4,
                     max_position_embeddings=64, dtype="bfloat16")
    m = LlamaForCausalLM(cfg)
    m.eval()
    rng = np.random.default_rng(4)
    ids = rng.integers(0, 53, (1, 6))
    out = G.generate(m, paddle.to_tensor(ids), max_new_tokens=4)
    ref = _reference_greedy(m, ids, 4)
    np.testing.assert_array_equal(out.numpy(), ref)


def test_weight_only_quantized_generate():
    """weight_quant='int8' serving path: runs the same one-program
    generate with (int8, scale) weight leaves and stays close to the
    dense greedy trajectory (reference: deploy models converted through
    nn.quant weight_quantize before serving)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models import generation as G

    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      max_position_embeddings=128)
    paddle.seed(3)
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (2, 12)).astype(np.int64))

    dense = G.generate(model, ids, max_new_tokens=8).numpy()
    q8 = G.generate(model, ids, max_new_tokens=8,
                    weight_quant="int8").numpy()
    assert q8.shape == dense.shape
    # int8 per-channel is near-lossless at init scale: the first GENERATED
    # token matches exactly, the rest nearly always
    np.testing.assert_array_equal(q8[:, 12], dense[:, 12])
    agree = (q8[:, 12:] == dense[:, 12:]).mean()
    assert agree >= 0.75, (agree, q8[:, 12:], dense[:, 12:])
    # second call with unchanged weights reuses the cached quant state
    c1 = model._wq_cache["state"]
    G.generate(model, ids, max_new_tokens=8, weight_quant="int8")
    assert model._wq_cache["state"] is c1

    q4 = G.generate(model, ids, max_new_tokens=8,
                    weight_quant="int4").numpy()
    assert q4.shape == dense.shape

    import pytest
    with pytest.raises(ValueError, match="weight_quant"):
        G.generate(model, ids, max_new_tokens=4, weight_quant="int2")


def test_weight_quant_with_paged_cache():
    """cache='paged' + weight_quant must serve from the paged block-table
    pool (a local-variable shadow of the `cache` argument used to
    silently reroute this combination to the dense path)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models import generation as G

    cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      max_position_embeddings=256)
    paddle.seed(5)
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 128, (2, 12)).astype(np.int64))

    G._FN_CACHE.clear()
    out = G.generate(model, ids, max_new_tokens=6, cache="paged",
                     weight_quant="int8").numpy()
    paged_keys = [k for k in G._FN_CACHE if k[0] == "paged"]
    assert paged_keys, "paged+quant generate never built the paged program"
    assert paged_keys[0][-1] == "int8"
    dense_q = G.generate(model, ids, max_new_tokens=6,
                         weight_quant="int8").numpy()
    assert out.shape == dense_q.shape
    # same quantized weights, same greedy decode: first generated token
    # matches across cache layouts
    np.testing.assert_array_equal(out[:, 12], dense_q[:, 12])
