"""Decode-step cache attention kernel (VERDICT r1 item 9).

Interpret-mode Pallas vs the XLA einsum reference on CPU; the compiled
path is exercised on TPU by test_flash_attention_tpu-style gating in
bench.py's decode rung.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.pallas import decode_attention as DA

rng = np.random.RandomState(0)


@pytest.fixture(autouse=True)
def _interpret():
    import jax

    from paddle_tpu.models import generation as G
    DA._INTERPRET = True
    G._FN_CACHE.clear()       # _INTERPRET is baked in at trace time
    # parity tolerances assume true-f32 dots; on TPU the f32 matmul
    # default is a bf16-pass MXU scheme (~6e-4 drift at these scales)
    with jax.default_matmul_precision("highest"):
        yield
    DA._INTERPRET = False
    G._FN_CACHE.clear()


@pytest.mark.parametrize("nh,kvh", [(4, 4), (8, 2)])
def test_matches_xla_reference(nh, kvh):
    B, T, D = 2, 256, 64
    q = jnp.asarray(rng.randn(B, nh, D).astype(np.float32)) * 0.4
    k = jnp.asarray(rng.randn(B, kvh, T, D).astype(np.float32)) * 0.4
    v = jnp.asarray(rng.randn(B, kvh, T, D).astype(np.float32)) * 0.4
    pos = jnp.asarray([37, 201], jnp.int32)

    got = DA.decode_attention(q, k, v, pos)
    ref = DA._xla_decode(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5)


def test_respects_per_batch_positions():
    """Entries beyond pos must not influence the output."""
    B, T, nh, D = 1, 256, 2, 64
    q = jnp.asarray(rng.randn(B, nh, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, nh, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, nh, T, D).astype(np.float32))
    pos = jnp.asarray([10], jnp.int32)
    out1 = DA.decode_attention(q, k, v, pos)
    # trash the cache past pos: output must be identical
    k2 = k.at[:, :, 11:].set(99.0)
    v2 = v.at[:, :, 11:].set(-99.0)
    out2 = DA.decode_attention(q, k2, v2, pos)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               atol=1e-6)


def test_generation_uses_kernel_consistently():
    """End-to-end generate on CPU (fallback path) stays deterministic
    after the decode-kernel wiring."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import llama_tiny, LlamaForCausalLM
    from paddle_tpu.models.generation import generate

    paddle.seed(0)
    cfg = llama_tiny(num_hidden_layers=2, hidden_size=64,
                     intermediate_size=128, vocab_size=128,
                     num_attention_heads=4, num_key_value_heads=4,
                     max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(rng.randint(0, 128, (2, 8)).astype(np.int64))
    out1 = generate(model, ids, max_new_tokens=4)
    out2 = generate(model, ids, max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray(out1._data),
                                  np.asarray(out2._data))
