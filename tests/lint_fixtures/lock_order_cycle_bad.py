"""BAD: two lock orders across methods (lock-order-cycle)."""
import threading


class Transfer:
    def __init__(self):
        self.lock_src = threading.Lock()
        self.lock_dst = threading.Lock()

    def forward(self):
        with self.lock_src:
            with self.lock_dst:
                pass

    def backward(self):
        with self.lock_dst:
            with self.lock_src:     # opposite order: ABBA deadlock
                pass
