"""GOOD twin: the header value passes through a bounding map (an LRU
canonicalizer) before it becomes a label."""
from paddle_tpu import observability as obs

REQS = obs.counter("serving_fixture_requests_total", "requests served",
                   ("tenant",))


def handle(self, table):
    tenant = table.canonical(self.headers.get("X-Tenant") or "anon")
    REQS.labels(tenant).inc()
