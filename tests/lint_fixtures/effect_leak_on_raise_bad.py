"""BAD: pages freed only on the happy path (effect-leak-on-raise)."""


def prefill(blocks, model, req):
    pages = blocks.allocate_seq(req.id, req.prompt_len)
    out = model.forward(req.prompt, pages)      # may raise: pages leak
    blocks.free_seq(req.id)
    return out
