"""BAD: thread target swallows every exception (thread-bare-except)."""
import threading


def worker(q):
    while True:
        item = q.get()
        if item is None:
            return
        try:
            item()
        except Exception:
            pass                    # error vanishes with the thread


def main(q):
    t = threading.Thread(target=worker, args=(q,))
    t.start()
    q.put(None)
    t.join()
