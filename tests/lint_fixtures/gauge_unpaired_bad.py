"""BAD: gauge inc'd but the dec is skipped on early return
(gauge-unpaired)."""


def admit(gauge_inflight, queue, req):
    gauge_inflight.inc()
    if queue.full():
        return None             # inflight never comes back down
    queue.put(req)
    gauge_inflight.dec()
    return req
