"""GOOD twin: the span is a context manager, closed on every path."""


def handle_request(tracer, handler, req):
    span = tracer.start_span("server.request")
    with span:
        return handler(req)
