"""GOOD twin: the operand is cast before mixing with the scalar."""
import jax
import jax.numpy as jnp


@jax.jit
def step_penalty(active):
    mask = active.astype(jnp.float32)
    return mask * 0.5 + 1
