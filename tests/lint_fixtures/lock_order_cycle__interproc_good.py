"""GOOD twin: both call chains acquire in the same a -> b order."""
import threading


class Pipeline:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()

    def submit(self):
        with self.lock_a:
            self._flush()

    def _flush(self):
        with self.lock_b:
            pass

    def drain(self):
        with self.lock_a:
            self._push()

    def _push(self):
        with self.lock_b:
            pass
