"""GOOD twin: shape-dependent branching is static; values use where."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp_positive(x):
    if x.ndim == 2:         # static: shapes are known at trace time
        x = x[None]
    return jnp.where(x > 0, x, 0.0)
