"""GOOD twin: counter carries _total."""
from paddle_tpu import observability as obs

REQS = obs.counter("serving_fixture_requests_total", "requests served")
