"""BAD: donated buffer read after the jit call (jit-donated-reuse)."""
import jax


def _accumulate(buf, x):
    return buf + x


step = jax.jit(_accumulate, donate_argnums=(0,))


def run(buf, x):
    out = step(buf, x)
    return out + buf        # buf's device memory was donated away
