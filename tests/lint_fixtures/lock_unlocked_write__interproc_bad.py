"""BAD: helper's write is locked via one caller, bare via another
(lock-unlocked-write).

``_bump`` never takes the lock itself, so intraprocedurally every
write looks uniformly unlocked and the pass stays quiet; the chain
``record -> _bump`` makes the same line a locked write, exposing the
race with ``fast_path``.
"""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def _bump(self):
        self.count += 1

    def record(self):
        with self._lock:
            self._bump()

    def fast_path(self):
        self._bump()                # races with record()
