"""BAD: started thread is never joined (thread-unjoined)."""
import threading


class Poller:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def stop(self):
        self._stopping = True       # forgets self._t.join()

    def _run(self):
        while not getattr(self, "_stopping", False):
            pass
