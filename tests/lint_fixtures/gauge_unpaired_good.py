"""GOOD twin: the dec runs in a finally, so every path restores it."""


def admit(gauge_inflight, queue, req):
    gauge_inflight.inc()
    try:
        if queue.full():
            return None
        queue.put(req)
        return req
    finally:
        gauge_inflight.dec()
