"""GOOD twin: the handler records the failure before continuing."""
import logging
import threading

log = logging.getLogger(__name__)


def worker(q):
    while True:
        item = q.get()
        if item is None:
            return
        try:
            item()
        except Exception:
            log.exception("task failed")


def main(q):
    t = threading.Thread(target=worker, args=(q,))
    t.start()
    q.put(None)
    t.join()
