"""BAD: ABBA only visible through a call chain (lock-order-cycle).

No single method nests the two locks, so the intraprocedural pass sees
nothing; ``submit -> _flush`` acquires a then b while
``drain -> _push`` acquires b then a.
"""
import threading


class Pipeline:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()

    def submit(self):
        with self.lock_a:
            self._flush()

    def _flush(self):
        with self.lock_b:
            pass

    def drain(self):
        with self.lock_b:
            self._push()

    def _push(self):
        with self.lock_a:
            pass
