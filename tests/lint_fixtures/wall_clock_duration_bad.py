"""BAD: duration measured with the wall clock (wall-clock-duration)."""
import time


def elapsed_since(t0):
    return time.time() - t0
