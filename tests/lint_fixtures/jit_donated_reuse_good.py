"""GOOD twin: the donated buffer is rebound from the call result."""
import jax


def _accumulate(buf, x):
    return buf + x


step = jax.jit(_accumulate, donate_argnums=(0,))


def run(buf, x):
    buf = step(buf, x)
    return buf
