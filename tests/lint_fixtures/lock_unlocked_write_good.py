"""GOOD twin: every mutation goes through the lock."""
import threading


class HitCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0

    def bump(self):
        with self._lock:
            self.hits += 1

    def reset(self):
        with self._lock:
            self.hits = 0
