"""GOOD twin: the pages are freed on every outgoing path."""


def prefill(blocks, model, req):
    pages = blocks.allocate_seq(req.id, req.prompt_len)
    try:
        return model.forward(req.prompt, pages)
    finally:
        blocks.free_seq(req.id)
