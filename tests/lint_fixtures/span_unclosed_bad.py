"""BAD: span abandoned when the handler raises (span-unclosed)."""


def handle_request(tracer, handler, req):
    span = tracer.start_span("server.request")
    resp = handler(req)         # may raise: the span never ends
    span.end()
    return resp
