"""GOOD twin: snapshot the callback under the lock, call it outside."""
import threading


class Emitter:
    def __init__(self, on_token=None):
        self._lock = threading.Lock()
        self.on_token = on_token

    def emit(self, tok):
        with self._lock:
            cb = self.on_token
        cb(tok)
