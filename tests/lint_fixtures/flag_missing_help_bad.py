"""BAD: flag registered without help text (flag-missing-help)."""
from paddle_tpu.flags import define_flag

define_flag("FLAGS_fixture_quiet_mode", False)
