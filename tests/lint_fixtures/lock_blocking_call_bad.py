"""BAD: sleeping while holding the lock (lock-blocking-call)."""
import threading
import time


class Prober:
    def __init__(self):
        self._lock = threading.Lock()
        self.probes = 0

    def probe(self):
        with self._lock:
            time.sleep(0.1)     # every other thread stalls here
            self.probes += 1
