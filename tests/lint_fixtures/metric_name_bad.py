"""BAD: metric without a subsystem prefix (metric-name)."""
from paddle_tpu import observability as obs

REQS = obs.counter("fixture_requests_total", "requests served")
