"""GOOD twin: the constant carries a narrow dtype explicitly."""
import jax
import numpy as np


@jax.jit
def add_bias(x):
    bias = np.arange(8, dtype=np.int32)
    return x + bias
