"""BAD: narrow-int reduction with no cast-back (jit-dtype-promotion)."""
import jax
import jax.numpy as jnp


@jax.jit
def accepted_counts(draft, out):
    m = (draft == out).astype(jnp.int32)
    return jnp.cumprod(m, axis=1).sum(axis=1)   # int64 under x64
