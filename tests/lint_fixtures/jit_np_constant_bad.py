"""BAD: numpy constant with host-default dtype inside a jitted body
(jit-np-constant)."""
import jax
import numpy as np


@jax.jit
def add_bias(x):
    bias = np.arange(8)         # int64 on host, baked into the trace
    return x + bias
