"""GOOD twin: the collective uses the axis the mapping binds."""
import jax
from jax.sharding import PartitionSpec as P


def all_reduce(xs, mesh):
    def body(x):
        return jax.lax.psum(x, "tp")

    return jax.shard_map(body, mesh=mesh, in_specs=P("tp"),
                         out_specs=P("tp"),
                         axis_names=frozenset({"tp"}))(xs)
