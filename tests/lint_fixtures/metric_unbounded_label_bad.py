"""BAD: request-header string fed straight to a metric label
(metric-unbounded-label)."""
from paddle_tpu import observability as obs

REQS = obs.counter("serving_fixture_requests_total", "requests served",
                   ("tenant",))


def handle(self):
    tenant = self.headers.get("X-Tenant") or "anon"
    REQS.labels(tenant.strip()).inc()
