"""BAD: collective on an axis the mapping shard_map does not bind
(collective-unknown-axis)."""
import jax
from jax.sharding import PartitionSpec as P


def all_reduce(xs, mesh):
    def body(x):
        return jax.lax.psum(x, "dp")        # mapping binds only "tp"

    return jax.shard_map(body, mesh=mesh, in_specs=P("tp"),
                         out_specs=P("tp"),
                         axis_names=frozenset({"tp"}))(xs)
