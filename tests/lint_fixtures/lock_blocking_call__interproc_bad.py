"""BAD: callee blocks while the caller holds the lock
(lock-blocking-call).

``_fetch`` looks innocent in isolation — the sleep only serializes
everything because ``refresh`` calls it with ``_lock`` held.
"""
import threading
import time


class Refresher:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def refresh(self):
        with self._lock:
            self.value = self._fetch()

    def _fetch(self):
        time.sleep(0.1)             # stalls every lock waiter
        return 42
