"""GOOD twin: block outside, take the lock only for the update."""
import threading
import time


class Prober:
    def __init__(self):
        self._lock = threading.Lock()
        self.probes = 0

    def probe(self):
        time.sleep(0.1)
        with self._lock:
            self.probes += 1
