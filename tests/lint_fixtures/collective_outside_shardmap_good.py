"""GOOD twin: the collective runs inside the shard_map mapping."""
import jax
from jax.sharding import PartitionSpec as P


def build_reduce(mesh):
    def mapped(local_loss):
        return jax.lax.psum(local_loss, "tp")

    return jax.shard_map(mapped, mesh=mesh, in_specs=P("tp"),
                         out_specs=P(), axis_names=frozenset({"tp"}))
