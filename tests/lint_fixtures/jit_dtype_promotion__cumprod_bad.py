"""BAD variant: the PR-10 speculative-verify promotion, factory form.

Lifted from the speculative-decoding verify step: the acceptance-mask
``cumprod().sum()`` promoted to int64 under ``jax_enable_x64``, shifting
the traced avals between hosts and silently retracing every step — only
the perf-gate trace counter caught it.  The jit target here is a factory
closure (``jax.jit(build_verify())``), the same shape the runner uses.
"""
import jax
import jax.numpy as jnp


def build_verify():
    def verify(tokens, draft, active):
        ok = (draft == tokens[:, None]).astype(jnp.int32)
        m = ok * active[:, None].astype(jnp.int32)
        acc = jnp.cumprod(m, axis=1).sum(axis=1)    # int64 under x64
        return acc

    return verify


verify_fn = jax.jit(build_verify())
