"""GOOD twin: one global order, both paths follow it."""
import threading


class Transfer:
    def __init__(self):
        self.lock_src = threading.Lock()
        self.lock_dst = threading.Lock()

    def forward(self):
        with self.lock_src:
            with self.lock_dst:
                pass

    def backward(self):
        with self.lock_src:
            with self.lock_dst:
                pass
