"""BAD: literal-axis collective with no shard_map mapping it
(collective-outside-shardmap)."""
import jax


@jax.jit
def reduce_loss(local_loss):
    return jax.lax.psum(local_loss, "tp")   # "tp" is unbound under jit
