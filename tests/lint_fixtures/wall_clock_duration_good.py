"""GOOD twin: monotonic clock for durations, wall clock for stamps."""
import time


def elapsed_since(t0):
    return time.perf_counter() - t0


def created_stamp():
    return int(time.time())
