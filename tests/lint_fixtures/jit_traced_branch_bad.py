"""BAD: python branch on a traced value (jit-traced-branch)."""
import jax
import jax.numpy as jnp


@jax.jit
def clamp_positive(x):
    if x > 0:               # TracerBoolConversionError at runtime
        return x
    return jnp.zeros_like(x)
