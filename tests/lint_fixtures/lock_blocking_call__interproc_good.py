"""GOOD twin: fetch outside the lock, publish under it."""
import threading
import time


class Refresher:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def refresh(self):
        fresh = self._fetch()
        with self._lock:
            self.value = fresh

    def _fetch(self):
        time.sleep(0.1)
        return 42
