"""A known-bad pattern silenced by an inline suppression comment."""
import time


def elapsed_since(t0):
    # tpu-lint: disable=wall-clock-duration
    return time.time() - t0
