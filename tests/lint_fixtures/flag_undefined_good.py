"""GOOD twin: the registered spelling."""
from paddle_tpu.flags import FLAGS


def buffer_size():
    return FLAGS.get("FLAGS_trace_buffer_size", 0)
