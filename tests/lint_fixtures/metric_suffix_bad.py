"""BAD: counter without the _total unit suffix (metric-suffix)."""
from paddle_tpu import observability as obs

REQS = obs.counter("serving_fixture_requests", "requests served")
