"""GOOD twin: every path into the helper holds the lock."""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def _bump(self):
        self.count += 1

    def record(self):
        with self._lock:
            self._bump()

    def fast_path(self):
        with self._lock:
            self._bump()
