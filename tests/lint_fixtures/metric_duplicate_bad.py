"""BAD: one name, two metric kinds (metric-duplicate)."""
from paddle_tpu import observability as obs

H = obs.histogram("serving_fixture_wait_seconds", "queue wait")
G = obs.gauge("serving_fixture_wait_seconds", "queue wait, but a gauge")
