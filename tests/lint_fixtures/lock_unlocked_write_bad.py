"""BAD: attribute written with and without the lock
(lock-unlocked-write)."""
import threading


class HitCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0

    def bump(self):
        with self._lock:
            self.hits += 1

    def reset(self):
        self.hits = 0       # races with bump()'s locked increment
