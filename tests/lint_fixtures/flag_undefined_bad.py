"""BAD: typo'd flag name reads as permanently-default
(flag-undefined)."""
from paddle_tpu.flags import FLAGS


def buffer_size():
    return FLAGS.get("FLAGS_trace_buffer_sz", 0)
