"""BAD: user-supplied callback invoked under the lock
(callback-under-lock)."""
import threading


class Emitter:
    def __init__(self, on_token=None):
        self._lock = threading.Lock()
        self.on_token = on_token

    def emit(self, tok):
        with self._lock:
            self.on_token(tok)      # arbitrary user code under _lock
