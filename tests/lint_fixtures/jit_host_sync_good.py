"""GOOD twin: concretize outside the jitted body."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    return jnp.sum(x * x)


def host_value(x):
    return np.asarray(step(x))
