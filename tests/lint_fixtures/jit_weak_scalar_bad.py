"""BAD: python float weak-promotes an int32 operand (jit-weak-scalar)."""
import jax
import jax.numpy as jnp


@jax.jit
def step_penalty(active):
    mask = active.astype(jnp.int32)
    return mask * 0.5 + 1       # float (float64 under x64)
