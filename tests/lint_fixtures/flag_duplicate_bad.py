"""BAD: the same flag registered twice (flag-duplicate)."""
from paddle_tpu.flags import define_flag

define_flag("FLAGS_fixture_retries", 3, "fixture retry budget")
define_flag("FLAGS_fixture_retries", 5, "fixture retry budget, again")
