"""BAD: host sync inside a jitted function (jit-host-sync)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    y = jnp.sum(x * x)
    np.asarray(y)           # device->host transfer mid-trace
    return y
