"""GOOD twin: shutdown joins the worker thread."""
import threading


class Poller:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def stop(self):
        self._stopping = True
        self._t.join(timeout=5.0)

    def _run(self):
        while not getattr(self, "_stopping", False):
            pass
