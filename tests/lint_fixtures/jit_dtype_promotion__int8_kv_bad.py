"""BAD variant: int8 KV-page reduction (ISSUE 18 quantized pages).

Lifted from the quantized-serving hazard: once KV rows are cast to
int8 page bytes, any reduction over them (here a debug occupancy sum)
promotes to int64 under ``jax_enable_x64`` and shifts the traced avals
between hosts.  The quantizer itself must reduce (amax) over the FLOAT
rows BEFORE the cast, and anything summing the int8 bytes afterwards
must cast back explicitly.
"""
import jax
import jax.numpy as jnp


@jax.jit
def page_occupancy(kpool, scale):
    q = jnp.clip(jnp.round(kpool / scale), -127, 127).astype(jnp.int8)
    return q.sum(axis=-1)               # int64 under x64
