"""GOOD twin: each flag registered exactly once."""
from paddle_tpu.flags import define_flag

define_flag("FLAGS_fixture_retries", 3, "fixture retry budget")
define_flag("FLAGS_fixture_backoff_s", 0.5, "fixture retry backoff")
