"""GOOD twin: the int8 page reduction casts back to int32 explicitly."""
import jax
import jax.numpy as jnp


@jax.jit
def page_occupancy(kpool, scale):
    q = jnp.clip(jnp.round(kpool / scale), -127, 127).astype(jnp.int8)
    return q.sum(axis=-1).astype(jnp.int32)
