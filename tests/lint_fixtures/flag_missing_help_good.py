"""GOOD twin: help text present."""
from paddle_tpu.flags import define_flag

define_flag("FLAGS_fixture_quiet_mode", False,
            "suppress fixture chatter (lint fixture only)")
