"""GOOD twin: the reduction chain casts back to int32 explicitly."""
import jax
import jax.numpy as jnp


@jax.jit
def accepted_counts(draft, out):
    m = (draft == out).astype(jnp.int32)
    return jnp.cumprod(m, axis=1).sum(axis=1).astype(jnp.int32)
