"""Row-sparse (SelectedRows-analog) embedding gradients.

Reference behavior being matched: paddle/phi/kernels/selected_rows/
(merge kernel, sgd SelectedRows branch, adam lazy_mode) and the
``sparse=True`` embedding grad (paddle/phi/ops/yaml/backward.yaml
embedding_grad sparse branch).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.framework.selected_rows import (RowSparseGrad, merge_rows,
                                                rowsparse_all_gather)

V, D = 50, 4


def _loss_and_backward(weight_t, ids, sparse):
    out = F.embedding(paddle.to_tensor(ids), weight_t, sparse=sparse)
    loss = (out * out).sum()
    loss.backward()
    return loss


def test_sparse_grad_is_rowsparse_and_matches_dense():
    w = np.random.randn(V, D).astype(np.float32)
    ids = np.array([[3, 7, 3], [0, 7, 12]], np.int64)  # dup rows 3 and 7

    wt_d = paddle.to_tensor(w, stop_gradient=False)
    _loss_and_backward(wt_d, ids, sparse=False)
    dense = np.asarray(wt_d._grad)

    wt_s = paddle.to_tensor(w, stop_gradient=False)
    _loss_and_backward(wt_s, ids, sparse=True)
    g = wt_s._grad
    assert isinstance(g, RowSparseGrad)
    # the dense [V, D] buffer is never the stored form
    assert g.values.shape == (ids.size, D)
    assert set(np.asarray(g.rows).tolist()) == {0, 3, 7, 12}
    np.testing.assert_allclose(np.asarray(g.to_dense()), dense, rtol=1e-6)


def test_padding_idx_rows_get_zero_grad():
    w = np.random.randn(V, D).astype(np.float32)
    ids = np.array([1, 2, 2, 1, 5], np.int64)
    wt = paddle.to_tensor(w, stop_gradient=False)
    _loss_and_backward(wt, ids, sparse=True)
    g = wt._grad.to_dense()
    wt2 = paddle.to_tensor(w, stop_gradient=False)
    out = F.embedding(paddle.to_tensor(ids), wt2, padding_idx=2, sparse=True)
    (out * out).sum().backward()
    g2 = wt2._grad.to_dense()
    assert np.abs(np.asarray(g2)[2]).max() == 0.0
    np.testing.assert_allclose(np.asarray(g2)[1], np.asarray(g)[1], rtol=1e-6)


def test_merge_rows_dedupes():
    rows = jnp.array([7, 3, 7, 3, 7], jnp.int32)
    vals = jnp.arange(10, dtype=jnp.float32).reshape(5, 2)
    g = RowSparseGrad(rows, vals, (V, 2))
    m = merge_rows(g)
    assert m.values.shape == vals.shape  # static N under jit
    np.testing.assert_allclose(np.asarray(m.to_dense()),
                               np.asarray(g.to_dense()), rtol=1e-6)
    valid = np.asarray(m.rows) < V
    assert sorted(np.asarray(m.rows)[valid].tolist()) == [3, 7]
    # merge is jit-safe
    m2 = jax.jit(merge_rows)(g)
    np.testing.assert_allclose(np.asarray(m2.to_dense()),
                               np.asarray(g.to_dense()), rtol=1e-6)


def test_accumulation_sparse_plus_sparse_and_dense():
    a = RowSparseGrad(jnp.array([1], jnp.int32),
                      jnp.ones((1, D)), (V, D))
    b = RowSparseGrad(jnp.array([1, 4], jnp.int32),
                      jnp.full((2, D), 2.0), (V, D))
    s = a + b
    assert isinstance(s, RowSparseGrad)
    assert np.asarray(s.to_dense())[1, 0] == 3.0
    dense = jnp.zeros((V, D)).at[4, 0].set(1.0)
    full = s + dense
    assert isinstance(full, jnp.ndarray)
    assert float(full[4, 0]) == 3.0


def _train(sparse, opt_cls, ids_steps, w0, **kw):
    emb = nn.Embedding(V, D, sparse=sparse)
    emb.weight._data = jnp.asarray(w0)
    o = opt_cls(learning_rate=0.1, parameters=emb.parameters(), **kw)
    for ids in ids_steps:
        out = emb(paddle.to_tensor(ids))
        loss = (out * out).sum()
        loss.backward()
        o.step()
        o.clear_grad()
    return np.asarray(emb.weight._data)


def test_sgd_sparse_matches_dense():
    w0 = np.random.randn(V, D).astype(np.float32)
    steps = [np.array([3, 7, 3], np.int64), np.array([0, 3], np.int64)]
    np.testing.assert_allclose(_train(True, opt.SGD, steps, w0),
                               _train(False, opt.SGD, steps, w0),
                               rtol=1e-5, atol=1e-6)


def test_lazy_adam_touched_rows_match_untouched_frozen():
    w0 = np.random.randn(V, D).astype(np.float32)
    steps = [np.array([3, 7], np.int64), np.array([3], np.int64)]
    lazy = _train(True, opt.Adam, steps, w0, lazy_mode=True)
    dense = _train(False, opt.Adam, steps, w0)
    # untouched rows: lazy leaves them bit-identical (dense adam does too
    # here because moments start at zero and grads there are zero)
    np.testing.assert_allclose(lazy[10], w0[10], rtol=0, atol=0)
    # touched-every-step rows agree with dense adam
    np.testing.assert_allclose(lazy[3], dense[3], rtol=1e-5, atol=1e-6)


def test_lazy_adamw_decays_touched_rows_only():
    w0 = np.ones((V, D), np.float32)
    steps = [np.array([2], np.int64)]
    out = _train(True, opt.AdamW, steps, w0, lazy_mode=True,
                 weight_decay=0.5)
    assert np.all(out[3] == 1.0)          # untouched: no decay applied
    assert np.all(out[2] < 1.0)           # touched: decayed + moved


def test_nonlazy_optimizer_densifies_correctly():
    w0 = np.random.randn(V, D).astype(np.float32)
    steps = [np.array([1, 1, 4], np.int64)]
    np.testing.assert_allclose(_train(True, opt.Momentum, steps, w0),
                               _train(False, opt.Momentum, steps, w0),
                               rtol=1e-5, atol=1e-6)


def test_global_norm_clip_with_sparse_grad():
    w0 = np.random.randn(V, D).astype(np.float32)
    steps = [np.array([3, 3, 9], np.int64)]
    clip = nn.ClipGradByGlobalNorm(0.01)
    a = _train(True, opt.SGD, steps, w0, grad_clip=clip)
    b = _train(False, opt.SGD, steps, w0, grad_clip=clip)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_rowsparse_all_gather_on_mesh():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs >=2 devices")
    from jax.sharding import Mesh, PartitionSpec as P
    from jax import shard_map
    n = 2
    mesh = Mesh(np.array(devs[:n]), ("dp",))
    rows = jnp.array([[1], [4]], jnp.int32)       # one row per rank
    vals = jnp.array([[[1.0, 1.0]], [[2.0, 2.0]]])

    def f(r, v):
        g = RowSparseGrad(r.reshape(-1), v.reshape(-1, 2), (V, 2))
        ag = rowsparse_all_gather(g, "dp")
        return ag.to_dense()

    out = shard_map(f, mesh=mesh, in_specs=(P("dp"), P("dp")),
                    out_specs=P(), check_vma=False)(rows, vals)
    assert float(out[1, 0]) == 1.0 and float(out[4, 0]) == 2.0


def test_grad_scaler_unscale_and_clear_grad_stay_sparse():
    emb = nn.Embedding(V, D, sparse=True)
    o = opt.SGD(learning_rate=0.1, parameters=emb.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    ids = paddle.to_tensor(np.array([1, 1, 4], np.int64))
    loss = (emb(ids) ** 2).sum()
    scaler.scale(loss).backward()
    scaler.step(o)
    scaler.update()
    # round-trip through the property + setter keeps the sparse form
    g = RowSparseGrad(jnp.array([2], jnp.int32), jnp.ones((1, D)), (V, D))
    emb.weight.grad = g
    assert isinstance(emb.weight.grad, RowSparseGrad)
    emb.weight.clear_grad(set_to_zero=True)
    g2 = emb.weight._grad
    assert isinstance(g2, RowSparseGrad)      # never densified
    assert float(jnp.abs(g2.values).max()) == 0.0


def test_sparse_grad_under_jit_train_step():
    # the whole lookup->loss->backward->sgd row update composes under jit
    w0 = np.random.randn(V, D).astype(np.float32)

    def step(w, ids):
        wt = paddle.to_tensor(w, stop_gradient=False)
        out = F.embedding(paddle.to_tensor(ids), wt, sparse=True)
        loss = (out * out).sum()
        loss.backward()
        g = wt._grad
        assert isinstance(g, RowSparseGrad)
        m = g.merged()
        return w.at[m.rows].add(-0.1 * m.values, mode="drop")

    ids = jnp.array([3, 7, 3], jnp.int32)
    got = jax.jit(step)(jnp.asarray(w0), ids)
    ref = step(jnp.asarray(w0), ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
