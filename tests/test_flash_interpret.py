"""Plain flash kernels in Pallas interpret mode: the kernel logic
(tail masking, causal offsets, GQA index maps, trip-count bounds) runs
in CI off-TPU (the _tpu suite covers real-Mosaic behavior)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as F


@pytest.fixture(autouse=True)
def _interpret():
    saved = F._INTERPRET
    F._INTERPRET = True
    try:
        yield
    finally:
        F._INTERPRET = saved


@pytest.mark.parametrize("sq,sk,causal,hk", [
    (256, 256, True, 4),      # square causal
    (200, 200, False, 4),     # tail-masked
    (150, 300, True, 2),      # cross-length causal + GQA
])
def test_interpret_parity(sq, sk, causal, hk):
    rng = np.random.default_rng(0)
    B, H, D = 1, 4, 64
    q = jnp.asarray(rng.standard_normal((B, sq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, sk, hk, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, sk, hk, D)), jnp.float32)
    out = F._pallas_sdpa(q, k, v, causal)
    ref = F._xla_sdpa(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)

    def lp(q, k, v):
        return jnp.sum(F._pallas_sdpa(q, k, v, causal) ** 2)

    def lr(q, k, v):
        return jnp.sum(F._xla_sdpa(q, k, v, is_causal=causal) ** 2)

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        a, b = np.asarray(a), np.asarray(b)
        denom = max(np.abs(b).max(), 1.0)
        assert np.abs(a - b).max() / denom < 5e-3


def test_interpret_masked_kernel_gqa():
    """flash_mask interval kernel under GQA: in-kernel kv index maps +
    per-q-head dK/dV group reduction (round-3 wiring)."""
    from paddle_tpu.ops.pallas import flash_mask as FM

    saved = FM._INTERPRET
    FM._INTERPRET = True
    try:
        rng = np.random.default_rng(1)
        B, S, H, HK, D = 1, 256, 4, 2, 64
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, S, HK, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, S, HK, D)), jnp.float32)
        keep = np.ones((B, 1, 1, S), bool)
        keep[:, :, :, 200:] = False
        am = jnp.asarray(keep)
        vecs = FM.padding_mask_to_intervals(am[:, :, 0, :], S)

        def bhsd(t):
            return jnp.swapaxes(t, 1, 2)

        def run_kernel(q, k, v):
            # DIRECT kernel call (sdpa's backend gate would take the
            # XLA fallback on CPU): GQA kv widths, no repeat
            out = FM.flash_mha_masked(bhsd(q), bhsd(k), bhsd(v), vecs,
                                      True, 1.0 / np.sqrt(D))
            return jnp.swapaxes(out, 1, 2)

        out = run_kernel(q, k, v)
        ref = F._xla_sdpa(q, k, v, attn_mask=am, is_causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)

        def lp(q, k, v):
            return jnp.sum(run_kernel(q, k, v) ** 2)

        def lr(q, k, v):
            return jnp.sum(F._xla_sdpa(q, k, v, attn_mask=am,
                                       is_causal=True) ** 2)

        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            a, b = np.asarray(a), np.asarray(b)
            denom = max(np.abs(b).max(), 1.0)
            assert np.abs(a - b).max() / denom < 5e-3
    finally:
        FM._INTERPRET = saved
