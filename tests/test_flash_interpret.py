"""Plain flash kernels in Pallas interpret mode: the kernel logic
(tail masking, causal offsets, GQA index maps, trip-count bounds) runs
in CI off-TPU (the _tpu suite covers real-Mosaic behavior)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as F


@pytest.fixture(autouse=True)
def _interpret():
    saved = F._INTERPRET
    F._INTERPRET = True
    try:
        yield
    finally:
        F._INTERPRET = saved


@pytest.mark.parametrize("sq,sk,causal,hk", [
    (256, 256, True, 4),      # square causal
    (200, 200, False, 4),     # tail-masked
    (150, 300, True, 2),      # cross-length causal + GQA
])
def test_interpret_parity(sq, sk, causal, hk):
    rng = np.random.default_rng(0)
    B, H, D = 1, 4, 64
    q = jnp.asarray(rng.standard_normal((B, sq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, sk, hk, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, sk, hk, D)), jnp.float32)
    out = F._pallas_sdpa(q, k, v, causal)
    ref = F._xla_sdpa(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)

    def lp(q, k, v):
        return jnp.sum(F._pallas_sdpa(q, k, v, causal) ** 2)

    def lr(q, k, v):
        return jnp.sum(F._xla_sdpa(q, k, v, is_causal=causal) ** 2)

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        a, b = np.asarray(a), np.asarray(b)
        denom = max(np.abs(b).max(), 1.0)
        assert np.abs(a - b).max() / denom < 5e-3
