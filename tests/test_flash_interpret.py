"""Plain flash kernels in Pallas interpret mode: the kernel logic
(tail masking, causal offsets, GQA index maps, trip-count bounds) runs
in CI off-TPU (the _tpu suite covers real-Mosaic behavior)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as F


@pytest.fixture(autouse=True)
def _interpret():
    saved = F._INTERPRET
    F._INTERPRET = True
    try:
        yield
    finally:
        F._INTERPRET = saved


@pytest.mark.parametrize("sq,sk,causal,hk", [
    (256, 256, True, 4),      # square causal
    (200, 200, False, 4),     # tail-masked
    (150, 300, True, 2),      # cross-length causal + GQA
])
def test_interpret_parity(sq, sk, causal, hk):
    rng = np.random.default_rng(0)
    B, H, D = 1, 4, 64
    q = jnp.asarray(rng.standard_normal((B, sq, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, sk, hk, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, sk, hk, D)), jnp.float32)
    out = F._pallas_sdpa(q, k, v, causal)
    ref = F._xla_sdpa(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)

    def lp(q, k, v):
        return jnp.sum(F._pallas_sdpa(q, k, v, causal) ** 2)

    def lr(q, k, v):
        return jnp.sum(F._xla_sdpa(q, k, v, is_causal=causal) ** 2)

    gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        a, b = np.asarray(a), np.asarray(b)
        denom = max(np.abs(b).max(), 1.0)
        assert np.abs(a - b).max() / denom < 5e-3


def test_interpret_masked_kernel_gqa():
    """flash_mask interval kernel under GQA: in-kernel kv index maps +
    per-q-head dK/dV group reduction (round-3 wiring)."""
    from paddle_tpu.ops.pallas import flash_mask as FM

    saved = FM._INTERPRET
    FM._INTERPRET = True
    try:
        rng = np.random.default_rng(1)
        B, S, H, HK, D = 1, 256, 4, 2, 64
        q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, S, HK, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, S, HK, D)), jnp.float32)
        keep = np.ones((B, 1, 1, S), bool)
        keep[:, :, :, 200:] = False
        am = jnp.asarray(keep)
        vecs = FM.padding_mask_to_intervals(am[:, :, 0, :], S)

        def bhsd(t):
            return jnp.swapaxes(t, 1, 2)

        def run_kernel(q, k, v):
            # DIRECT kernel call (sdpa's backend gate would take the
            # XLA fallback on CPU): GQA kv widths, no repeat
            out = FM.flash_mha_masked(bhsd(q), bhsd(k), bhsd(v), vecs,
                                      True, 1.0 / np.sqrt(D))
            return jnp.swapaxes(out, 1, 2)

        out = run_kernel(q, k, v)
        ref = F._xla_sdpa(q, k, v, attn_mask=am, is_causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)

        def lp(q, k, v):
            return jnp.sum(run_kernel(q, k, v) ** 2)

        def lr(q, k, v):
            return jnp.sum(F._xla_sdpa(q, k, v, attn_mask=am,
                                       is_causal=True) ** 2)

        gp = jax.grad(lp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gr):
            a, b = np.asarray(a), np.asarray(b)
            denom = max(np.abs(b).max(), 1.0)
            assert np.abs(a - b).max() / denom < 5e-3
    finally:
        FM._INTERPRET = saved


@pytest.mark.parametrize("sq,sk,causal,h,kvh", [
    (256, 256, True, 4, 4),
    (384, 640, True, 4, 4),      # Sq != Sk causal offset + tail block
    (256, 256, True, 8, 2),      # GQA
    (200, 330, False, 4, 4),     # odd unpadded lengths
])
def test_streamed_kernels_match_block_kernels(sq, sk, causal, h, kvh):
    """The grid-streamed long-seq variants (VMEM independent of sequence
    length) must be numerically identical to the full-VMEM block kernels
    — values AND all three grads (the 8k+ single-chip training path)."""
    import jax

    from paddle_tpu.ops.pallas import flash_attention as FA

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, sq, h, 64).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(2, sk, kvh, 64).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(2, sk, kvh, 64).astype(np.float32)) * 0.3

    def run(force):
        saved = FA._FORCE_STREAM
        FA._FORCE_STREAM = force
        try:
            # DIRECT kernel call: sdpa's backend gate takes the XLA
            # fallback on CPU, which would make this test vacuous
            def f(q, k, v):
                return (FA._pallas_sdpa(q, k, v, causal) ** 2).sum()
            return jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        finally:
            FA._FORCE_STREAM = saved

    v0, g0 = run(False)
    v1, g1 = run(True)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-5)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal,use_bias,gqa", [
    (True, False, 1), (False, True, 1), (True, True, 2),
])
def test_xla_streamed_masked_matches_dense(causal, use_bias, gqa):
    """The O(S)-memory chunked-XLA masked fallback (long-seq masked
    attention) must match the dense _xla_sdpa at small sizes."""
    from paddle_tpu.ops.pallas import flash_attention as FA
    from paddle_tpu.ops.pallas.flash_mask import padding_mask_to_intervals

    rng = np.random.RandomState(2)
    B, Sq, Sk, H, D = 2, 192, 320, 4, 64
    q = jnp.asarray(rng.randn(B, Sq, H, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, Sk, H // gqa, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, Sk, H // gqa, D).astype(np.float32)) * 0.3

    lengths = np.array([Sk, 150])
    bool_mask = jnp.asarray(
        np.arange(Sk)[None, None, None, :] < lengths[:, None, None, None])
    if use_bias:
        bias = jnp.asarray(
            rng.randn(B, 1, Sq, Sk).astype(np.float32)) * 0.5
        got = FA._xla_sdpa_streamed(q, k, v, causal, bias=bias, chunk=64)
        kr = jnp.repeat(k, gqa, axis=2) if gqa > 1 else k
        vr = jnp.repeat(v, gqa, axis=2) if gqa > 1 else v
        ref = FA._xla_sdpa(q, kr, vr, attn_mask=bias, is_causal=causal)
    else:
        vecs = padding_mask_to_intervals(bool_mask[:, :, 0, :], Sq)
        got = FA._xla_sdpa_streamed(q, k, v, causal, mask_vecs=vecs,
                                    chunk=64)
        add = jnp.where(bool_mask, 0.0, -1e9)
        ref = FA._xla_sdpa(q, k, v, attn_mask=add, is_causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
