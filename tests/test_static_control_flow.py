"""Static-graph control flow + the rest of paddle.static.nn.

Reference: python/paddle/static/nn/control_flow.py (cond:1509,
while_loop:682, case:961, switch_case:1084, static_pylayer:1303) and
common.py layer helpers.  Lowering: lax.cond / lax.while_loop /
jax.custom_vjp at executor-jit time (static/control_flow.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
import paddle_tpu.static.nn as snn

rng = np.random.RandomState(7)


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _exe():
    return static.Executor()


class TestCond:
    def test_both_branches(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [4], "float32")
            flag = static.data("flag", [1], "bool")
            out = snn.cond(flag, lambda: x * 2.0, lambda: x + 10.0)
        exe = _exe()
        xv = np.arange(4, dtype=np.float32)
        r_t = exe.run(m, feed={"x": xv, "flag": np.array([True])},
                      fetch_list=[out])[0]
        r_f = exe.run(m, feed={"x": xv, "flag": np.array([False])},
                      fetch_list=[out])[0]
        np.testing.assert_allclose(r_t, xv * 2.0)
        np.testing.assert_allclose(r_f, xv + 10.0)

    def test_matches_eager_twin(self):
        def compute(xv, flag):
            return xv * 3.0 + 1.0 if flag else xv ** 2

        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [3], "float32")
            f = static.data("f", [1], "bool")
            out = snn.cond(f, lambda: x * 3.0 + 1.0, lambda: x ** 2)
        exe = _exe()
        xv = rng.randn(3).astype(np.float32)
        for flag in (True, False):
            got = exe.run(m, feed={"x": xv, "f": np.array([flag])},
                          fetch_list=[out])[0]
            np.testing.assert_allclose(got, compute(xv, flag), rtol=1e-6)

    def test_nested_cond(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [2], "float32")
            a = static.data("a", [1], "bool")
            b = static.data("b", [1], "bool")
            out = snn.cond(
                a,
                lambda: snn.cond(b, lambda: x * 2.0, lambda: x * 3.0),
                lambda: x * 5.0)
        exe = _exe()
        xv = np.ones(2, np.float32)
        for av, bv, scale in [(True, True, 2), (True, False, 3),
                              (False, True, 5)]:
            r = exe.run(m, feed={"x": xv, "a": np.array([av]),
                                 "b": np.array([bv])}, fetch_list=[out])[0]
            np.testing.assert_allclose(r, xv * scale)

    def test_tuple_outputs(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [2], "float32")
            f = static.data("f", [1], "bool")
            a, b = snn.cond(f, lambda: (x + 1.0, x * 2.0),
                            lambda: (x - 1.0, x / 2.0))
        exe = _exe()
        xv = np.array([2.0, 4.0], np.float32)
        ra, rb = exe.run(m, feed={"x": xv, "f": np.array([True])},
                         fetch_list=[a, b])
        np.testing.assert_allclose(ra, xv + 1)
        np.testing.assert_allclose(rb, xv * 2)

    def test_mismatched_branches_raise(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [4], "float32")
            f = static.data("f", [1], "bool")
            with pytest.raises(ValueError):
                snn.cond(f, lambda: x, lambda: (x, x))

    def test_training_through_cond(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [4, 3], "float32")
            f = static.data("f", [1], "bool")
            h = snn.fc(x, 8, activation="relu")
            out = snn.cond(f, lambda: snn.fc(h, 2),
                           lambda: h[:, :2] * 0.0)
            loss = paddle.sum(out * out)
            opt = paddle.optimizer.SGD(learning_rate=0.05)
            opt.minimize(loss)
        exe = _exe()
        feed = {"x": rng.randn(4, 3).astype(np.float32),
                "f": np.array([True])}
        l0 = exe.run(m, feed=feed, fetch_list=[loss])[0]
        for _ in range(10):
            l1 = exe.run(m, feed=feed, fetch_list=[loss])[0]
        assert float(l1) < float(l0)

    def test_dygraph_fallback(self):
        paddle.disable_static()
        try:
            x = paddle.to_tensor([1.0, 2.0])
            out = snn.cond(paddle.to_tensor([True]),
                           lambda: x * 2, lambda: x)
            np.testing.assert_allclose(out.numpy(), [2.0, 4.0])
        finally:
            paddle.enable_static()


class TestWhileLoop:
    def test_counter(self):
        m = static.Program()
        with static.program_guard(m):
            i = static.data("i", [1], "int32")
            s = static.data("s", [1], "float32")
            i2, s2 = snn.while_loop(lambda i, s: i < 5,
                                    lambda i, s: [i + 1, s * 2.0], [i, s])
        exe = _exe()
        ri, rs = exe.run(m, feed={"i": np.array([0], np.int32),
                                  "s": np.array([1.0], np.float32)},
                         fetch_list=[i2, s2])
        assert int(ri[0]) == 5
        np.testing.assert_allclose(rs, [32.0])

    def test_matches_eager_twin(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [3], "float32")
            n = static.data("n", [1], "int32")
            i0 = static.data("i0", [1], "int32")
            _, out = snn.while_loop(
                lambda i, v: i < n,
                lambda i, v: [i + 1, v * 1.5 + 1.0], [i0, x])
        exe = _exe()
        xv = rng.randn(3).astype(np.float32)
        ref = xv.copy()
        for _ in range(4):
            ref = ref * 1.5 + 1.0
        got = exe.run(m, feed={"x": xv, "n": np.array([4], np.int32),
                               "i0": np.array([0], np.int32)},
                      fetch_list=[out])[1 - 1]
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_shape_change_raises(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("xs", [3], "float32")
            i = static.data("is", [1], "int32")
            with pytest.raises(ValueError):
                snn.while_loop(lambda i, v: i < 2,
                               lambda i, v: [i + 1, v[:2]], [i, x])


class TestCaseSwitch:
    def test_case_first_match_wins(self):
        m = static.Program()
        with static.program_guard(m):
            a = static.data("a", [1], "float32")
            x = static.data("x", [2], "float32")
            out = snn.case([(a > 2.0, lambda: x * 100.0),
                            (a > 1.0, lambda: x * 10.0)],
                           default=lambda: x)
        exe = _exe()
        xv = np.ones(2, np.float32)
        for av, scale in [(3.0, 100.0), (1.5, 10.0), (0.5, 1.0)]:
            r = exe.run(m, feed={"a": np.array([av], np.float32), "x": xv},
                        fetch_list=[out])[0]
            np.testing.assert_allclose(r, xv * scale)

    def test_switch_case(self):
        m = static.Program()
        with static.program_guard(m):
            idx = static.data("idx", [1], "int32")
            x = static.data("x", [3], "float32")
            out = snn.switch_case(idx, {0: lambda: x * 0.0,
                                        1: lambda: x + 1.0,
                                        2: lambda: x * 10.0})
        exe = _exe()
        xv = np.ones(3, np.float32)
        for k, want in [(0, xv * 0), (1, xv + 1), (2, xv * 10),
                        (7, xv * 10)]:     # out-of-range -> default (last)
            r = exe.run(m, feed={"idx": np.array([k], np.int32), "x": xv},
                        fetch_list=[out])[0]
            np.testing.assert_allclose(r, want)


class TestStaticPyLayer:
    def test_forward_and_custom_backward(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [2], "float32")
            x.stop_gradient = False
            out = snn.static_pylayer(lambda v: v * v, [x],
                                     backward_fn=lambda dy: dy * 7.0)
            (g,) = static.gradients([out], [x])
        exe = _exe()
        ro, rg = exe.run(m, feed={"x": np.array([2.0, 3.0], np.float32)},
                         fetch_list=[out, g])
        np.testing.assert_allclose(ro, [4.0, 9.0])
        np.testing.assert_allclose(rg, [7.0, 7.0])   # custom, not 2x

    def test_forward_only(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [3], "float32")
            out = snn.static_pylayer(lambda v: v + 5.0, [x])
        exe = _exe()
        r = exe.run(m, feed={"x": np.zeros(3, np.float32)},
                    fetch_list=[out])[0]
        np.testing.assert_allclose(r, np.full(3, 5.0))


class TestStaticNnLayers:
    def _run(self, build, feeds):
        m = static.Program()
        with static.program_guard(m):
            vars_, out = build()
        exe = _exe()
        return exe.run(m, feed=feeds, fetch_list=[out])[0]

    def test_layer_norm(self):
        xv = rng.randn(4, 6).astype(np.float32)

        def build():
            x = static.data("x", [4, 6], "float32")
            return [x], snn.layer_norm(x, begin_norm_axis=1)

        r = self._run(build, {"x": xv})
        ref = (xv - xv.mean(1, keepdims=True)) / np.sqrt(
            xv.var(1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(r, ref, rtol=1e-4, atol=1e-4)

    def test_group_instance_norm_shapes(self):
        xv = rng.randn(2, 8, 5, 5).astype(np.float32)

        def build_g():
            x = static.data("x", [2, 8, 5, 5], "float32")
            return [x], snn.group_norm(x, groups=4)

        def build_i():
            x = static.data("x", [2, 8, 5, 5], "float32")
            return [x], snn.instance_norm(x)

        assert self._run(build_g, {"x": xv}).shape == xv.shape
        assert self._run(build_i, {"x": xv}).shape == xv.shape

    def test_conv2d_transpose_shape(self):
        xv = rng.randn(1, 3, 8, 8).astype(np.float32)

        def build():
            x = static.data("x", [1, 3, 8, 8], "float32")
            return [x], snn.conv2d_transpose(x, 6, filter_size=2, stride=2)

        assert self._run(build, {"x": xv}).shape == (1, 6, 16, 16)

    def test_sequence_family(self):
        xv = rng.randn(2, 5, 3).astype(np.float32)

        def build(fn):
            def b():
                x = static.data("x", [2, 5, 3], "float32")
                return [x], fn(x)
            return b

        np.testing.assert_allclose(
            self._run(build(lambda x: snn.sequence_pool(x, "sum")),
                      {"x": xv}), xv.sum(1), rtol=1e-5)
        np.testing.assert_allclose(
            self._run(build(snn.sequence_first_step), {"x": xv}), xv[:, 0],
            rtol=1e-6)
        np.testing.assert_allclose(
            self._run(build(snn.sequence_last_step), {"x": xv}), xv[:, -1],
            rtol=1e-6)
        sm = self._run(build(snn.sequence_softmax), {"x": xv})
        np.testing.assert_allclose(sm.sum(1), np.ones((2, 3)), rtol=1e-5)
        out = self._run(build(
            lambda x: snn.sequence_conv(x, 4, filter_size=3)), {"x": xv})
        assert out.shape == (2, 5, 4)

    def test_spectral_norm_value(self):
        wv = (5 * rng.randn(6, 4)).astype(np.float32)

        def build():
            w = static.data("w", [6, 4], "float32")
            return [w], snn.spectral_norm(w, power_iters=30)

        r = self._run(build, {"w": wv})
        s = np.linalg.svd(r, compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=0.05)

    def test_bilinear_row_prelu_nce_shapes(self):
        m = static.Program()
        with static.program_guard(m):
            x = static.data("x", [3, 4], "float32")
            y = static.data("y", [3, 5], "float32")
            out = snn.bilinear_tensor_product(x, y, 6)
            seq = static.data("seq", [2, 7, 4], "float32")
            rc = snn.row_conv(seq, 2)
            pr = snn.prelu(x, mode="all")
            lab = static.data("lab", [3, 1], "int64")
            loss = snn.nce(x, lab, num_total_classes=11, num_neg_samples=3)
            dn = snn.data_norm(x)
        exe = _exe()
        feeds = {"x": rng.randn(3, 4).astype(np.float32),
                 "y": rng.randn(3, 5).astype(np.float32),
                 "seq": rng.randn(2, 7, 4).astype(np.float32),
                 "lab": rng.randint(0, 11, (3, 1)).astype(np.int64)}
        ro, rr, rp, rl, rd = exe.run(m, feed=feeds,
                                     fetch_list=[out, rc, pr, loss, dn])
        assert ro.shape == (3, 6)
        assert rr.shape == (2, 7, 4)
        assert rp.shape == (3, 4)
        assert rl.shape == (3, 1)
        np.testing.assert_allclose(rd.mean(0), 0.0, atol=1e-5)

    def test_namespace_complete(self):
        import ast
        import os
        path = "/root/reference/python/paddle/static/nn/__init__.py"
        if not os.path.exists(path):
            pytest.skip("no reference")
        ref = []
        for node in ast.walk(ast.parse(open(path).read())):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        ref = ast.literal_eval(node.value)
        missing = sorted(set(ref) - set(dir(snn)))
        assert not missing, missing
