"""Serving fused-op surface parity tests.

Reference: python/paddle/incubate/nn/functional/
(block_multihead_attention.py:34, masked_multihead_attention.py,
fused_moe.py, swiglu.py, fused_matmul_bias.py, blha_get_max_len.py,
variable_length_memory_efficient_attention.py, fused_transformer.py:976).
Each op is checked against a composed-op NumPy reference implementing
the documented semantics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate.nn.functional as F

rng = np.random.RandomState(3)


def t(a):
    return paddle.to_tensor(np.asarray(a))


def _softmax(x, axis=-1):
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def _silu(x):
    return x / (1.0 + np.exp(-x))


class TestSimpleOps:
    def test_swiglu_two_arg(self):
        x = rng.randn(4, 8).astype(np.float32)
        y = rng.randn(4, 8).astype(np.float32)
        out = F.swiglu(t(x), t(y)).numpy()
        np.testing.assert_allclose(out, _silu(x) * y, rtol=1e-5)

    def test_swiglu_split(self):
        x = rng.randn(4, 8).astype(np.float32)
        out = F.swiglu(t(x)).numpy()
        np.testing.assert_allclose(out, _silu(x[:, :4]) * x[:, 4:],
                                   rtol=1e-5)

    def test_fused_matmul_bias(self):
        x = rng.randn(3, 4).astype(np.float32)
        y = rng.randn(4, 5).astype(np.float32)
        b = rng.randn(5).astype(np.float32)
        out = F.fused_matmul_bias(t(x), t(y), t(b)).numpy()
        np.testing.assert_allclose(out, x @ y + b, rtol=1e-5)
        out2 = F.fused_matmul_bias(t(x.T), t(y), t(b),
                                   transpose_x=True).numpy()
        np.testing.assert_allclose(out2, x @ y + b, rtol=1e-5)

    def test_blha_get_max_len(self):
        enc = np.array([[3], [0], [7]], np.int32)
        dec = np.array([[0], [5], [2]], np.int32)
        me, md = F.blha_get_max_len(t(enc), t(dec), t(np.zeros((3,))))
        assert int(me.numpy()[0]) == 7
        assert int(md.numpy()[0]) == 5

    def test_fused_bias_dropout_residual_layer_norm(self):
        x = rng.randn(2, 6).astype(np.float32)
        res = rng.randn(2, 6).astype(np.float32)
        w = np.ones(6, np.float32)
        b = np.zeros(6, np.float32)
        out = F.fused_bias_dropout_residual_layer_norm(
            t(x), t(res), ln_scale=t(w), ln_bias=t(b), dropout_rate=0.0,
            training=False).numpy()
        h = x + res
        ref = (h - h.mean(-1, keepdims=True)) / np.sqrt(
            h.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


class TestVariableLengthAttention:
    def test_matches_naive(self):
        b, nh, s, d = 2, 4, 8, 16
        q = rng.randn(b, nh, s, d).astype(np.float32)
        k = rng.randn(b, nh, s, d).astype(np.float32)
        v = rng.randn(b, nh, s, d).astype(np.float32)
        ql = np.array([5, 8], np.int32)
        kl = np.array([5, 8], np.int32)
        out = F.variable_length_memory_efficient_attention(
            t(q), t(k), t(v), t(ql), t(kl)).numpy()
        for bi in range(b):
            L, Lk = ql[bi], kl[bi]
            logits = np.einsum("hqd,hkd->hqk", q[bi, :, :L],
                               k[bi, :, :Lk]) / np.sqrt(d)
            ref = np.einsum("hqk,hkd->hqd", _softmax(logits),
                            v[bi, :, :Lk])
            np.testing.assert_allclose(out[bi, :, :L], ref, rtol=1e-4,
                                       atol=1e-4)

    def test_causal_gqa(self):
        b, nh, kvh, s, d = 1, 4, 2, 6, 8
        q = rng.randn(b, nh, s, d).astype(np.float32)
        k = rng.randn(b, kvh, s, d).astype(np.float32)
        v = rng.randn(b, kvh, s, d).astype(np.float32)
        lens = np.array([s], np.int32)
        out = F.variable_length_memory_efficient_attention(
            t(q), t(k), t(v), t(lens), t(lens), causal=True).numpy()
        kk = np.repeat(k, 2, axis=1)
        vv = np.repeat(v, 2, axis=1)
        logits = np.einsum("bhqd,bhkd->bhqk", q, kk) / np.sqrt(d)
        cmask = np.tril(np.ones((s, s), bool))
        logits = np.where(cmask, logits, -np.inf)
        ref = np.einsum("bhqk,bhkd->bhqd", _softmax(logits), vv)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


class TestMaskedMultiheadAttention:
    def _naive(self, x, cache, lens, nh, kvh, hd):
        b = x.shape[0]
        q = x[:, :nh * hd].reshape(b, nh, hd)
        k = x[:, nh * hd:(nh + kvh) * hd].reshape(b, kvh, hd)
        v = x[:, (nh + kvh) * hd:].reshape(b, kvh, hd)
        kc, vc = cache[0].copy(), cache[1].copy()
        outs = []
        for bi in range(b):
            p = lens[bi]
            kc[bi, :, p] = k[bi]
            vc[bi, :, p] = v[bi]
            rep = nh // kvh
            kk = np.repeat(kc[bi, :, :p + 1], rep, axis=0)
            vv = np.repeat(vc[bi, :, :p + 1], rep, axis=0)
            logits = np.einsum("hd,htd->ht", q[bi], kk) / np.sqrt(hd)
            outs.append(np.einsum("ht,htd->hd", _softmax(logits), vv))
        return np.stack(outs).reshape(b, nh * hd), kc, vc

    def test_decode_step_parity(self):
        b, nh, kvh, tmax, hd = 3, 4, 2, 16, 8
        x = rng.randn(b, (nh + 2 * kvh) * hd).astype(np.float32)
        cache = rng.randn(2, b, kvh, tmax, hd).astype(np.float32)
        lens = np.array([5, 0, 11], np.int32)
        out, new_cache = F.masked_multihead_attention(
            t(x), cache_kv=t(cache), sequence_lengths=t(lens.reshape(-1, 1)))
        ref_out, ref_kc, ref_vc = self._naive(x, cache, lens, nh, kvh, hd)
        np.testing.assert_allclose(out.numpy(), ref_out, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(new_cache.numpy()[0], ref_kc, rtol=1e-5)
        np.testing.assert_allclose(new_cache.numpy()[1], ref_vc, rtol=1e-5)

    def test_quant_knobs_raise(self):
        with pytest.raises(NotImplementedError):
            F.masked_multihead_attention(
                t(np.zeros((1, 12), np.float32)),
                cache_kv=t(np.zeros((2, 1, 1, 4, 4), np.float32)),
                qkv_out_scale=t(np.ones(4, np.float32)))


class TestBlockMultiheadAttention:
    def _setup(self, lens_enc, lens_dec, lens_now, nh=4, kvh=2, hd=8,
               block_size=4, max_seq=16):
        b = len(lens_now)
        tok = int(sum(lens_now))
        pages_per_seq = max_seq // block_size
        nblocks = b * pages_per_seq + 1
        tables = np.arange(b * pages_per_seq, dtype=np.int32) \
            .reshape(b, pages_per_seq)
        kc = np.zeros((nblocks, kvh, block_size, hd), np.float32)
        vc = np.zeros((nblocks, kvh, block_size, hd), np.float32)
        # pre-fill cache for decode sequences
        for bi in range(b):
            for p in range(lens_dec[bi]):
                blk = tables[bi, p // block_size]
                kc[blk, :, p % block_size] = rng.randn(kvh, hd)
                vc[blk, :, p % block_size] = rng.randn(kvh, hd)
        qkv = rng.randn(tok, (nh + 2 * kvh) * hd).astype(np.float32)
        # padding offsets: padded_idx = i + pad_off[i]
        pad_off = np.zeros(tok, np.int32)
        cum = 0
        for bi in range(b):
            for p in range(lens_now[bi]):
                i = cum + p
                pad_off[i] = bi * max_seq + p - i
            cum += lens_now[bi]
        cu_q = np.cumsum([0] + list(lens_now)).astype(np.int32)
        return (b, tok, qkv, kc, vc, tables, pad_off, cu_q, nh, kvh, hd,
                block_size, max_seq)

    def _naive(self, qkv, kc, vc, tables, lens_dec, lens_now, nh, kvh, hd,
               bs):
        tok = qkv.shape[0]
        b = len(lens_now)
        q = qkv[:, :nh * hd].reshape(tok, nh, hd)
        k = qkv[:, nh * hd:(nh + kvh) * hd].reshape(tok, kvh, hd)
        v = qkv[:, (nh + kvh) * hd:].reshape(tok, kvh, hd)
        kc, vc = kc.copy(), vc.copy()
        out = np.zeros((tok, nh, hd), np.float32)
        i = 0
        for bi in range(b):
            for p in range(lens_now[bi]):
                cpos = lens_dec[bi] + p
                blk = tables[bi, cpos // bs]
                kc[blk, :, cpos % bs] = k[i]
                vc[blk, :, cpos % bs] = v[i]
                # gather prefix 0..cpos
                kk = np.zeros((kvh, cpos + 1, hd), np.float32)
                vv = np.zeros((kvh, cpos + 1, hd), np.float32)
                for s in range(cpos + 1):
                    bblk = tables[bi, s // bs]
                    kk[:, s] = kc[bblk, :, s % bs]
                    vv[:, s] = vc[bblk, :, s % bs]
                rep = nh // kvh
                kk = np.repeat(kk, rep, axis=0)
                vv = np.repeat(vv, rep, axis=0)
                logits = np.einsum("hd,htd->ht", q[i], kk) / np.sqrt(hd)
                out[i] = np.einsum("ht,htd->hd", _softmax(logits), vv)
                i += 1
        return out.reshape(tok, nh * hd), kc, vc

    def _run(self, lens_enc, lens_dec, lens_now):
        (b, tok, qkv, kc, vc, tables, pad_off, cu_q, nh, kvh, hd, bs,
         max_seq) = self._setup(lens_enc, lens_dec, lens_now)
        out, _, kc2, vc2 = F.block_multihead_attention(
            t(qkv), t(kc), t(vc),
            t(np.array(lens_enc, np.int32).reshape(-1, 1)),
            t(np.array(lens_dec, np.int32).reshape(-1, 1)),
            t(np.array(lens_now, np.int32).reshape(-1, 1)),
            t(pad_off), t(np.zeros(b, np.int32)), t(cu_q), t(cu_q),
            t(tables), max_seq_len=max_seq, block_size=bs)
        ref_out, ref_kc, ref_vc = self._naive(
            qkv, kc, vc, tables, lens_dec, lens_now, nh, kvh, hd, bs)
        np.testing.assert_allclose(out.numpy(), ref_out, rtol=1e-3,
                                   atol=1e-3)
        np.testing.assert_allclose(kc2.numpy()[:-1], ref_kc[:-1],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(vc2.numpy()[:-1], ref_vc[:-1],
                                   rtol=1e-5, atol=1e-6)

    def test_prefill(self):
        self._run([5, 7], [0, 0], [5, 7])

    def test_decode(self):
        self._run([0, 0, 0], [3, 9, 1], [1, 1, 1])

    def test_mixed_prefill_decode(self):
        self._run([4, 0], [0, 6], [4, 1])


class TestFusedMoe:
    def test_parity_with_dense_reference(self):
        b, s, d, e, f = 2, 6, 16, 4, 32
        x = rng.randn(b, s, d).astype(np.float32)
        gates = rng.randn(b, s, e).astype(np.float32)
        w1 = (rng.randn(e, d, 2 * f) / np.sqrt(d)).astype(np.float32)
        w2 = (rng.randn(e, f, d) / np.sqrt(f)).astype(np.float32)
        b1 = rng.randn(e, 1, 2 * f).astype(np.float32)
        b2 = rng.randn(e, 1, d).astype(np.float32)
        out = F.fused_moe(t(x), t(gates), t(w1), t(w2), t(b1), None,
                          t(b2), None, "None", 2, True).numpy()

        probs = _softmax(gates.reshape(-1, e))
        order = np.argsort(-probs, axis=-1)[:, :2]
        xt = x.reshape(-1, d)
        ref = np.zeros_like(xt)
        for i in range(xt.shape[0]):
            pv = probs[i, order[i]]
            pv = pv / pv.sum()
            for j, ei in enumerate(order[i]):
                h = xt[i] @ w1[ei] + b1[ei, 0]
                u, g = h[:f], h[f:]
                h = _silu(u) * g
                ref[i] += pv[j] * (h @ w2[ei] + b2[ei, 0])
        np.testing.assert_allclose(out.reshape(-1, d), ref, rtol=2e-3,
                                   atol=2e-3)

    def test_quant_method_raises(self):
        with pytest.raises(NotImplementedError):
            F.fused_moe(t(np.zeros((1, 2, 4), np.float32)),
                        t(np.zeros((1, 2, 2), np.float32)),
                        t(np.zeros((2, 4, 8), np.float32)),
                        t(np.zeros((2, 4, 4), np.float32)),
                        quant_method="weight_only_int8")


class TestFusedMultiTransformer:
    def _weights(self, n_layers, d, nh, hd, ffn):
        ws = {}
        ws["ln_s"] = [np.ones(d, np.float32) for _ in range(n_layers)]
        ws["ln_b"] = [np.zeros(d, np.float32) for _ in range(n_layers)]
        ws["qkv_w"] = [(rng.randn(3, nh, hd, d) / np.sqrt(d))
                       .astype(np.float32) for _ in range(n_layers)]
        ws["qkv_b"] = [np.zeros(3 * nh * hd, np.float32)
                       for _ in range(n_layers)]
        ws["out_w"] = [(rng.randn(nh * hd, d) / np.sqrt(d))
                       .astype(np.float32) for _ in range(n_layers)]
        ws["out_b"] = [np.zeros(d, np.float32) for _ in range(n_layers)]
        ws["fln_s"] = [np.ones(d, np.float32) for _ in range(n_layers)]
        ws["fln_b"] = [np.zeros(d, np.float32) for _ in range(n_layers)]
        ws["f1_w"] = [(rng.randn(d, ffn) / np.sqrt(d)).astype(np.float32)
                      for _ in range(n_layers)]
        ws["f1_b"] = [np.zeros(ffn, np.float32) for _ in range(n_layers)]
        ws["f2_w"] = [(rng.randn(ffn, d) / np.sqrt(ffn))
                      .astype(np.float32) for _ in range(n_layers)]
        ws["f2_b"] = [np.zeros(d, np.float32) for _ in range(n_layers)]
        return ws

    def _naive(self, x, ws, n_layers, nh, hd):
        def ln(h):
            mu = h.mean(-1, keepdims=True)
            var = h.var(-1, keepdims=True)
            return (h - mu) / np.sqrt(var + 1e-5)

        def gelu(v):
            from scipy.special import erf
            return v * 0.5 * (1 + erf(v / np.sqrt(2)))

        b, s, d = x.shape
        h = x.copy()
        for i in range(n_layers):
            resid = h
            hn = ln(h)
            w2d = ws["qkv_w"][i].reshape(-1, d)
            qkv = hn @ w2d.T + ws["qkv_b"][i]
            q = qkv[..., :nh * hd].reshape(b, s, nh, hd)
            k = qkv[..., nh * hd:2 * nh * hd].reshape(b, s, nh, hd)
            v = qkv[..., 2 * nh * hd:].reshape(b, s, nh, hd)
            logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
            cmask = np.tril(np.ones((s, s), bool))
            logits = np.where(cmask, logits, -np.inf)
            attn = np.einsum("bhqk,bkhd->bqhd", _softmax(logits), v) \
                .reshape(b, s, nh * hd)
            h = resid + attn @ ws["out_w"][i] + ws["out_b"][i]
            resid = h
            hn = ln(h)
            f = gelu(hn @ ws["f1_w"][i] + ws["f1_b"][i])
            h = resid + f @ ws["f2_w"][i] + ws["f2_b"][i]
        return h

    def test_prefill_parity(self):
        n_layers, d, nh, hd, ffn = 2, 16, 2, 8, 32
        b, s = 2, 5
        ws = self._weights(n_layers, d, nh, hd, ffn)
        x = rng.randn(b, s, d).astype(np.float32)
        out = F.fused_multi_transformer(
            t(x), [t(w) for w in ws["ln_s"]], [t(w) for w in ws["ln_b"]],
            [t(w) for w in ws["qkv_w"]], [t(w) for w in ws["qkv_b"]],
            [t(w) for w in ws["out_w"]], [t(w) for w in ws["out_b"]],
            [t(w) for w in ws["fln_s"]], [t(w) for w in ws["fln_b"]],
            [t(w) for w in ws["f1_w"]], [t(w) for w in ws["f1_b"]],
            [t(w) for w in ws["f2_w"]], [t(w) for w in ws["f2_b"]])
        ref = self._naive(x, ws, n_layers, nh, hd)
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-3, atol=2e-3)

    def test_decode_consistent_with_prefill(self):
        """Prefill s tokens in one call; then decode token s given the
        caches — must equal prefilling s+1 tokens directly."""
        n_layers, d, nh, hd, ffn = 2, 16, 2, 8, 32
        b, s, tmax = 1, 4, 8
        ws = self._weights(n_layers, d, nh, hd, ffn)
        x = rng.randn(b, s + 1, d).astype(np.float32)

        def args(xx, caches=None, **kw):
            return F.fused_multi_transformer(
                t(xx), [t(w) for w in ws["ln_s"]],
                [t(w) for w in ws["ln_b"]],
                [t(w) for w in ws["qkv_w"]], [t(w) for w in ws["qkv_b"]],
                [t(w) for w in ws["out_w"]], [t(w) for w in ws["out_b"]],
                [t(w) for w in ws["fln_s"]], [t(w) for w in ws["fln_b"]],
                [t(w) for w in ws["f1_w"]], [t(w) for w in ws["f1_b"]],
                [t(w) for w in ws["f2_w"]], [t(w) for w in ws["f2_b"]],
                cache_kvs=caches, **kw)

        caches = [t(np.zeros((2, b, nh, tmax, hd), np.float32))
                  for _ in range(n_layers)]
        out_pre, caches2 = args(x[:, :s], caches)
        out_dec, _ = args(
            x[:, s:s + 1], caches2,
            time_step=t(np.array(s, np.int32)),
            seq_lens=t(np.full((b,), s, np.int32)))
        out_full = args(x)
        np.testing.assert_allclose(
            np.asarray(out_dec.numpy())[:, 0],
            np.asarray(out_full.numpy())[:, s], rtol=2e-3, atol=2e-3)


def test_namespace_complete():
    import ast
    import os
    path = ("/root/reference/python/paddle/incubate/nn/functional/"
            "__init__.py")
    if not os.path.exists(path):
        pytest.skip("no reference")
    ref = []
    for node in ast.walk(ast.parse(open(path).read())):
        if isinstance(node, ast.Assign):
            for tg in node.targets:
                if getattr(tg, "id", None) == "__all__":
                    ref = ast.literal_eval(node.value)
    missing = sorted(set(ref) - set(dir(F)))
    assert not missing, missing
